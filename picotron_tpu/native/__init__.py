"""ctypes bindings for the native (C++) data-loader kernels.

Loads ``_build/libpicotron_data.so``, building it with g++ on first import if
missing (cached afterwards). Every binding has a numpy fallback in
``picotron_tpu.data`` producing bitwise-identical results, so the framework
runs unchanged where a toolchain is unavailable; set
``PICOTRON_DISABLE_NATIVE=1`` to force the fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "dataloader.cc")
_SO = os.path.join(_DIR, "_build", "libpicotron_data.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _build() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    # Compile to a per-pid temp name and atomically rename into place:
    # concurrent first importers (e.g. the sweep launcher starting several
    # trainers) must never dlopen a half-written .so.
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    i64 = ctypes.c_int64
    lib.affine_chain.argtypes = [i32p, u8p, i64p, i64, i64, i64, i64]
    lib.affine_chain.restype = None
    lib.gather_batch.argtypes = [i32p, i64, i64p, i64, i32p, i32p]
    lib.gather_batch.restype = None
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, or None when disabled/unbuildable."""
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("PICOTRON_DISABLE_NATIVE") == "1":
        return None
    if not os.path.exists(_SO) or (
            os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
        if not _build():
            return None
    try:
        _lib = _declare(ctypes.CDLL(_SO))
    except OSError:
        _lib = None
    return _lib


def available() -> bool:
    return get_lib() is not None


def affine_chain(toks: np.ndarray, jumps: np.ndarray, jump_vals: np.ndarray,
                 a: int, b: int, vocab: int) -> None:
    """In-place sequential chain fill; toks[0] must be pre-set."""
    lib = get_lib()
    assert lib is not None
    lib.affine_chain(toks, jumps, jump_vals, len(toks), a, b, vocab)


def gather_batch(samples: np.ndarray, indices: np.ndarray
                 ) -> tuple[np.ndarray, np.ndarray]:
    """samples [n, chunk] int32, indices [rows] int64 ->
    (input_ids, target_ids) each [rows, chunk-1] int32."""
    lib = get_lib()
    assert lib is not None
    n_rows, chunk = len(indices), samples.shape[1]
    input_ids = np.empty((n_rows, chunk - 1), np.int32)
    target_ids = np.empty((n_rows, chunk - 1), np.int32)
    lib.gather_batch(samples, chunk, indices, n_rows, input_ids, target_ids)
    return input_ids, target_ids
