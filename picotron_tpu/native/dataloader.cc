// Native data-loader kernels for picotron_tpu.
//
// The reference framework's performance-critical pieces are all native code
// (SURVEY.md §2.2): CUDA flash-attn, Triton RMSNorm, NCCL, and — on the data
// side — HF's Rust tokenizers. The TPU rebuild keeps that split: device math
// lives in Pallas/XLA, and the host-side data hot loops live here, compiled
// with g++ and bound via ctypes (picotron_tpu/native/__init__.py). Each entry
// point has a bitwise-identical numpy fallback in picotron_tpu/data.py; tests
// (tests/test_native.py) assert exact equality between the two paths.
//
// Build: `make native` at the repo root, or automatically at first import.

#include <cstdint>
#include <cstring>

extern "C" {

// Sequential affine bigram chain: toks[i] = jumps[i] ? jump_vals[i]
//                                          : (a * toks[i-1] + b) % vocab.
// The random draws (jumps mask, jump values, a, b, toks[0]) are produced by
// numpy's PCG64 on the Python side so native and fallback paths are bitwise
// identical; only the loop-carried recurrence — the part Python can't
// vectorize — runs here.
void affine_chain(int32_t* toks, const uint8_t* jumps,
                  const int64_t* jump_vals, int64_t length,
                  int64_t a, int64_t b, int64_t vocab) {
  int64_t prev = toks[0];
  for (int64_t i = 1; i < length; ++i) {
    prev = jumps[i] ? jump_vals[i] : (a * prev + b) % vocab;
    toks[i] = static_cast<int32_t>(prev);
  }
}

// Assemble one global batch: for each output row r, copy the shifted
// input/target views of packed sample `indices[r]` (length `chunk`,
// yielding chunk-1 tokens each) into contiguous [n_rows, chunk-1] buffers.
// Replaces a reshape + fancy-index + two ascontiguousarray copies per step.
void gather_batch(const int32_t* samples, int64_t chunk,
                  const int64_t* indices, int64_t n_rows,
                  int32_t* input_ids, int32_t* target_ids) {
  const int64_t out_w = chunk - 1;
  for (int64_t r = 0; r < n_rows; ++r) {
    const int32_t* src = samples + indices[r] * chunk;
    std::memcpy(input_ids + r * out_w, src, out_w * sizeof(int32_t));
    std::memcpy(target_ids + r * out_w, src + 1, out_w * sizeof(int32_t));
  }
}

}  // extern "C"
