"""Thread-safe metrics registry: counters, gauges, log-bucketed histograms.

The single metrics plane for training and serving (docs/OBSERVABILITY.md).
Before this, every subsystem kept its own ad-hoc numbers — the batcher's
private percentile lists, serve.py's rejection maps, resilience counters
scattered through log lines — and nothing could be scraped. The registry
replaces all of them with three instrument types behind one snapshot:

- ``Counter``: monotonically increasing float (requests, retries, tokens).
- ``Gauge``: a settable level (queue depth, active slots, pool pages).
- ``Histogram``: fixed log-spaced buckets (Prometheus-cumulative on
  export) plus a bounded window of recent raw samples, so the SAME
  instrument serves ``/metrics`` (bucket counts) and ``/statz``
  (exact p50/p95/p99 over the retained window — the contract the
  batcher's old ``_queue_waits``/``_ttfts`` lists provided).

Labels: every instrument can carry label key/values
(``registry.counter("x_total", state="shed")``); children with one name
form a family that renders as ``x_total{state="shed"} 3`` in the
Prometheus text exposition. ``CounterDict`` wraps a one-label family in
plain-dict semantics so existing counter dicts (``batcher.counters``,
``serve.rejections``) keep their exact read/compare surface while every
write mirrors into the registry.

Locking discipline (picolint C001–C004 clean by construction): the
registry lock guards only the name table; each instrument has its own
leaf lock guarding only its numbers. No lock is ever held across user
code, I/O, or another instrument's lock — ``snapshot()`` copies the
table under the registry lock, releases it, then reads each instrument
under its own lock.
"""

from __future__ import annotations

import bisect
import threading
from collections import deque
from typing import Optional

import numpy as np

# Default histogram bounds: log-spaced (x2 per bucket) from 100 us to
# ~105 s — wide enough for queue waits, TTFTs, dispatch and step times
# without per-site tuning. 21 finite buckets + the implicit +Inf.
DEFAULT_BUCKETS = tuple(1e-4 * (2.0 ** i) for i in range(21))

# Raw samples a histogram retains for exact percentiles (oldest dropped
# past the cap — the same recent-window semantics the batcher's old
# sample lists had).
DEFAULT_SAMPLE_WINDOW = 4096


def percentiles_of(samples) -> Optional[dict]:
    """{p50, p95, p99, n} of a sample sequence (seconds), or None when
    empty — the ``/statz`` percentile payload shape."""
    if not len(samples):
        return None
    a = np.asarray(samples, np.float64)
    p50, p95, p99 = np.percentile(a, [50, 95, 99])
    return {"p50": float(p50), "p95": float(p95), "p99": float(p99),
            "n": int(a.size)}


class Counter:
    """Monotonic counter. ``inc`` only; negative deltas are clamped to 0
    so a buggy caller can never make a counter run backwards."""

    __slots__ = ("_mu", "_v")

    def __init__(self):
        self._mu = threading.Lock()
        self._v = 0.0

    def inc(self, delta: float = 1.0) -> None:
        if delta < 0:
            delta = 0.0
        with self._mu:
            self._v += delta

    @property
    def value(self) -> float:
        with self._mu:
            return self._v


class Gauge:
    """A settable level."""

    __slots__ = ("_mu", "_v")

    def __init__(self):
        self._mu = threading.Lock()
        self._v = 0.0

    def set(self, v: float) -> None:
        with self._mu:
            self._v = float(v)

    def inc(self, delta: float = 1.0) -> None:
        with self._mu:
            self._v += delta

    @property
    def value(self) -> float:
        with self._mu:
            return self._v


class Histogram:
    """Fixed log-spaced buckets + a bounded recent-sample window.

    ``bucket_counts[i]`` counts observations ``<= bounds[i]`` EXCLUSIVE
    of earlier buckets (per-bucket, not cumulative — the Prometheus
    renderer accumulates); observations above the last bound land in the
    implicit +Inf bucket. ``percentiles()`` is exact over the retained
    window (recent ``sample_window`` observations)."""

    __slots__ = ("_mu", "bounds", "_counts", "_inf", "_sum", "_count",
                 "_samples")

    def __init__(self, buckets=DEFAULT_BUCKETS,
                 sample_window: int = DEFAULT_SAMPLE_WINDOW):
        bounds = tuple(float(b) for b in buckets)
        if not bounds or any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
            raise ValueError("histogram buckets must be strictly increasing "
                             f"and non-empty, got {buckets!r}")
        self._mu = threading.Lock()
        self.bounds = bounds
        self._counts = [0] * len(bounds)
        self._inf = 0
        self._sum = 0.0
        self._count = 0
        self._samples: deque = deque(maxlen=max(1, int(sample_window)))

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)  # v <= bounds[i]
        with self._mu:
            if i < len(self._counts):
                self._counts[i] += 1
            else:
                self._inf += 1
            self._sum += v
            self._count += 1
            self._samples.append(v)

    def percentiles(self) -> Optional[dict]:
        with self._mu:
            window = list(self._samples)
        return percentiles_of(window)

    def read(self) -> dict:
        """One consistent view: per-bucket counts, sum, count."""
        with self._mu:
            return {"bounds": self.bounds, "counts": list(self._counts),
                    "inf": self._inf, "sum": self._sum,
                    "count": self._count}

    @property
    def count(self) -> int:
        with self._mu:
            return self._count

    @property
    def sum(self) -> float:
        with self._mu:
            return self._sum


class _NullInstrument:
    """No-op stand-in for every instrument type (``obs.enabled: false``):
    accepts the full write surface, reports empty."""

    __slots__ = ()

    def inc(self, delta: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def percentiles(self) -> Optional[dict]:
        return None

    def read(self) -> dict:
        return {"bounds": (), "counts": [], "inf": 0, "sum": 0.0,
                "count": 0}

    @property
    def value(self) -> float:
        return 0.0

    @property
    def count(self) -> int:
        return 0

    @property
    def sum(self) -> float:
        return 0.0


NULL_INSTRUMENT = _NullInstrument()

_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class MetricsRegistry:
    """Name table of instrument families. ``counter``/``gauge``/
    ``histogram`` are get-or-create (same name + labels returns the same
    instrument), so call sites never coordinate registration."""

    def __init__(self, sample_window: int = DEFAULT_SAMPLE_WINDOW):
        self._mu = threading.Lock()
        self._sample_window = int(sample_window)
        # name -> {"type": str, "help": str, "children": {label_key: obj}}
        self._families: dict = {}

    # ---- get-or-create -----------------------------------------------------

    def _get(self, kind: str, name: str, help_: str, labels: dict,
             **kw):
        key = _label_key(labels)
        with self._mu:
            fam = self._families.get(name)
            if fam is None:
                fam = {"type": kind, "help": help_, "children": {}}
                self._families[name] = fam
            if fam["type"] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{fam['type']}, not {kind}")
            if help_ and not fam["help"]:
                fam["help"] = help_
            child = fam["children"].get(key)
            if child is None:
                child = _TYPES[kind](**kw)
                fam["children"][key] = child
            return child

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get("counter", name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "", buckets=None,
                  sample_window: Optional[int] = None,
                  **labels) -> Histogram:
        return self._get(
            "histogram", name, help, labels,
            buckets=buckets if buckets is not None else DEFAULT_BUCKETS,
            sample_window=(sample_window if sample_window is not None
                           else self._sample_window))

    def counter_dict(self, name: str, keys, help: str = "",
                     label: str = "state") -> "CounterDict":
        return CounterDict(self, name, keys, help=help, label=label)

    # ---- read side ---------------------------------------------------------

    def _copy_table(self) -> list:
        """(name, type, help, [(labels, instrument)]) rows — taken under
        the registry lock, read without it."""
        with self._mu:
            return [(name, fam["type"], fam["help"],
                     sorted(fam["children"].items()))
                    for name, fam in sorted(self._families.items())]

    def snapshot(self) -> dict:
        """Full structured read: {name: {"type", "help", "values":
        {label_str: value | histogram-read}}}. No lock held across
        instrument reads."""
        out = {}
        for name, kind, help_, children in self._copy_table():
            values = {}
            for key, inst in children:
                lbl = ",".join(f'{k}="{v}"' for k, v in key)
                if kind == "histogram":
                    values[lbl] = inst.read()
                else:
                    values[lbl] = inst.value
            out[name] = {"type": kind, "help": help_, "values": values}
        return out

    def summary(self) -> dict:
        """Compact flat view for embedding in bench JSON: counters and
        gauges as numbers, histograms as {count, sum, p50, p95, p99}.
        Keys are ``name`` or ``name{label="v"}``."""
        out = {}
        for name, kind, _help, children in self._copy_table():
            for key, inst in children:
                lbl = ",".join(f'{k}="{v}"' for k, v in key)
                full = f"{name}{{{lbl}}}" if lbl else name
                if kind == "histogram":
                    pct = inst.percentiles() or {}
                    out[full] = {
                        "count": inst.count,
                        "sum": round(inst.sum, 6),
                        **{p: round(pct[p], 6)
                           for p in ("p50", "p95", "p99") if p in pct}}
                else:
                    v = inst.value
                    out[full] = int(v) if float(v).is_integer() else v
        return out

    def prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every family."""
        lines = []
        for name, kind, help_, children in self._copy_table():
            if help_:
                lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
            for key, inst in children:
                lbl = ",".join(f'{k}="{v}"' for k, v in key)
                if kind != "histogram":
                    lines.append(_sample_line(name, lbl, inst.value))
                    continue
                h = inst.read()
                cum = 0
                for bound, c in zip(h["bounds"], h["counts"]):
                    cum += c
                    le = _fmt_float(bound)
                    blbl = (f'{lbl},le="{le}"' if lbl else f'le="{le}"')
                    lines.append(_sample_line(f"{name}_bucket", blbl, cum))
                blbl = (f'{lbl},le="+Inf"' if lbl else 'le="+Inf"')
                lines.append(_sample_line(f"{name}_bucket", blbl,
                                          h["count"]))
                lines.append(_sample_line(f"{name}_sum", lbl, h["sum"]))
                lines.append(_sample_line(f"{name}_count", lbl,
                                          h["count"]))
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt_float(v: float) -> str:
    return repr(float(v))


def _sample_line(name: str, lbl: str, v) -> str:
    v = float(v)
    sval = str(int(v)) if v.is_integer() else repr(v)
    return (f"{name}{{{lbl}}} {sval}" if lbl else f"{name} {sval}")


def parse_prometheus(text: str) -> dict:
    """Inverse of ``prometheus()`` for tests and the smoke drive:
    {sample-name-with-labels: float}. Comment lines are skipped; the last
    occurrence of a duplicated sample wins (as a scraper would see)."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        try:
            out[key] = float(val)
        except ValueError:
            continue
    return out


class NullRegistry(MetricsRegistry):
    """Registry for ``obs.enabled: false``: hands out shared no-op
    instruments, snapshots empty. CounterDicts built on it degrade to
    plain dicts (their authoritative local values still work)."""

    def __init__(self):
        super().__init__()

    def _get(self, kind, name, help_, labels, **kw):
        return NULL_INSTRUMENT

    def snapshot(self) -> dict:
        return {}

    def summary(self) -> dict:
        return {}

    def prometheus(self) -> str:
        return ""


class CounterDict(dict):
    """A plain dict whose writes mirror into a one-label counter family.

    Existing code keeps its exact surface — ``d[k] += 1``, ``dict(d)``,
    ``d == {...}`` — while every increment lands in the registry as
    ``name{label=k}``. The dict itself stays the authoritative read side
    (tests and ``/statz`` compare against it); the registry child only
    ever receives the positive deltas, so the two can never disagree for
    monotonic counters. NOT internally locked: callers serialize writes
    exactly as they did for the plain dict this replaces."""

    def __init__(self, registry: MetricsRegistry, name: str, keys,
                 help: str = "", label: str = "state"):
        super().__init__({k: 0 for k in keys})
        self._registry = registry
        self._name = name
        self._help = help
        self._label = label
        self._children = {
            k: registry.counter(name, help, **{label: k}) for k in keys}

    def __setitem__(self, k, v) -> None:
        old = self.get(k, 0)
        dict.__setitem__(self, k, v)
        child = self._children.get(k)
        if child is None:
            child = self._registry.counter(
                self._name, self._help, **{self._label: k})
            self._children[k] = child
        child.inc(v - old)
