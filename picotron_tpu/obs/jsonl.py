"""Per-step training metrics as JSONL — the structured replacement for
regex-scraping the per-step log line.

``picotron_tpu.train`` appends one JSON object per optimizer step
(controller process only) to the path named by ``$PICOTRON_METRICS_JSONL``
(the supervisor/scheduler export — lands next to the run log) or
``obs.metrics_jsonl``; ``tools/extract_metrics.py`` prefers this file over
the legacy log regex. Rows carry the exact fields the regex used to
recover — ``step``, ``loss``, ``tokens_per_sec``, ``tokens_per_sec_per_chip``,
``trained_tokens``, ``mfu_pct``, ``memory_gb`` (the last two null except on
log-frequency steps, where they are actually computed) — plus a wall
timestamp. A final ``{"event": "summary", "metrics": ...}`` row embeds the
run's registry snapshot; row consumers key on ``"step"`` and skip it.

Writes are line-buffered and flushed per row so a preempted/killed run
keeps every completed step; a write error disables the writer with one
warning instead of ever failing a training step.
"""

from __future__ import annotations

import json
import os
from typing import Optional


class MetricsJsonl:
    """Append-only JSONL metrics writer (never raises out of write())."""

    def __init__(self, path: str, log=None):
        self.path = path
        self._log = log
        self._f = None
        try:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._f = open(path, "a")
        except OSError as e:
            self._warn(f"metrics jsonl: cannot open {path!r} ({e}); "
                       f"per-step metrics disabled")

    def _warn(self, msg: str) -> None:
        if self._log is not None:
            self._log(msg)

    def write(self, row: dict) -> None:
        if self._f is None:
            return
        try:
            self._f.write(json.dumps(row) + "\n")
            self._f.flush()
        except (OSError, ValueError, TypeError) as e:
            self._warn(f"metrics jsonl: write failed ({e}); disabling")
            self.close()

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None


def resolve_path(ocfg) -> Optional[str]:
    """The effective JSONL path: the supervisor/scheduler's
    ``$PICOTRON_METRICS_JSONL`` export wins over the config field (same
    precedence as the heartbeat path); None when neither is set or obs
    is disabled."""
    if not ocfg.enabled:
        return None
    return (os.environ.get("PICOTRON_METRICS_JSONL", "")
            or ocfg.metrics_jsonl) or None
