"""Unified telemetry: metrics registry, span tracer, export surfaces.

One observability plane shared by training and serving
(docs/OBSERVABILITY.md). Three layers:

- ``obs.metrics`` — thread-safe counters/gauges/histograms behind a
  registry; Prometheus text exposition; ``CounterDict`` dict-semantics
  views for the pre-existing counter maps.
- ``obs.tracing`` — begin/end spans with parent links in a bounded ring,
  exported as Chrome-trace JSON (``tools/trace_dump.py``,
  ``GET /tracez``).
- ``obs.profiler`` — on-demand timed ``jax.profiler`` captures
  (SIGUSR2 / ``POST /profilez``).

Ownership model:

- each ``InferenceEngine`` (and each ``train()`` run) owns a FRESH
  registry via ``Obs.from_config(cfg.obs)`` — counters start at zero per
  server/run, so ``GET /metrics`` agrees with that server's ``/statz``
  even when several engines share a process (tests);
- ``GLOBAL_REGISTRY`` holds process-wide counters owned by no run in
  particular (resilience retries, emergency saves) — export surfaces
  render it alongside the local registry;
- ``GLOBAL_TRACER`` is the one process span ring (like the logging
  root): engine, batcher, serve, train, and ``comm_trace`` all record
  into it, so a trace dump interleaves every subsystem on one timeline.
  ``obs.enabled: false`` swaps in null instruments — every record call
  no-ops and the hot paths carry zero bookkeeping.
"""

from __future__ import annotations

from typing import Optional

from picotron_tpu.obs.metrics import (  # noqa: F401 - public surface
    Counter,
    CounterDict,
    Gauge,
    Histogram,
    MetricsRegistry,
    NullRegistry,
    percentiles_of,
)
from picotron_tpu.obs.jsonl import MetricsJsonl  # noqa: F401
from picotron_tpu.obs.profiler import ProfileCapture, install_sigusr2  # noqa: F401
from picotron_tpu.obs.tracing import NullTracer, Span, SpanTracer  # noqa: F401

# Process-wide surfaces (see module docstring).
GLOBAL_REGISTRY = MetricsRegistry()
GLOBAL_TRACER = SpanTracer()
_NULL_TRACER = NullTracer()


class Obs:
    """The bundle a subsystem carries: its registry + the shared tracer,
    with one ``enabled`` flag gating both."""

    def __init__(self, enabled: bool = True,
                 registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[SpanTracer] = None):
        self.enabled = bool(enabled)
        if not self.enabled:
            self.registry = registry or NullRegistry()
            self.tracer = tracer or _NULL_TRACER
        else:
            self.registry = registry or MetricsRegistry()
            self.tracer = tracer or GLOBAL_TRACER

    @classmethod
    def from_config(cls, ocfg) -> "Obs":
        """Build from a config ``obs`` section (config.ObsConfig)."""
        if not ocfg.enabled:
            return cls(enabled=False)
        GLOBAL_TRACER.resize(ocfg.span_ring)
        return cls(enabled=True,
                   registry=MetricsRegistry(
                       sample_window=ocfg.sample_window))


def null_obs() -> Obs:
    return Obs(enabled=False)


def global_counter(name: str, help: str = "", **labels) -> Counter:
    """A counter on the process-wide registry (resilience retries,
    emergency saves, ... — owned by no single run)."""
    return GLOBAL_REGISTRY.counter(name, help, **labels)
