"""Span tracer: begin/end spans with parent links in a bounded ring.

The per-request / per-step timeline complement of the metrics registry
(docs/OBSERVABILITY.md). Spans are cheap host-side records — name, wall
window, thread, parent id, small args dict — appended to a bounded ring
when they END (an unfinished span costs nothing but its object). The ring
is the export surface: ``chrome_trace()`` renders the retained spans as
Chrome-trace/Perfetto ``traceEvents`` JSON (``tools/trace_dump.py``
validates and queries it; ``GET /tracez`` on the serving front end dumps
it live), with each event's ``args`` carrying ``id``/``parent`` so a
request's whole chain — queue wait -> prefill -> every dispatch ->
delivery — reads as one parented tree.

Three record styles:

- ``with tracer.span("prefill", parent=root, prompt_tokens=n):`` — the
  common scoped form;
- ``begin()`` / ``end()`` — for windows that open and close in different
  call frames (a request's root span lives from submit to finish);
- ``record(name, t0, t1, parent=...)`` — retroactive: one engine dispatch
  serves many slots, so the batcher mirrors the dispatch window into one
  child span PER REQUEST after the fact, which is what makes every
  request's chain complete without multi-parent events.

``instant()`` records zero-duration marks (trace-time collective logs
from ``comm_trace``).

Thread-safety: one leaf lock guards the id counter and ring; nothing else
is shared. The clock is ``time.monotonic`` (one timebase across threads);
timestamps are exported in microseconds as Chrome expects.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

DEFAULT_RING = 4096


class Span:
    """One timed window. ``t1 is None`` until ended/recorded."""

    __slots__ = ("name", "span_id", "parent_id", "t0", "t1", "tid", "args")

    def __init__(self, name: str, span_id: int, parent_id: Optional[int],
                 t0: float, tid: int, args: dict):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = t0
        self.t1: Optional[float] = None
        self.tid = tid
        self.args = args

    @property
    def duration_s(self) -> Optional[float]:
        return None if self.t1 is None else self.t1 - self.t0


class _NullSpan(Span):
    __slots__ = ()

    def __init__(self):
        super().__init__("", 0, None, 0.0, 0, {})


NULL_SPAN = _NullSpan()


def _parent_id(parent) -> Optional[int]:
    if parent is None:
        return None
    pid = parent.span_id if isinstance(parent, Span) else int(parent)
    return pid or None  # the null span's id 0 means "no parent"


class SpanTracer:
    """Bounded ring of finished spans (oldest dropped past ``ring``)."""

    def __init__(self, ring: int = DEFAULT_RING, clock=time.monotonic):
        self._mu = threading.Lock()
        self._clock = clock
        self._next_id = 1
        self._ring: deque = deque(maxlen=max(1, int(ring)))

    @property
    def enabled(self) -> bool:
        return True

    def resize(self, ring: int) -> None:
        """Grow (never shrink) the ring — config-driven sizing of the
        shared process tracer without discarding retained spans."""
        ring = int(ring)
        with self._mu:
            if ring > (self._ring.maxlen or 0):
                self._ring = deque(self._ring, maxlen=ring)

    # ---- record surface ----------------------------------------------------

    def begin(self, name: str, parent=None, **args) -> Span:
        with self._mu:
            sid = self._next_id
            self._next_id += 1
        return Span(name, sid, _parent_id(parent), self._clock(),
                    threading.get_ident(), args)

    def end(self, span: Span, **args) -> Span:
        if span.span_id == 0:  # null span
            return span
        span.t1 = self._clock()
        if args:
            span.args = {**span.args, **args}
        with self._mu:
            self._ring.append(span)
        return span

    class _Scoped:
        __slots__ = ("_tracer", "_span")

        def __init__(self, tracer: "SpanTracer", span: Span):
            self._tracer = tracer
            self._span = span

        def __enter__(self) -> Span:
            return self._span

        def __exit__(self, exc_type, exc, tb) -> None:
            if exc_type is not None:
                self._span.args = {**self._span.args,
                                   "error": exc_type.__name__}
            self._tracer.end(self._span)

    def span(self, name: str, parent=None, **args) -> "_Scoped":
        return self._Scoped(self, self.begin(name, parent=parent, **args))

    def record(self, name: str, t0: float, t1: float, parent=None,
               **args) -> Span:
        """Retroactively record a finished window."""
        s = self.begin(name, parent=parent, **args)
        s.t0 = t0
        s.t1 = t1
        with self._mu:
            self._ring.append(s)
        return s

    def instant(self, name: str, **args) -> Span:
        t = self._clock()
        return self.record(name, t, t, **args)

    # ---- read side ---------------------------------------------------------

    def spans(self) -> list:
        with self._mu:
            return list(self._ring)

    def clear(self) -> None:
        with self._mu:
            self._ring.clear()

    def chrome_trace(self) -> dict:
        """Chrome-trace JSON ("traceEvents" array format): one complete
        ("X") event per span — instants (t0 == t1) render as "i" — with
        ``args.id``/``args.parent`` carrying the chain links."""
        pid = os.getpid()
        events = []
        for s in self.spans():
            args = {"id": s.span_id}
            if s.parent_id:
                args["parent"] = s.parent_id
            args.update(s.args)
            ev = {"name": s.name, "cat": "picotron", "pid": pid,
                  "tid": s.tid, "ts": round(s.t0 * 1e6, 3), "args": args}
            if s.t1 is not None and s.t1 > s.t0:
                ev["ph"] = "X"
                ev["dur"] = round((s.t1 - s.t0) * 1e6, 3)
            else:
                ev["ph"] = "i"
                ev["s"] = "p"
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump_chrome(self, path: str) -> None:
        import json

        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)


class NullTracer(SpanTracer):
    """``obs.enabled: false``: the whole record surface no-ops and hands
    back the shared null span (parenting off it is a no-op too)."""

    def __init__(self):
        super().__init__(ring=1)

    @property
    def enabled(self) -> bool:
        return False

    def begin(self, name, parent=None, **args) -> Span:
        return NULL_SPAN

    def end(self, span, **args) -> Span:
        return span

    def record(self, name, t0, t1, parent=None, **args) -> Span:
        return NULL_SPAN

    def spans(self) -> list:
        return []
