"""On-demand timed ``jax.profiler`` capture (SIGUSR2 / ``POST /profilez``).

The training loop's windowed profiler (``logging.profile_start/stop``)
answers "profile steps N..M of a run I am about to launch"; this module
answers the production question — "this process is slow RIGHT NOW, grab a
trace" — for a live server or trainer without restarting it:

- ``ProfileCapture.start()`` begins ``jax.profiler.start_trace(dir)`` and
  arms a daemon timer that stops it after ``seconds``;
- ``install_sigusr2(capture)`` makes ``kill -USR2 <pid>`` trigger exactly
  that (the serve CLI and the train CLI both install it);
- the serving front end exposes the same start as ``POST /profilez``.

One capture at a time: a start while one is running reports busy instead
of tripping jax's double-start error. The signal handler only flips an
event and spawns the worker — nothing slow runs on the signal path.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional


class ProfileCapture:
    """Timed profiler window manager. ``start()`` is safe from any
    thread (and from a signal handler via ``request()``)."""

    def __init__(self, out_dir: str, seconds: float = 5.0, log=None):
        self.out_dir = out_dir
        self.seconds = float(seconds)
        self._mu = threading.Lock()
        self._running = False
        self._count = 0
        self._log = log

    @property
    def running(self) -> bool:
        with self._mu:
            return self._running

    @property
    def captures(self) -> int:
        with self._mu:
            return self._count

    def _say(self, msg: str) -> None:
        if self._log is not None:
            self._log(msg)

    def start(self, out_dir: Optional[str] = None,
              seconds: Optional[float] = None) -> dict:
        """Begin one timed capture. Returns ``{"ok": True, "dir",
        "seconds"}`` or ``{"ok": False, "error"}`` when one is already
        running (or jax refuses to start a trace)."""
        d = out_dir or self.out_dir
        s = float(seconds if seconds is not None else self.seconds)
        if s <= 0:
            return {"ok": False, "error": f"seconds must be > 0, got {s}"}
        with self._mu:
            if self._running:
                return {"ok": False, "error": "capture already running"}
            self._running = True
        try:
            import jax

            os.makedirs(d, exist_ok=True)
            jax.profiler.start_trace(d)
        except Exception as e:  # noqa: BLE001 - reported, never fatal
            with self._mu:
                self._running = False
            return {"ok": False,
                    "error": f"profiler start failed: {e}"}
        t = threading.Thread(target=self._stop_after, args=(s,),
                             name="obs-profile-stop", daemon=True)
        t.start()
        self._say(f"profiler: capturing {s:.3g}s into {d}")
        return {"ok": True, "dir": d, "seconds": s}

    def _stop_after(self, seconds: float) -> None:
        time.sleep(seconds)
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 - reported, never fatal
            self._say(f"profiler: stop failed: {e}")
        finally:
            with self._mu:
                self._running = False
                self._count += 1
        self._say("profiler: capture done")

    def request(self) -> None:
        """Signal-handler-safe trigger: hand the start to a worker thread
        so the handler never touches jax or the filesystem."""
        threading.Thread(target=self.start, name="obs-profile-start",
                         daemon=True).start()


def install_sigusr2(capture: ProfileCapture) -> bool:
    """SIGUSR2 -> one timed capture. Returns False off the main thread
    (embedded runs: the signal surface is simply unavailable there)."""
    import signal

    try:
        signal.signal(signal.SIGUSR2,
                      lambda signum, frame: capture.request())
        return True
    except ValueError:
        return False
