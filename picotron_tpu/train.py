"""Training entry point: ``python -m picotron_tpu.train --config exp.json``.

The TPU single-controller collapse of the reference's ``train.py`` (:57-281).
What torchrun + rendezvous + per-rank env vars did there is one process here:
the config names a (dp, pp, cp, tp) topology, the mesh is built over the
visible devices, and one jitted shard_map program runs the whole 4D step.

Per-step log line carries the same fields the reference prints
(train.py:247-259): step, loss, global batch size, tokens/s, tokens/s/chip,
trained tokens, MFU, device memory — which is exactly what the
extract_metrics CLI scrapes (extract_metrics.py:55-68). wandb logging is
opt-in with the same run-name convention (train.py:132-150); a jax.profiler
trace window replaces the reference's absent profiler (SURVEY.md §5.1).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional


def _ensure_devices(cfg) -> None:
    """use_cpu runs (the reference's Gloo path, train.py:83) need the virtual
    CPU device count pinned before a backend exists."""
    if cfg.distributed.use_cpu:
        os.environ["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={cfg.world_size} "
            + os.environ.get("XLA_FLAGS", "")
        )
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax

        jax.config.update("jax_platforms", "cpu")


def _maybe_init_distributed() -> None:
    """Join a multi-host mesh when launched by the pod/slurm template
    (template/base_job.slurm exports these; the analogue of torchrun's
    RANK/WORLD_SIZE rendezvous, reference train.py:83-94). One JAX process
    per host; after initialize(), jax.devices() spans every host's chips."""
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not addr:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]),
    )


def _wandb_init(cfg):
    """Run name convention from the reference: {name}_{tokens-per-step}_
    {topology} (train.py:132-143)."""
    import wandb

    from picotron_tpu.utils import to_readable_format

    d = cfg.distributed
    run_name = (
        f"{cfg.logging.run_name}_{to_readable_format(cfg.tokens_per_step)}"
        f"_dp{d.dp_size}_tp{d.tp_size}_pp{d.pp_size}_cp{d.cp_size}"
    )
    wandb.init(name=run_name, config=cfg.to_dict())
    return wandb


def train(cfg, max_steps_override: Optional[int] = None):
    """Run the training loop; returns (final_step, trained_tokens, last_loss)."""
    import jax

    from picotron_tpu import checkpoint as ckpt_mod
    from picotron_tpu import train_step as ts
    from picotron_tpu import utils
    from picotron_tpu.data import MicroBatchDataLoader
    from picotron_tpu.models import llama
    from picotron_tpu.topology import topology_from_config

    t0_setup = time.perf_counter()
    topo = topology_from_config(cfg)
    m, t, c, lg = cfg.model, cfg.training, cfg.checkpoint, cfg.logging
    utils.set_all_seed(t.seed)

    loader = MicroBatchDataLoader(cfg)
    params, opt_state = ts.init_state(cfg, topo)
    if c.hf_bootstrap_path:
        # header-only names+shapes check — zero tensor bytes read; guards
        # BOTH modes against a template that disagrees with the model config
        ckpt_mod.validate_hf_template(c.hf_bootstrap_path, m)
        if c.hf_bootstrap_reinit:
            # reference semantics (checkpoint.py:99-100): the HF file is a
            # shape template only; training starts from the seed-derived
            # random init above
            utils.log0(f"hf_bootstrap_reinit: validated "
                       f"{c.hf_bootstrap_path} as a shape template; keeping "
                       f"random init (reference re-randomize semantics)")
        else:
            params = ckpt_mod.load_hf_safetensors(
                c.hf_bootstrap_path, m, topo,
                interleave=cfg.distributed.pp_interleave,
                fsdp=cfg.distributed.fsdp)
    spc = t.steps_per_call
    step_fn = ts.build_train_step(cfg, topo, multi_step=spc)
    step_fn_single = step_fn if spc == 1 else None  # lazily built for the tail

    manager = None
    if c.save_frequency > 0 or c.load_path:
        manager = ckpt_mod.CheckpointManager(c.load_path or c.save_dir)

    layout = (m.num_hidden_layers, cfg.distributed.pp_size,
              cfg.distributed.pp_interleave)
    z1 = (cfg.distributed.zero1, cfg.distributed.dp_size)
    step, trained_tokens = 0, 0
    if c.load_path:
        params, opt_state, step, trained_tokens = manager.load(
            params, opt_state, layout=layout, zero1=z1)
        loader.skip_steps(step)
        utils.log0(f"resumed from {c.load_path} at step {step} "
                   f"({utils.to_readable_format(trained_tokens)} tokens)")
        if c.load_path != c.save_dir and c.save_frequency > 0:
            manager.close()
            manager = ckpt_mod.CheckpointManager(c.save_dir)

    # wandb/log gating: only the controller process reports (reference
    # train.py:101, utils.py:12-20)
    wandb = _wandb_init(cfg) if (lg.use_wandb and utils.is_main_process()) else None
    n_params = llama.num_params(m)
    peak = utils.peak_flops_per_chip()
    n_chips = topo.world_size
    max_steps = max_steps_override or t.total_train_steps
    utils.log0(f"model {m.name}: {utils.to_readable_format(n_params)} params | "
          f"mesh dp={topo.dp_size} pp={topo.pp_size} cp={topo.cp_size} "
          f"tp={topo.tp_size} on {n_chips} x {jax.devices()[0].device_kind} | "
          f"global batch {cfg.global_batch_size} "
          f"({utils.to_readable_format(cfg.tokens_per_step)} tokens/step) | "
          f"setup {time.perf_counter() - t0_setup:.1f}s")

    loss = float("nan")
    last_saved_step = step
    profiling = profile_done = False
    while step < max_steps and (t.max_tokens is None or trained_tokens < t.max_tokens):
        # Profiler window snaps to dispatch boundaries (a dispatch is spc
        # steps): stop is checked before start so a window narrower than one
        # dispatch still traces one full dispatch; the done latch makes the
        # window fire exactly once.
        if profiling and lg.profile_stop and step >= lg.profile_stop:
            jax.profiler.stop_trace()
            profiling, profile_done = False, True
        if (lg.profile_start and not profiling and not profile_done
                and step >= lg.profile_start):
            jax.profiler.start_trace(lg.profile_dir)
            profiling = True
        t_start = time.perf_counter()
        step_before = step
        # spc optimizer steps per device dispatch; a tail shorter than spc
        # (by step count OR token budget) would trigger a recompile at a new
        # stack shape — run those steps singly instead.
        steps_left = max_steps - step
        if t.max_tokens is not None:
            tokens_left = t.max_tokens - trained_tokens
            steps_left = min(steps_left, -(-tokens_left // cfg.tokens_per_step))
        k = spc if steps_left >= spc else 1
        if k > 1:
            tokens, targets = ts.shard_batch_stack(
                [next(loader) for _ in range(k)], topo)
            params, opt_state, loss_arr = step_fn(params, opt_state, tokens, targets)
            losses = [float(x) for x in jax.block_until_ready(loss_arr)]
        else:
            tokens, targets = ts.shard_batch(next(loader), topo)
            if step_fn_single is None:
                step_fn_single = ts.build_train_step(cfg, topo)
            params, opt_state, loss_arr = step_fn_single(
                params, opt_state, tokens, targets)
            losses = [float(jax.block_until_ready(loss_arr))]
        dt_call = time.perf_counter() - t_start

        # Throughput is per dispatch (identical for every step in the group);
        # mfu/memory are computed lazily, once, and only if a step logs.
        tok_s = k * cfg.tokens_per_step / dt_call
        tok_s_chip = tok_s / n_chips
        stats = None
        for i, loss in enumerate(losses):
            step += 1
            trained_tokens += cfg.tokens_per_step
            if step % lg.log_frequency == 0 and stats is None:
                stats = (utils.get_mfu(tok_s_chip, n_params, m.num_hidden_layers,
                                       m.hidden_size, t.seq_length, peak),
                         utils.device_memory_gb())
            mfu, mem = stats if stats is not None else (None, None)
            if step % lg.log_frequency == 0:
                parts = [
                    f"Step: {step:<5d}",
                    f"Loss: {loss:6.4f}",
                    f"Global batch size: {utils.to_readable_format(cfg.tokens_per_step)}",
                    f"Tokens/s: {utils.to_readable_format(tok_s)}",
                    f"Tokens/s/chip: {utils.to_readable_format(tok_s_chip)}",
                    f"Tokens: {utils.to_readable_format(trained_tokens)}",
                ]
                if mfu is not None:
                    parts.append(f"MFU: {mfu:.2f}%")
                if mem is not None:
                    parts.append(f"Memory usage: {mem:.2f}GB")
                utils.log0(" | ".join(parts), flush=True)
            if wandb is not None and step % lg.log_frequency == 0:
                wandb.log({"loss": loss, "tokens_per_sec": tok_s,
                           "tokens_per_sec_per_chip": tok_s_chip,
                           "trained_tokens": trained_tokens,
                           **({"mfu": mfu} if mfu is not None else {}),
                           **({"memory_gb": mem} if mem is not None else {})},
                          step=step)

        # Save at group boundaries only: params here are the end-of-group
        # state, so the recorded step must be the end-of-group step.
        if (manager is not None and c.save_frequency > 0
                and step // c.save_frequency > step_before // c.save_frequency):
            manager.save(step, params, opt_state, trained_tokens, layout=layout,
                         zero1=z1)
            last_saved_step = step

    if profiling:
        jax.profiler.stop_trace()
    if manager is not None:
        if c.save_frequency > 0 and step != last_saved_step:
            manager.save(step, params, opt_state, trained_tokens, layout=layout,
                         zero1=z1)
        manager.close()
    if wandb is not None:
        wandb.finish()
    return step, trained_tokens, loss


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="picotron-tpu trainer (one JSON config per experiment, "
                    "reference train.py:57-63)")
    parser.add_argument("--config", required=True, help="path to config.json")
    parser.add_argument("--max-steps", type=int, default=None,
                        help="override training.total_train_steps")
    args = parser.parse_args(argv)

    with open(args.config) as f:
        raw = json.load(f)
    from picotron_tpu.config import Config
    from picotron_tpu.utils import log0

    cfg = Config.from_dict(raw)
    _ensure_devices(cfg)
    _maybe_init_distributed()
    step, tokens, loss = train(cfg, max_steps_override=args.max_steps)
    log0(f"done: {step} steps, {tokens} tokens, final loss {loss:.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
