"""Training entry point: ``python -m picotron_tpu.train --config exp.json``.

The TPU single-controller collapse of the reference's ``train.py`` (:57-281).
What torchrun + rendezvous + per-rank env vars did there is one process here:
the config names a (dp, pp, cp, tp) topology, the mesh is built over the
visible devices, and one jitted shard_map program runs the whole 4D step.

Per-step log line carries the same fields the reference prints
(train.py:247-259): step, loss, global batch size, tokens/s, tokens/s/chip,
trained tokens, MFU, device memory — which is exactly what the
extract_metrics CLI scrapes (extract_metrics.py:55-68). wandb logging is
opt-in with the same run-name convention (train.py:132-150); a jax.profiler
trace window replaces the reference's absent profiler (SURVEY.md §5.1).

Fault tolerance (picotron_tpu/resilience/, docs/RESILIENCE.md) is wired
through the loop: SIGTERM/SIGINT finish the in-flight dispatch, flush an
emergency checkpoint, and exit ``EXIT_PREEMPTED``; ANY crash still flushes a
final save via try/finally; re-running the same command auto-resumes from
the latest checkpoint; per-step losses feed an EMA anomaly detector with
skip/rollback/abort policies; and a config-driven chaos injector gives all
of it a deterministic test surface (``make chaos-smoke``).

Telemetry (picotron_tpu/obs, docs/OBSERVABILITY.md): the controller
process writes a per-step metrics JSONL (``$PICOTRON_METRICS_JSONL`` /
``obs.metrics_jsonl``) that ``tools/extract_metrics.py`` ingests instead
of regex-scraping the log; every dispatch records data/dispatch/host-sync
spans (plus checkpoint and consensus-tick spans) into the process trace
ring, dumped as Chrome-trace JSON at exit when ``obs.trace_path`` is set;
rollbacks, anomalies, consensus adoptions, and emergency saves count in
the metrics registry, whose snapshot lands as the JSONL's final summary
row. ``kill -USR2 <pid>`` grabs a timed ``jax.profiler`` capture into
``obs.profile_dir`` without restarting the run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional


def _ensure_devices(cfg) -> None:
    """use_cpu runs (the reference's Gloo path, train.py:83) need the virtual
    CPU device count pinned before a backend exists. On a CPU pod (the
    supervisor's --num-procs exports the rendezvous env) the world is split
    across processes: each rank hosts world/nproc of the virtual devices,
    or the global mesh would see nproc * world."""
    if cfg.distributed.use_cpu:
        n_local = cfg.world_size
        nproc = int(os.environ.get("JAX_NUM_PROCESSES", "1") or 1)
        if os.environ.get("JAX_COORDINATOR_ADDRESS") and nproc > 1:
            if cfg.world_size % nproc:
                raise ValueError(
                    f"world_size {cfg.world_size} is not divisible by the "
                    f"pod's JAX_NUM_PROCESSES={nproc}")
            n_local = cfg.world_size // nproc
        os.environ["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={n_local} "
            + os.environ.get("XLA_FLAGS", "")
        )
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax

        jax.config.update("jax_platforms", "cpu")


def _maybe_init_distributed() -> None:
    """Join a multi-host mesh when launched by the pod/slurm template
    (template/base_job.slurm exports these; the analogue of torchrun's
    RANK/WORLD_SIZE rendezvous, reference train.py:83-94). One JAX process
    per host; after initialize(), jax.devices() spans every host's chips."""
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not addr:
        return
    import jax

    if os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip() == "cpu":
        # CPU pods (the reference's Gloo path): without this, any program
        # spanning processes fails with "Multiprocess computations aren't
        # implemented on the CPU backend"
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]),
    )


def _wandb_init(cfg):
    """Run name convention from the reference: {name}_{tokens-per-step}_
    {topology} (train.py:132-143)."""
    import wandb

    from picotron_tpu.utils import to_readable_format

    d = cfg.distributed
    run_name = (
        f"{cfg.logging.run_name}_{to_readable_format(cfg.tokens_per_step)}"
        f"_dp{d.dp_size}_tp{d.tp_size}_pp{d.pp_size}_cp{d.cp_size}"
    )
    wandb.init(name=run_name, config=cfg.to_dict())
    return wandb


def _touch(path: str) -> None:
    """Heartbeat for the supervisor's stall detector: mtime = liveness."""
    try:
        with open(path, "a"):
            os.utime(path, None)
    except OSError:
        pass  # a lost heartbeat must never kill the training step


def _savable(*trees) -> bool:
    """Whether every leaf is a live array: after a crash INSIDE a donating
    dispatch, the loop variables still reference the donated (deleted)
    inputs, which cannot be saved — the last periodic checkpoint stands."""
    import jax

    return not any(
        getattr(x, "is_deleted", lambda: False)()
        for t in trees for x in jax.tree.leaves(t))


def train(cfg, max_steps_override: Optional[int] = None,
          loss_history: Optional[list] = None):
    """Run the training loop; returns (final_step, trained_tokens, last_loss).

    ``loss_history``, when given, collects ``(step, loss)`` per optimizer
    step — the chaos/equivalence suite compares full trajectories through
    it instead of scraping logs."""
    import jax

    from picotron_tpu import checkpoint as ckpt_mod
    from picotron_tpu import resilience
    from picotron_tpu import train_step as ts
    from picotron_tpu import utils
    from picotron_tpu.data import MicroBatchDataLoader
    from picotron_tpu.models import llama
    from picotron_tpu.obs import GLOBAL_REGISTRY, MetricsJsonl, Obs
    from picotron_tpu.obs.jsonl import resolve_path as jsonl_path
    from picotron_tpu.resilience.anomaly import AnomalyAbort, LossAnomalyDetector
    from picotron_tpu.resilience.chaos import ChaosInjector
    from picotron_tpu.resilience.cluster import ClusterCoordinator, ClusterMonitor
    from picotron_tpu.resilience.preemption import PreemptionGuard
    from picotron_tpu.topology import topology_from_config

    t0_setup = time.perf_counter()
    topo = topology_from_config(cfg)
    m, t, c, lg, r = (cfg.model, cfg.training, cfg.checkpoint, cfg.logging,
                      cfg.resilience)
    utils.set_all_seed(t.seed)

    guard = PreemptionGuard().install() if r.handle_signals \
        else PreemptionGuard()  # not installed: .triggered stays False
    # Pod control plane (resilience/cluster.py): consensus turns ANY host's
    # SIGTERM into the same coordinated break on every host; the monitor is
    # the wedge escape when a host dies outright. Both are inert on a
    # single process.
    coord = (ClusterCoordinator(r.consensus_interval)
             if r.consensus_interval > 0 else None)
    monitor = None
    if r.peer_timeout_s > 0 and jax.process_count() > 1:
        cluster_dir = r.cluster_dir or (
            os.path.join(c.save_dir, "_cluster") if c.save_dir else "")
        if cluster_dir:
            monitor = ClusterMonitor(
                cluster_dir, jax.process_index(), jax.process_count(),
                peer_timeout_s=r.peer_timeout_s,
                lease_interval_s=r.lease_interval_s).start()
        else:
            utils.log0("cluster monitor disabled: peer_timeout_s set but "
                       "no cluster_dir and no checkpoint.save_dir to "
                       "derive one from")
    chaos = ChaosInjector(r, save_dir=c.save_dir)
    detector = LossAnomalyDetector(
        ema_beta=r.anomaly_ema_beta, zscore=r.anomaly_zscore,
        warmup_steps=r.anomaly_warmup_steps)
    # The supervisor's export wins over a static config path: it names the
    # exact file its stall detector watches (PER-RANK in pod mode —
    # <hb>.p<i>); a config path carried over from single-host use would
    # leave the watched files untouched and stall-kill a healthy pod.
    heartbeat = os.environ.get("PICOTRON_HEARTBEAT", "") or r.heartbeat_path
    # Telemetry (docs/OBSERVABILITY.md): per-run registry + the process
    # span ring; the per-step metrics JSONL replaces log-scraping
    # (controller process only — same gating as the log/wandb reports).
    obs = Obs.from_config(cfg.obs)
    jpath = jsonl_path(cfg.obs)
    jsonl = (MetricsJsonl(jpath, log=utils.log0)
             if jpath and utils.is_main_process() else None)
    rollbacks_ctr = obs.registry.counter(
        "picotron_rollbacks_total", "anomaly rollbacks taken")
    adoptions_ctr = obs.registry.counter(
        "picotron_consensus_adoptions_total",
        "peer preemption verdicts adopted via consensus")

    # state the finally below may touch — defined before anything can raise
    manager = None
    wandb = None
    params = opt_state = None
    step = last_saved_step = trained_tokens = 0
    loss = float("nan")
    profiling = profile_done = False
    layout = (m.num_hidden_layers, cfg.distributed.pp_size,
              cfg.distributed.pp_interleave)
    z1 = (cfg.distributed.zero1, cfg.distributed.dp_size)

    try:
        loader = MicroBatchDataLoader(cfg)
        params, opt_state = ts.init_state(cfg, topo)
        if c.hf_bootstrap_path:
            # header-only names+shapes check — zero tensor bytes read; guards
            # BOTH modes against a template that disagrees with the model config
            ckpt_mod.validate_hf_template(c.hf_bootstrap_path, m)
            if c.hf_bootstrap_reinit:
                # reference semantics (checkpoint.py:99-100): the HF file is a
                # shape template only; training starts from the seed-derived
                # random init above
                utils.log0(f"hf_bootstrap_reinit: validated "
                           f"{c.hf_bootstrap_path} as a shape template; keeping "
                           f"random init (reference re-randomize semantics)")
            else:
                params = ckpt_mod.load_hf_safetensors(
                    c.hf_bootstrap_path, m, topo,
                    interleave=cfg.distributed.pp_interleave,
                    fsdp=cfg.distributed.fsdp)
        spc = t.steps_per_call
        step_fn = ts.build_train_step(cfg, topo, multi_step=spc)
        step_fn_single = step_fn if spc == 1 else None  # lazily built for the tail
        step_fn_poison = None  # lazily built chaos NaN-injection program

        # Resume resolution: an explicit load_path is REQUIRED to hold a
        # checkpoint; "auto" (or, with resilience.auto_resume, an empty
        # load_path while save_frequency > 0) discovers the latest checkpoint
        # under save_dir when one exists — re-running the same command
        # continues the run instead of restarting it from scratch.
        resume_dir, resume_required = None, False
        if c.load_path and c.load_path != "auto":
            resume_dir, resume_required = c.load_path, True
        elif c.load_path == "auto" or (r.auto_resume and c.save_frequency > 0):
            resume_dir = c.save_dir
        if c.save_frequency > 0 or resume_dir:
            manager = ckpt_mod.CheckpointManager(
                resume_dir or c.save_dir, io_attempts=r.io_attempts,
                io_backoff=r.io_backoff, io_jitter=r.io_jitter,
                mirror_dir=r.ckpt_mirror_dir)
        if manager is not None and resume_dir and (
                resume_required or manager.latest_step() is not None):
            params, opt_state, step, trained_tokens = manager.load(
                params, opt_state, layout=layout, zero1=z1)
            # geometry guard BEFORE skipping: a changed batch geometry would
            # silently position the loader on different data
            loader.verify_resume(
                (manager.last_restored_meta or {}).get("data"), step)
            loader.skip_steps(step)
            last_saved_step = step
            utils.log0(f"resumed from {resume_dir} at step {step} "
                       f"({utils.to_readable_format(trained_tokens)} tokens)")
            if resume_dir != c.save_dir and c.save_frequency > 0:
                manager.close()
                manager = ckpt_mod.CheckpointManager(
                    c.save_dir, io_attempts=r.io_attempts,
                    io_backoff=r.io_backoff, io_jitter=r.io_jitter,
                    mirror_dir=r.ckpt_mirror_dir)

        # wandb/log gating: only the controller process reports (reference
        # train.py:101, utils.py:12-20)
        wandb = _wandb_init(cfg) if (lg.use_wandb and utils.is_main_process()) else None
        n_params = llama.num_params(m)
        peak = utils.peak_flops_per_chip()
        n_chips = topo.world_size
        max_steps = max_steps_override or t.total_train_steps
        utils.log0(f"model {m.name}: {utils.to_readable_format(n_params)} params | "
              f"mesh dp={topo.dp_size} pp={topo.pp_size} cp={topo.cp_size} "
              f"tp={topo.tp_size} on {n_chips} x {jax.devices()[0].device_kind} | "
              f"global batch {cfg.global_batch_size} "
              f"({utils.to_readable_format(cfg.tokens_per_step)} tokens/step) | "
              f"setup {time.perf_counter() - t0_setup:.1f}s")

        rollbacks = 0
        while step < max_steps and (t.max_tokens is None or trained_tokens < t.max_tokens):
            # Preemption check. With consensus on, the decision is collective:
            # every process all-reduces its local flag at the same boundaries,
            # so a SIGTERM delivered to ONE host becomes the same break — and
            # the same collective emergency save — on ALL hosts. A locally-
            # set flag between rounds waits for the next round; breaking
            # alone would tear the collective save.
            if coord is not None:
                with obs.tracer.span("consensus_tick", step=step):
                    preempt = coord.preempt_now(step, guard.triggered)
            else:
                preempt = guard.triggered
            if preempt:
                if not guard.triggered:
                    # a peer's signal, learned via consensus: adopt it so the
                    # emergency-save path and the exit code behave exactly
                    # like a locally-signaled host (this host's OWN copy of
                    # the pod-wide SIGTERM stays benign, not an escalation)
                    adoptions_ctr.inc()
                    guard.adopt()
                utils.log0(f"preemption: {guard.signame} received; flushing "
                           f"checkpoint at step {step} and exiting "
                           f"{resilience.EXIT_PREEMPTED}", flush=True)
                break
            if heartbeat:
                _touch(heartbeat)
            # Profiler window snaps to dispatch boundaries (a dispatch is spc
            # steps): stop is checked before start so a window narrower than one
            # dispatch still traces one full dispatch; the done latch makes the
            # window fire exactly once.
            if profiling and lg.profile_stop and step >= lg.profile_stop:
                jax.profiler.stop_trace()
                profiling, profile_done = False, True
            if (lg.profile_start and not profiling and not profile_done
                    and step >= lg.profile_start):
                jax.profiler.start_trace(lg.profile_dir)
                profiling = True
            t_start = time.perf_counter()
            step_before = step
            # spc optimizer steps per device dispatch; a tail shorter than spc
            # (by step count OR token budget) would trigger a recompile at a new
            # stack shape — run those steps singly instead.
            steps_left = max_steps - step
            if t.max_tokens is not None:
                tokens_left = t.max_tokens - trained_tokens
                steps_left = min(steps_left, -(-tokens_left // cfg.tokens_per_step))
            k = spc if steps_left >= spc else 1
            poisoned = chaos.poison_step(step + 1)  # config pins spc==1 here
            if k > 1:
                tokens, targets = ts.shard_batch_stack(
                    [next(loader) for _ in range(k)], topo)
                t_disp = time.perf_counter()
                params, opt_state, loss_arr = step_fn(params, opt_state, tokens, targets)
                t_sync = time.perf_counter()
                losses = [float(x) for x in utils.host_values(loss_arr)]
            else:
                tokens, targets = ts.shard_batch(next(loader), topo)
                if poisoned:
                    if step_fn_poison is None:
                        step_fn_poison = ts.build_train_step(
                            cfg, topo, poison_nonfinite=True)
                    fn = step_fn_poison
                else:
                    if step_fn_single is None:
                        step_fn_single = ts.build_train_step(cfg, topo)
                    fn = step_fn_single
                t_disp = time.perf_counter()
                params, opt_state, loss_arr = fn(
                    params, opt_state, tokens, targets)
                t_sync = time.perf_counter()
                losses = [float(utils.host_values(loss_arr))]
            t_end = time.perf_counter()
            dt_call = t_end - t_start
            # per-dispatch spans: data (batch build) -> dispatch (async
            # submit) -> host_sync (blocked on device losses), parented
            # under one train/dispatch root — the serving trace's exact
            # counterpart, dumped at exit via obs.trace_path
            droot = obs.tracer.record("train/dispatch", t_start, t_end,
                                      step=step_before + 1, steps=k)
            obs.tracer.record("data", t_start, t_disp, parent=droot)
            obs.tracer.record("dispatch", t_disp, t_sync, parent=droot)
            obs.tracer.record("host_sync", t_sync, t_end, parent=droot)
            obs.registry.histogram(
                "picotron_train_dispatch_seconds",
                "train dispatch wall time (k fused steps)").observe(dt_call)

            # Throughput is per dispatch (identical for every step in the group);
            # mfu/memory are computed lazily, once, and only if a step logs.
            tok_s = k * cfg.tokens_per_step / dt_call
            tok_s_chip = tok_s / n_chips
            stats = None
            do_rollback = False
            for i, loss in enumerate(losses):
                step += 1
                trained_tokens += cfg.tokens_per_step
                if loss_history is not None:
                    loss_history.append((step, loss))
                anom = detector.observe(step, loss)
                if anom is not None:
                    obs.registry.counter(
                        "picotron_loss_anomalies_total",
                        "loss anomalies flagged, by kind",
                        kind=anom.kind).inc()
                    utils.log0(
                        f"loss anomaly at step {step}: loss={loss:.6g} "
                        f"kind={anom.kind} consecutive={anom.consecutive} "
                        f"policy={r.anomaly_policy}", flush=True)
                    if r.anomaly_policy == "abort":
                        raise AnomalyAbort(
                            f"anomalous loss {loss} at step {step} "
                            f"(kind={anom.kind}); anomaly_policy='abort'")
                    if (r.anomaly_policy == "rollback"
                            and anom.consecutive >= r.rollback_after):
                        do_rollback = True
                if step % lg.log_frequency == 0 and stats is None:
                    stats = (utils.get_mfu(tok_s_chip, n_params, m.num_hidden_layers,
                                           m.hidden_size, t.seq_length, peak),
                             utils.device_memory_gb())
                mfu, mem = stats if stats is not None else (None, None)
                if step % lg.log_frequency == 0:
                    parts = [
                        f"Step: {step:<5d}",
                        f"Loss: {loss:6.4f}",
                        f"Global batch size: {utils.to_readable_format(cfg.tokens_per_step)}",
                        f"Tokens/s: {utils.to_readable_format(tok_s)}",
                        f"Tokens/s/chip: {utils.to_readable_format(tok_s_chip)}",
                        f"Tokens: {utils.to_readable_format(trained_tokens)}",
                    ]
                    if mfu is not None:
                        parts.append(f"MFU: {mfu:.2f}%")
                    if mem is not None:
                        parts.append(f"Memory usage: {mem:.2f}GB")
                    utils.log0(" | ".join(parts), flush=True)
                if wandb is not None and step % lg.log_frequency == 0:
                    wandb.log({"loss": loss, "tokens_per_sec": tok_s,
                               "tokens_per_sec_per_chip": tok_s_chip,
                               "trained_tokens": trained_tokens,
                               **({"mfu": mfu} if mfu is not None else {}),
                               **({"memory_gb": mem} if mem is not None else {})},
                              step=step)
                if jsonl is not None:
                    # EVERY step, not just log-frequency ones: the JSONL
                    # is the machine surface, the log line the human one.
                    # mfu/memory stay null off log steps (they are only
                    # computed there); extract_metrics averages over the
                    # non-null rows exactly as it did for the regex path.
                    jsonl.write({
                        "step": step, "loss": loss,
                        "tokens_per_sec": tok_s,
                        "tokens_per_sec_per_chip": tok_s_chip,
                        "trained_tokens": trained_tokens,
                        "mfu_pct": mfu, "memory_gb": mem,
                        "t": round(time.time(), 3)})

            # Save at group boundaries only: params here are the end-of-group
            # state, so the recorded step must be the end-of-group step.
            # A pending rollback skips the save — these params are the
            # anomalous state the rollback exists to discard; saving them
            # first would make the restore below reload the bad step and
            # replay the anomaly until max_rollbacks aborts the run.
            if (manager is not None and c.save_frequency > 0
                    and not do_rollback
                    and step // c.save_frequency > step_before // c.save_frequency):
                with obs.tracer.span("checkpoint", step=step):
                    manager.save(step, params, opt_state, trained_tokens,
                                 layout=layout, zero1=z1,
                                 data_meta=loader.state_meta(step))
                last_saved_step = step

            if monitor is not None:
                monitor.notify_step(step)
            chaos.after_step(step, manager=manager)

            if do_rollback:
                if manager is None or manager.latest_step() is None:
                    raise AnomalyAbort(
                        f"rollback requested at step {step} but no "
                        f"checkpoint exists under {c.save_dir}")
                rollbacks += 1
                rollbacks_ctr.inc()
                if rollbacks > r.max_rollbacks:
                    raise AnomalyAbort(
                        f"anomaly persisted through {r.max_rollbacks} "
                        f"rollbacks; aborting at step {step}")
                with obs.tracer.span("rollback", step=step):
                    params, opt_state, step, trained_tokens = manager.load(
                        params, opt_state, layout=layout, zero1=z1)
                loader.seek_steps(step)
                detector.reset()
                last_saved_step = step
                utils.log0(f"anomaly rollback #{rollbacks}: restored step "
                           f"{step}, replaying", flush=True)
    finally:
        if profiling:
            jax.profiler.stop_trace()
        guard.uninstall()
        flush_abandoned = False
        try:
            # the emergency/final flush: reached on clean completion,
            # preemption, AND any crash — a run never loses more than the
            # current dispatch (unless that dispatch consumed the donated
            # state, in which case the last periodic checkpoint stands)
            if (manager is not None and c.save_frequency > 0 and r.save_on_exit
                    and step > last_saved_step and _savable(params, opt_state)):
                def _flush():
                    manager.save(step, params, opt_state, trained_tokens,
                                 layout=layout, zero1=z1,
                                 data_meta=loader.state_meta(step))

                if guard.triggered:
                    # preemption path: the flush runs on a background
                    # thread, joined with a deadline — a wedged save costs
                    # at most emergency_save_timeout_s of the grace window
                    if guard.emergency_save(
                            _flush, timeout_s=r.emergency_save_timeout_s):
                        utils.log0(f"flushed emergency checkpoint at step "
                                   f"{step}", flush=True)
                    else:
                        flush_abandoned = True
                else:
                    _flush()
                    utils.log0(f"flushed checkpoint at step {step}",
                               flush=True)
        finally:
            if manager is not None and not flush_abandoned:
                try:
                    manager.close()  # drains any in-flight async save
                except Exception as e:
                    utils.log0(f"checkpoint manager close failed: {e!r}")
            if monitor is not None:
                # Stopped only AFTER the final (collective) flush: a peer
                # dying mid-save still needs the wedge escape. Mark done
                # only on clean/coordinated exits — a crash's stale lease
                # is exactly how the peers learn this host is gone.
                monitor.stop(mark_done=sys.exc_info()[0] is None)
            if wandb is not None:
                wandb.finish()
            if jsonl is not None:
                # the run's registry snapshot (rollbacks, anomalies,
                # adoptions, retries, emergency saves, dispatch timing)
                # rides out as the terminal summary row — consumers key
                # rows on "step" and skip it
                jsonl.write({"event": "summary",
                             "metrics": {**obs.registry.summary(),
                                         **GLOBAL_REGISTRY.summary()}})
                jsonl.close()
            if cfg.obs.trace_path and utils.is_main_process() \
                    and obs.enabled:
                try:
                    obs.tracer.dump_chrome(cfg.obs.trace_path)
                except OSError as e:
                    utils.log0(f"trace dump failed: {e!r}")
    return step, trained_tokens, loss


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="picotron-tpu trainer (one JSON config per experiment, "
                    "reference train.py:57-63)")
    parser.add_argument("--config", required=True, help="path to config.json")
    parser.add_argument("--max-steps", type=int, default=None,
                        help="override training.total_train_steps")
    args = parser.parse_args(argv)

    with open(args.config) as f:
        raw = json.load(f)
    from picotron_tpu.config import Config
    from picotron_tpu.utils import log0

    cfg = Config.from_dict(raw)
    _ensure_devices(cfg)
    _maybe_init_distributed()
    if cfg.obs.enabled:
        # kill -USR2 <pid> -> one timed jax.profiler capture into
        # obs.profile_dir: the "this run is slow RIGHT NOW" surface,
        # no restart or pre-planned profile window needed
        from picotron_tpu.obs import ProfileCapture, install_sigusr2

        install_sigusr2(ProfileCapture(
            cfg.obs.profile_dir, cfg.obs.profile_seconds, log=log0))
    from picotron_tpu import resilience
    from picotron_tpu.resilience.anomaly import AnomalyAbort

    try:
        step, tokens, loss = train(cfg, max_steps_override=args.max_steps)
    except AnomalyAbort as e:
        log0(f"aborted by anomaly policy: {e}")
        return resilience.EXIT_ANOMALY
    if resilience.was_preempted():
        log0(f"preempted; checkpoint flushed at step {step} — exit "
             f"{resilience.EXIT_PREEMPTED} (re-run the same command to resume)")
        return resilience.EXIT_PREEMPTED
    log0(f"done: {step} steps, {tokens} tokens, final loss {loss:.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
