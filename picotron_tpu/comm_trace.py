"""Collective/p2p trace channel — the analogue of the reference's VERBOSE=1
send/recv tracing (reference pipeline_parallel/pp_communications.py:28 and
context_parallel/cp_communications.py:33-35 print every op with rank, peer
and shape).

Under XLA the runtime comm schedule IS the traced program: everything inside
jit executes exactly as traced, so logging each collective once at trace
time (op, mesh axis, shape, dtype) reproduces the information content of the
reference's per-call prints without a host callback in the hot path.

- ``PICOTRON_VERBOSE=1``: one stderr line per collective per trace, and
  one instant event (``comm/<op>``) in the process span ring
  (picotron_tpu/obs) — so a Chrome-trace dump (``obs.trace_path``,
  ``GET /tracez``, ``tools/trace_dump.py``) shows which collectives each
  traced program carries alongside the step/request spans.
- ``PICOTRON_VERBOSE=2``: additionally injects ``jax.debug.print`` so every
  *execution* logs the op tag (slow — debugging only; runs per device under
  shard_map, the closest analogue of the reference's per-rank prints).

The env var is read at call time, so tests (and running jobs restarted with
the flag) do not need an import-order dance.

Caveat: collectives that autodiff DERIVES as transposes of traced ones
(e.g. the reverse all-to-alls in the Ulysses backward) carry no trace call
of their own — their forward counterpart's line stands for the pair, the
same way the reference logs a send/recv pair once.
"""

from __future__ import annotations

import os
import sys


def _level() -> int:
    try:
        return int(os.environ.get("PICOTRON_VERBOSE", "0") or "0")
    except ValueError:
        return 0


def log(op: str, axis, x, extra: str = ""):
    """Record one collective at trace time; identity on ``x``.

    ``axis`` is the mesh axis name (or tuple) the collective runs over —
    the device-group analogue of the reference's src/dest rank pair.
    """
    lvl = _level()
    if lvl <= 0:
        return x
    shape = tuple(getattr(x, "shape", ()) or ())
    dtype = getattr(x, "dtype", "?")
    msg = f"[comm] {op} axis={axis} shape={shape} dtype={dtype}"
    if extra:
        msg += f" {extra}"
    print(msg, file=sys.stderr)
    # the same record, structured: an instant event in the process span
    # ring (this runs at TRACE time, host-side — never inside compiled
    # code, so the wall clock here is legal)
    from picotron_tpu.obs import GLOBAL_TRACER

    GLOBAL_TRACER.instant(f"comm/{op}", axis=str(axis), shape=str(shape),
                          dtype=str(dtype),
                          **({"extra": extra} if extra else {}))
    if lvl >= 2:
        import jax

        jax.debug.print("[comm-exec] " + op + " axis=" + str(axis)
                        + " shape=" + str(shape))
    return x
