"""JAX hot-path analyzer: PICO-J001..J005.

Entry points are discovered syntactically — functions decorated with or
passed to ``jax.jit`` / ``jax.pmap`` / ``pl.pallas_call`` / ``shard_map``
(including the ``utils.shard_map`` compat wrapper and
``functools.partial(kernel, ...)`` indirection), plus bodies handed to
``lax.fori_loop`` / ``while_loop`` / ``scan`` / ``cond`` (those trace even
outside jit).  From each entry the intra-project call graph is walked
(``callgraph.Project``), and every reachable function is analyzed as
*traced code*:

- **PICO-J001** — host-sync operations on traced values.  A light taint
  pass marks the function's parameters and anything assigned from them or
  from ``jnp``/``jax``/``lax`` call results; ``float()``/``int()``/
  ``bool()``/``.item()``/``.tolist()``/``np.asarray``/``np.array``/
  ``jax.device_get``/``.block_until_ready()`` applied to a tainted value
  is a finding, as is an ``if``/``while`` test that coerces an
  array-derived value.  Shape/dtype reads (``x.shape``, ``x.ndim``,
  ``x.dtype``, ``len(x)``) are static under trace and stop the taint.
- **PICO-J002** — host nondeterminism under trace (``time.*``,
  ``random.*``, ``np.random.*``, ``os.urandom``, ``uuid.*``,
  ``datetime.now``): evaluated once at trace time, baked into the
  compiled program.
- **PICO-J003** — ``pl.program_id`` (or any ``*.program_id``) read inside
  a function passed as a ``fori_loop``/``while_loop``/``scan`` body: the
  0.4.37 Pallas interpreter cannot resolve it in the sub-jaxpr (see
  ``ops/pallas/decode_attention.py``).  Scanned everywhere, traced or
  not — the trap fires at kernel runtime.
- **PICO-J004** — ``jax.jit``/``jax.pmap``/``pl.pallas_call`` evaluated
  lexically inside a ``for``/``while`` loop: a fresh callable per
  iteration means a recompile per iteration unless cached outside.
- **PICO-J005** — ``pltpu.make_async_copy`` started with no matching
  ``.wait()`` in the enclosing function, or started per-iteration inside
  a ``fori_loop``/``while_loop``/``scan`` body whose every wait sits
  outside the loop: the DMA is still in flight when its buffer is read
  (or the semaphore imbalances) — the exact hazard the double-buffered
  decode kernel (``ops/pallas/decode_attention.py``) must discipline.
- **PICO-J006** — a compiled model program called around ``_dispatch``.
  In any class defining ``_dispatch`` (the retry / flash-fallback fault
  wrapper), a call to a ``self._*_jit`` / ``self._*_prog`` attribute
  whose first operand is ``params`` (the model-program signature —
  housekeeping programs take the cache or nothing first) must sit inside
  a ``self._dispatch(...)`` argument; a direct call silently opts the
  program family out of the engine's fault semantics.  Builder calls
  (``self._make_*``) construct rather than dispatch and are exempt.
"""

from __future__ import annotations

import ast
from typing import Optional

from picotron_tpu.analysis.callgraph import (
    FuncInfo, ModuleInfo, Project, dotted_name, enclosing_qualname)
from picotron_tpu.analysis.findings import Finding

# attribute reads that yield static (trace-time Python) values: reading
# them off a tracer does not sync, and values derived from them are static
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                "itemsize", "nbytes"}
# calls whose results are static regardless of argument taint
STATIC_CALLS = {"len", "isinstance", "type", "getattr", "hasattr", "range"}
# names whose attributes produce traced arrays (taint sources / "derived")
ARRAY_NAMESPACES = {"jnp", "lax", "jax", "pl", "pltpu"}
HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
HOST_SYNC_CASTS = {"float", "int", "bool", "complex"}
# wrappers whose function-valued args enter trace
JIT_WRAPPERS = {("jax", "jit"), ("jax", "pmap"), ("jax", "vmap"),
                ("jax", "grad"), ("jax", "value_and_grad"),
                ("jax", "checkpoint"), ("jax", "remat"),
                ("jax", "shard_map")}
LOOP_BODY_WRAPPERS = {"fori_loop", "while_loop", "scan", "cond"}


def _callee_parts(call: ast.Call) -> Optional[list]:
    return dotted_name(call.func)


def _is_jit_wrapper(parts: list, mod: ModuleInfo) -> bool:
    """Whether a dotted callee name is a jit-like wrapper call."""
    if len(parts) >= 2 and (parts[-2], parts[-1]) in JIT_WRAPPERS:
        return True
    if parts[-1] in ("pallas_call",):
        return True
    if parts[-1] in ("shard_map", "shard_map_compat"):
        return True
    if len(parts) == 1 and parts[0] in ("jit", "pmap"):
        src = mod.from_imports.get(parts[0])
        return src is not None and src[0].startswith("jax")
    return False


def _unwrap_partial(node: ast.expr) -> ast.expr:
    """``functools.partial(f, ...)`` / ``partial(f, ...)`` -> ``f``."""
    if isinstance(node, ast.Call):
        parts = dotted_name(node.func)
        if parts and parts[-1] == "partial" and node.args:
            return node.args[0]
    return node


def _func_args_of_call(call: ast.Call, parts: list) -> list:
    """The positional args of a wrapper call that are traced callables."""
    if parts[-1] in LOOP_BODY_WRAPPERS:
        if parts[-1] == "fori_loop":
            return call.args[2:3]
        if parts[-1] == "while_loop":
            return call.args[0:2]
        if parts[-1] == "scan":
            return call.args[0:1]
        if parts[-1] == "cond":
            return call.args[1:3]
    return call.args[0:1]  # jit/pmap/pallas_call/shard_map: first arg


class _EntryCollector(ast.NodeVisitor):
    """Find every function that enters trace in one module."""

    def __init__(self, project: Project, mod: ModuleInfo):
        self.project = project
        self.mod = mod
        self.entries: list = []  # FuncInfo
        self.lambda_entries: list = []  # (ast.Lambda, context qualname)
        self._scope: list = []  # qualname prefix stack
        self._class: Optional[str] = None

    # -- scope tracking ---------------------------------------------------- #

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, self._class = self._class, node.name
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()
        self._class = prev

    def _visit_func(self, node) -> None:
        self._check_decorators(node)
        self._scope.append(node.name)
        self._scope.append("<locals>")
        self.generic_visit(node)
        self._scope.pop()
        self._scope.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- entry forms ------------------------------------------------------- #

    def _check_decorators(self, node) -> None:
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            target = _unwrap_partial(target) if isinstance(dec, ast.Call) \
                else target
            parts = dotted_name(target)
            if parts and _is_jit_wrapper(parts, self.mod):
                fi = self._resolve_local(node.name)
                if fi is not None:
                    self.entries.append(fi)
            # @partial(jax.jit, static_argnames=...) — the partial's first
            # arg is the wrapper, the decorated function is the entry
            if isinstance(dec, ast.Call):
                inner = dotted_name(dec.func)
                if inner and inner[-1] == "partial" and dec.args:
                    wparts = dotted_name(dec.args[0])
                    if wparts and _is_jit_wrapper(wparts, self.mod):
                        fi = self._resolve_local(node.name)
                        if fi is not None:
                            self.entries.append(fi)

    def visit_Call(self, node: ast.Call) -> None:
        parts = _callee_parts(node)
        if parts and (_is_jit_wrapper(parts, self.mod)
                      or parts[-1] in LOOP_BODY_WRAPPERS):
            for arg in _func_args_of_call(node, parts):
                self._add_entry(_unwrap_partial(arg))
        self.generic_visit(node)

    def _add_entry(self, expr: ast.expr) -> None:
        if isinstance(expr, ast.Lambda):
            self.lambda_entries.append((expr, ".".join(
                [p for p in self._scope if p != "<locals>"]) or "<module>"))
            return
        fi = None
        if isinstance(expr, ast.Name):
            fi = self._resolve_local(expr.id)
        elif isinstance(expr, ast.Attribute):
            if (isinstance(expr.value, ast.Name) and expr.value.id == "self"
                    and self._class):
                fi = self.mod.functions.get(f"{self._class}.{expr.attr}")
            else:
                fi = self.project.resolve_callee_expr(self.mod, expr,
                                                      self._class)
        if fi is not None:
            self.entries.append(fi)

    def _resolve_local(self, name: str) -> Optional[FuncInfo]:
        """A bare name in the current scope: innermost nested def first,
        then module level, then project imports."""
        prefix = list(self._scope)
        while prefix:
            fi = self.mod.functions.get(".".join(prefix + [name]))
            if fi is not None:
                return fi
            prefix.pop()
        return self.project.resolve_name(self.mod, name)


def traced_functions(project: Project) -> tuple:
    """``(reachable, direct)``: qualname keys ``(modname, qualname)`` of
    every function reachable from a jit/pallas/control-flow entry point,
    and the subset that IS such an entry (decorated with / passed to a
    wrapper).  Direct entries have definitely-traced parameters; a
    transitively-reached helper may take any mix of tracers and static
    Python values, so its params must not be presumed traced (the
    ``is_entry`` contract in ``_TracedFuncChecker``)."""
    entries: list = []
    for mod in project.modules.values():
        col = _EntryCollector(project, mod)
        col.visit(mod.tree)
        entries.extend(col.entries)
    direct = {(fi.module.modname, fi.qualname) for fi in entries}
    seen: set = set()
    work = list(entries)
    while work:
        fi = work.pop()
        key = (fi.module.modname, fi.qualname)
        if key in seen:
            continue
        seen.add(key)
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            callee = project.resolve_call(fi.module, node, fi.class_name)
            if callee is not None:
                work.append(callee)
        # nested defs run under the same trace
        for qual, sub in fi.module.functions.items():
            if qual.startswith(fi.qualname + ".<locals>."):
                work.append(sub)
    return seen, direct


# --------------------------------------------------------------------------- #
# J001/J002: taint + nondeterminism inside traced functions
# --------------------------------------------------------------------------- #


def _names_in(node: ast.expr, stop_static: bool = True) -> set:
    """Names referenced in ``node``, optionally pruning subtrees under
    static attribute reads / static calls (``x.shape``, ``len(x)``)."""
    out: set = set()

    def walk(n: ast.AST) -> None:
        if stop_static and isinstance(n, ast.Attribute) \
                and n.attr in STATIC_ATTRS:
            return
        if stop_static and isinstance(n, ast.Call):
            parts = dotted_name(n.func)
            if parts and parts[-1] in STATIC_CALLS:
                return
        if isinstance(n, ast.Name):
            out.add(n.id)
        for c in ast.iter_child_nodes(n):
            walk(c)

    walk(node)
    return out


# jax/jnp functions whose results are static host values, not tracers —
# shape/type/topology introspection and trace-time-only utilities
JAX_STATIC_FUNCS = {"eval_shape", "ShapeDtypeStruct", "typeof",
                    "device_count", "local_device_count", "process_index",
                    "process_count", "devices", "local_devices",
                    "named_scope", "dtype", "result_type"}


def _call_is_array(call: ast.Call, mod: ModuleInfo) -> bool:
    """Whether this one call's RESULT is a traced array (jnp/lax/... and
    not a static introspection helper)."""
    parts = dotted_name(call.func)
    if not parts:
        return False
    if parts[:2] in (["jax", "tree"], ["jax", "tree_util"]):
        return False  # containers of leaves; coercion on them is host-side
    if parts[-1] in JAX_STATIC_FUNCS:
        return False
    if parts[0] in ARRAY_NAMESPACES:
        return True
    if len(parts) == 1:
        src = mod.from_imports.get(parts[0])
        return src is not None and src[0].split(".")[0] == "jax" \
            and parts[0] not in JAX_STATIC_FUNCS
    return False


def _is_array_call(node: ast.expr, mod: ModuleInfo) -> bool:
    """Whether ``node`` contains an array-producing call, pruning
    subtrees under static attribute reads (``jnp.sum(x).dtype`` is a
    static value, not a tracer)."""

    def walk(n: ast.AST) -> bool:
        if isinstance(n, ast.Attribute) and n.attr in STATIC_ATTRS:
            return False
        if isinstance(n, ast.Call):
            parts = dotted_name(n.func)
            if parts and parts[-1] in STATIC_CALLS:
                return False
            if _call_is_array(n, mod):
                return True
        return any(walk(c) for c in ast.iter_child_nodes(n))

    return walk(node)


def _numpy_aliases(mod: ModuleInfo) -> set:
    """Local names bound to HOST numpy.  The bare names ``np``/``numpy``
    count only when the module doesn't rebind them to something else —
    ``import jax.numpy as np`` makes ``np.asarray`` a traced no-sync op,
    not a host sync."""
    out = set()
    for local, target in mod.module_aliases.items():
        if target in ("numpy", "np"):
            out.add(local)
    for name in ("np", "numpy"):
        if name not in mod.module_aliases and name not in mod.from_imports:
            out.add(name)
    return out


def _nondet_call(parts: list, mod: ModuleInfo,
                 np_aliases: set) -> Optional[str]:
    """A human message when the dotted callee is a trace-time
    nondeterminism source, else None."""
    root = parts[0]
    if root == "time" and parts[-1] in ("time", "monotonic", "perf_counter",
                                        "time_ns", "monotonic_ns", "sleep"):
        return f"time.{parts[-1]}() is evaluated once at trace time"
    if root == "random":
        # `from jax import random` shadows the stdlib module — not host RNG
        src = mod.from_imports.get("random")
        if src is None or not src[0].startswith("jax"):
            return f"stdlib random.{parts[-1]}() draws host RNG under trace"
    if root in np_aliases and len(parts) >= 2 \
            and parts[1] == "random":
        return "np.random under trace bakes one draw into the program"
    if root == "os" and parts[-1] == "urandom":
        return "os.urandom under trace bakes one draw into the program"
    if root == "uuid":
        return "uuid under trace bakes one value into the program"
    if root == "datetime" and parts[-1] in ("now", "utcnow", "today"):
        return "datetime.now() is evaluated once at trace time"
    if root == "secrets":
        return "secrets under trace bakes one draw into the program"
    return None


def _scalar_annotated(node) -> set:
    """Param names annotated with a host scalar type (``eps: float``) —
    those are static under jit and never tainted."""
    out = set()
    a = node.args
    for p in getattr(a, "posonlyargs", []) + a.args + a.kwonlyargs:
        ann = p.annotation
        if isinstance(ann, ast.Name) and ann.id in ("float", "int", "bool",
                                                    "str", "bytes"):
            out.add(p.arg)
        elif isinstance(ann, ast.Constant) and isinstance(ann.value, str) \
                and ann.value in ("float", "int", "bool", "str"):
            out.add(p.arg)
    return out


class _TracedFuncChecker(ast.NodeVisitor):
    """J001 + J002 over one traced function (nested defs included).

    ``is_entry`` — whether this function is a DIRECT jit/pallas entry: its
    parameters are definitely traced arrays, so host syncs on them fire.
    Transitively-reached helpers often take a mix of tracers and static
    Python values (a ``scale: float``, a config), so there only values
    *derived from array calls* inside the function are flagged — precision
    over recall, the contract that keeps the shipped tree's baseline
    empty of real code."""

    def __init__(self, fi: FuncInfo, findings: list, is_entry: bool = True,
                 static_params: frozenset = frozenset()):
        self.fi = fi
        self.mod = fi.module
        self.findings = findings
        self.np_aliases = _numpy_aliases(self.mod)
        # taint: parameters + anything assigned from tainted/array exprs
        if is_entry:
            self.tainted = (set(fi.params) - {"self", "cls"}
                            - set(static_params)
                            - _scalar_annotated(fi.node))
        else:
            self.tainted = set()
        # derived: definitely-array values (results of jnp/lax/jax calls)
        self.derived: set = set()

    def run(self) -> None:
        node = self.fi.node
        body = node.body if hasattr(node, "body") else []
        if isinstance(body, list):
            for stmt in body:
                self.visit(stmt)

    # -- taint propagation -------------------------------------------------- #

    def _expr_tainted(self, expr: ast.expr) -> bool:
        return bool(_names_in(expr) & (self.tainted | self.derived))

    def _expr_derived(self, expr: ast.expr) -> bool:
        return bool(_names_in(expr) & self.derived) \
            or _is_array_call(expr, self.mod)

    def _bind(self, target: ast.expr, tainted: bool, derived: bool) -> None:
        # structural, NOT ast.walk: `out[i] = jnp.sum(a)` taints the
        # container `out`, never the index `i` (a host loop variable),
        # and `self.x = ...` taints neither `self` nor the chain
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
            if derived:
                self.derived.add(target.id)
            elif not tainted:
                self.derived.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, tainted, derived)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, tainted, derived)
        elif isinstance(target, ast.Subscript):
            base = target.value
            while isinstance(base, (ast.Subscript, ast.Attribute)):
                base = base.value
            # a store into one slot never CLEARS the container's taint
            if isinstance(base, ast.Name):
                if tainted:
                    self.tainted.add(base.id)
                if derived:
                    self.derived.add(base.id)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        t, d = self._expr_tainted(node.value), self._expr_derived(node.value)
        for target in node.targets:
            self._bind(target, t or d, d)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        if self._expr_tainted(node.value) or self._expr_derived(node.value):
            self._bind(node.target, True, self._expr_derived(node.value))

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self.visit(node.value)
            t = self._expr_tainted(node.value)
            d = self._expr_derived(node.value)
            self._bind(node.target, t or d, d)

    def visit_For(self, node: ast.For) -> None:
        if self._expr_tainted(node.iter) or self._expr_derived(node.iter):
            self._bind(node.target, True, self._expr_derived(node.iter))
        self.generic_visit(node)

    def _visit_nested(self, node) -> None:
        # nested defs trace with the parent; their params are fresh taints
        a = node.args
        for p in getattr(a, "posonlyargs", []) + a.args + a.kwonlyargs:
            self.tainted.add(p.arg)
        self.generic_visit(node)

    visit_FunctionDef = _visit_nested
    visit_AsyncFunctionDef = _visit_nested

    # -- checks ------------------------------------------------------------- #

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(
            rule=rule, path=self.mod.rel, line=node.lineno,
            context=self.fi.qualname, snippet=self.mod.snippet(node.lineno),
            message=message))

    def visit_Call(self, node: ast.Call) -> None:
        parts = dotted_name(node.func)
        if parts is not None:
            self._check_host_sync(node, parts)
            msg = _nondet_call(parts, self.mod, self.np_aliases)
            if msg is not None:
                self._emit("PICO-J002", node,
                           f"host nondeterminism under trace: {msg}")
        elif isinstance(node.func, ast.Attribute):
            self._check_method_sync(node, node.func)
        self.generic_visit(node)

    def _sync_arg_hit(self, a: ast.expr) -> bool:
        """Whether a host-sync call's argument is a traced value.  When
        the argument is itself a call, only that call's own result type
        counts — ``bool(typeof_vma(lax.axis_index(...)))`` coerces the
        (static) helper result, not the tracer buried inside it."""
        if isinstance(a, ast.Call):
            return _call_is_array(a, self.mod)
        return self._expr_tainted(a) or self._expr_derived(a)

    def _check_host_sync(self, node: ast.Call, parts: list) -> None:
        name = parts[-1]
        arg_hit = any(self._sync_arg_hit(a) for a in node.args)
        if len(parts) == 1 and name in HOST_SYNC_CASTS and arg_hit:
            self._emit("PICO-J001", node,
                       f"{name}() on a traced value forces a host sync "
                       f"(ConcretizationTypeError under jit)")
        elif len(parts) >= 2 and parts[0] in self.np_aliases \
                and name in ("asarray", "array", "copy") and arg_hit:
            self._emit("PICO-J001", node,
                       f"np.{name}() on a traced value forces a host sync")
        elif len(parts) >= 2 and parts[-2] == "jax" \
                and name == "device_get" and (arg_hit or node.args):
            self._emit("PICO-J001", node,
                       "jax.device_get inside traced code is a host sync")
        elif name in HOST_SYNC_METHODS and len(parts) >= 2:
            recv = {parts[0]}
            if recv & (self.tainted | self.derived):
                self._emit("PICO-J001", node,
                           f".{name}() on a traced value is a host sync")

    def _check_method_sync(self, node: ast.Call, func: ast.Attribute) -> None:
        if func.attr in HOST_SYNC_METHODS and self._expr_tainted(func.value):
            self._emit("PICO-J001", node,
                       f".{func.attr}() on a traced value is a host sync")

    def _check_bool_coercion(self, test: ast.expr, node: ast.AST,
                             kind: str) -> None:
        # identity tests are static under trace (`if cache is not None:`
        # is how optional-arg plumbing looks inside every jitted program)
        if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return
        # only definitely-array values: `if cfg.use_flash:` on a static
        # Python config must not fire, `if jnp.any(bad):` must
        if _names_in(test) & self.derived or _is_array_call(test, self.mod):
            self._emit("PICO-J001", node,
                       f"bool coercion of a traced value in `{kind}` "
                       f"(data-dependent Python control flow under trace)")

    def visit_If(self, node: ast.If) -> None:
        self.visit(node.test)
        self._check_bool_coercion(node.test, node, "if")
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_While(self, node: ast.While) -> None:
        self.visit(node.test)
        self._check_bool_coercion(node.test, node, "while")
        for stmt in node.body + node.orelse:
            self.visit(stmt)


# --------------------------------------------------------------------------- #
# J003: program_id inside loop bodies; J004: jit built in a loop
# --------------------------------------------------------------------------- #


def _loop_body_functions(mod: ModuleInfo) -> list:
    """(body FuncInfo | Lambda, wrapper name) for every function passed as
    a fori_loop/while_loop/scan body in ``mod``."""
    out: list = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        parts = dotted_name(node.func)
        if not parts or parts[-1] not in ("fori_loop", "while_loop", "scan"):
            continue
        for arg in _func_args_of_call(node, parts):
            arg = _unwrap_partial(arg)
            if isinstance(arg, ast.Lambda):
                out.append((arg, parts[-1]))
            elif isinstance(arg, ast.Name):
                qual = enclosing_qualname(mod, node)
                prefix = [] if qual == "<module>" else qual.split(".")
                while True:
                    fi = mod.functions.get(".".join(
                        prefix + ["<locals>", arg.id]) if prefix
                        else arg.id)
                    if fi is None and prefix:
                        fi = mod.functions.get(
                            ".".join(prefix[:-1] + [arg.id]))
                    if fi is not None or not prefix:
                        break
                    prefix = prefix[:-2] if prefix[-1] == "<locals>" \
                        else prefix[:-1]
                if fi is not None:
                    out.append((fi.node, parts[-1]))
    return out


def _check_program_id(project: Project, mod: ModuleInfo,
                      findings: list) -> None:
    for body, wrapper in _loop_body_functions(mod):
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            parts = dotted_name(node.func)
            if parts and parts[-1] == "program_id":
                findings.append(Finding(
                    rule="PICO-J003", path=mod.rel, line=node.lineno,
                    context=enclosing_qualname(mod, node),
                    snippet=mod.snippet(node.lineno),
                    message=f"pl.program_id read inside a {wrapper} body: "
                            f"the 0.4.37 Pallas interpreter cannot resolve "
                            f"it in the sub-jaxpr — read grid ids once, "
                            f"before the loop (docs/ANALYSIS.md#pico-j003)"))


def _check_jit_in_loop(mod: ModuleInfo, findings: list) -> None:
    RECOMPILERS = {("jax", "jit"), ("jax", "pmap")}

    def scan(node: ast.AST, in_loop: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            for child in ast.iter_child_nodes(node):
                scan(child, False)  # a def inside a loop runs per CALL
            return
        if in_loop and isinstance(node, ast.Call):
            parts = dotted_name(node.func)
            hit = parts and (
                (len(parts) >= 2 and (parts[-2], parts[-1])
                 in RECOMPILERS)
                or parts[-1] == "pallas_call")
            if hit:
                findings.append(Finding(
                    rule="PICO-J004", path=mod.rel, line=node.lineno,
                    context=enclosing_qualname(mod, node),
                    snippet=mod.snippet(node.lineno),
                    message=f"{'.'.join(parts)}(...) inside a loop "
                            f"builds a fresh callable per iteration — "
                            f"every call recompiles; hoist and cache "
                            f"it outside the loop"))
        if isinstance(node, (ast.For, ast.AsyncFor)):
            # the iterator expression runs ONCE at loop setup; only the
            # body repeats (and for-else runs once, after)
            scan(node.iter, in_loop)
            scan(node.target, in_loop)
            for stmt in node.body:
                scan(stmt, True)
            for stmt in node.orelse:
                scan(stmt, in_loop)
            return
        if isinstance(node, ast.While):
            scan(node.test, True)  # the test re-evaluates every pass
            for stmt in node.body:
                scan(stmt, True)
            for stmt in node.orelse:
                scan(stmt, in_loop)
            return
        for child in ast.iter_child_nodes(node):
            scan(child, in_loop)

    scan(mod.tree, False)


# --------------------------------------------------------------------------- #
# J005: make_async_copy started without a reachable wait
# --------------------------------------------------------------------------- #


def _outermost_functions(tree: ast.AST) -> list:
    """Module-level functions and class methods, NOT nested defs — a DMA
    kernel's start/wait pairing is analyzed over the whole outermost
    function (helper closures included), so the double-buffer idiom of a
    ``_start`` helper next to a ``_wait`` helper reads as paired."""
    funcs: list = []

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.append(child)
            else:
                walk(child)

    walk(tree)
    return funcs


def _dma_starts_waits(root: ast.AST) -> tuple:
    """``(start_calls, wait_calls)`` on make_async_copy values inside one
    subtree: ``.start()``/``.wait()`` chained directly onto a
    ``make_async_copy(...)`` call, or on a name the subtree binds to one.
    Receiver-typed on purpose — ``thread.start()`` / ``event.wait()`` /
    helper-returned descriptors never match (precision over recall, the
    empty-baseline contract)."""
    names: set = set()
    for node in ast.walk(root):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            parts = dotted_name(node.value.func)
            if parts and parts[-1] == "make_async_copy":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    starts: list = []
    waits: list = []
    for node in ast.walk(root):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("start", "wait")):
            continue
        recv = node.func.value
        hit = isinstance(recv, ast.Name) and recv.id in names
        if not hit and isinstance(recv, ast.Call):
            parts = dotted_name(recv.func)
            hit = bool(parts) and parts[-1] == "make_async_copy"
        if hit:
            (starts if node.func.attr == "start" else waits).append(node)
    return starts, waits


def _check_dma_waits(mod: ModuleInfo, findings: list) -> None:
    """PICO-J005, two layers:

    (a) an outermost function whose subtree starts DMAs but never waits
        on any — the copy is still in flight when its buffer is read;
    (b) a ``fori_loop``/``while_loop``/``scan`` body that starts DMAs
        per iteration while every wait sits OUTSIDE the loop path — N
        starts against the wait discipline of 1, the semaphore-imbalance
        hazard double buffering introduces (a warm-up start outside the
        loop with the waits inside is the CORRECT pipelined shape and
        stays silent).
    """
    flagged: set = set()

    def emit(node: ast.AST, detail: str) -> None:
        if id(node) in flagged:
            return
        flagged.add(id(node))
        findings.append(Finding(
            rule="PICO-J005", path=mod.rel, line=node.lineno,
            context=enclosing_qualname(mod, node),
            snippet=mod.snippet(node.lineno),
            message=f"make_async_copy started {detail} — pair every "
                    f"start with a wait built from the same (src, dst, "
                    f"sem) triple on the same control path "
                    f"(docs/ANALYSIS.md#pico-j005)"))

    for fn in _outermost_functions(mod.tree):
        starts, waits = _dma_starts_waits(fn)
        if starts and not waits:
            for s in starts:
                emit(s, "with no .wait() anywhere in the enclosing "
                        "function: the DMA may still be in flight when "
                        "its destination buffer is read")
    for body, wrapper in _loop_body_functions(mod):
        bstarts, bwaits = _dma_starts_waits(body)
        if bstarts and not bwaits:
            for s in bstarts:
                emit(s, f"inside a {wrapper} body whose every .wait() "
                        f"sits outside the loop: one wait cannot "
                        f"discharge N per-iteration starts")


_PROGRAM_ATTR_SUFFIXES = ("_jit", "_prog")


def _is_program_call(call: ast.Call) -> bool:
    """``self._<family>_jit(params, ...)`` / ``self._<family>_prog(
    params, ...)`` — a compiled MODEL program dispatch.  The ``params``
    first operand is the discriminator: housekeeping programs
    (``_set_length_jit``, ``_release_jit``, ...) take the cache (or
    nothing) first and may run outside the fault wrapper.  ``_make_*``
    builders construct programs rather than dispatch them."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
            and f.value.id == "self"):
        return False
    name = f.attr
    if not name.startswith("_") or name.startswith("_make_"):
        return False
    if not name.endswith(_PROGRAM_ATTR_SUFFIXES):
        return False
    if not call.args:
        return False
    first = call.args[0]
    return (isinstance(first, ast.Name)
            and (first.id == "params" or first.id.endswith("_params")))


def _check_dispatch_routing(mod: ModuleInfo, findings: list) -> None:
    """PICO-J006: in a class that defines ``_dispatch`` (the retry /
    flash-fallback fault wrapper), every compiled model-program call
    (``self._*_jit(params, ...)``) must occur inside an argument of a
    ``self._dispatch(...)`` call — usually ``self._dispatch(lambda:
    self._x_jit(params, ...))``.  A direct call opts that program family
    out of the engine's fault semantics; nothing else re-dispatches it
    after a flash->dense rebuild."""
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = [n for n in cls.body if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        if not any(m.name == "_dispatch" for m in methods):
            continue
        routed: set = set()
        for node in ast.walk(cls):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "_dispatch"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                for arg in list(node.args) + [k.value for k in
                                              node.keywords]:
                    routed.update(id(n) for n in ast.walk(arg))
        for m in methods:
            if m.name == "_dispatch":
                continue  # the wrapper itself runs the routed callable
            for node in ast.walk(m):
                if (isinstance(node, ast.Call) and _is_program_call(node)
                        and id(node) not in routed):
                    findings.append(Finding(
                        rule="PICO-J006", path=mod.rel, line=node.lineno,
                        context=enclosing_qualname(mod, node),
                        snippet=mod.snippet(node.lineno),
                        message=f"compiled model program "
                                f"self.{node.func.attr}(params, ...) "
                                f"called outside self._dispatch — wrap "
                                f"it as self._dispatch(lambda: ...) so "
                                f"the family inherits retry/fallback "
                                f"fault semantics "
                                f"(docs/ANALYSIS.md#pico-j006)"))


# --------------------------------------------------------------------------- #
# driver
# --------------------------------------------------------------------------- #


def analyze(project: Project) -> list:
    findings: list = []
    traced, direct = traced_functions(project)
    analyzed: set = set()
    for modname, qual in sorted(traced):
        mod = project.modules[modname]
        fi = mod.functions.get(qual)
        if fi is None:
            continue
        # nested defs are visited by their parent's checker; don't run a
        # second, parent-less pass over them
        parent = qual.split(".<locals>.")[0]
        if parent != qual and (modname, parent) in traced:
            continue
        if (modname, qual) in analyzed:
            continue
        analyzed.add((modname, qual))
        _TracedFuncChecker(fi, findings,
                           is_entry=(modname, qual) in direct).run()
    for mod in project.modules.values():
        _check_program_id(project, mod, findings)
        _check_jit_in_loop(mod, findings)
        _check_dma_waits(mod, findings)
        _check_dispatch_routing(mod, findings)
    return findings
