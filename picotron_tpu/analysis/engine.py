"""picolint suite driver: run the analyzers, apply suppressions, diff the
baseline, format output.

The baseline (``analysis/baseline.json``) is the contract that makes the
suite enforceable in tier-1 **today** without blocking on a perfectly
clean history: only findings *not* in the baseline fail the run.  Policy
(docs/ANALYSIS.md): every true positive gets **fixed**, never baselined;
a baseline entry is only for a documented false positive and must carry a
non-empty ``reason``.  Entries match findings by fingerprint
(rule + path + enclosing qualname + normalized source line), so ordinary
edits elsewhere in the file don't invalidate them — but editing the
flagged line itself re-opens the finding, which is the point.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from picotron_tpu.analysis import concurrency_rules, jax_rules
from picotron_tpu.analysis.callgraph import load_project
from picotron_tpu.analysis.findings import RULES, Finding, _norm

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def run_suite(root: str, files: Optional[list] = None) -> list:
    """All findings (suppression comments already applied), sorted by
    (path, line, rule).  ``root`` is the directory containing the code to
    scan — for the self-scan, the repo root with files limited to
    ``picotron_tpu/``."""
    project = load_project(root, files)
    findings = jax_rules.analyze(project) + concurrency_rules.analyze(project)
    out = []
    for f in findings:
        mod = next((m for m in project.modules.values() if m.rel == f.path),
                   None)
        if mod is not None and mod.suppressions.silences(f):
            continue
        out.append(f)
    # dedup exact duplicates (a nested def reachable two ways, etc.)
    seen: set = set()
    uniq = []
    for f in sorted(out, key=Finding.sort_key):
        key = (f.rule, f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            uniq.append(f)
    return uniq


# --------------------------------------------------------------------------- #
# baseline
# --------------------------------------------------------------------------- #


def load_baseline(path: str) -> list:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        entries = data.get("findings")
        if not isinstance(entries, list):
            raise ValueError(
                f"baseline {path}: expected a {{'findings': [...]}} "
                f"object (keys: {sorted(data)})")
    elif isinstance(data, list):
        entries = data
    else:
        raise ValueError(
            f"baseline {path}: expected an object or a list, "
            f"got {type(data).__name__}")
    for e in entries:
        for key in ("rule", "path", "context", "snippet"):
            if key not in e:
                raise ValueError(
                    f"baseline entry missing {key!r}: {e}")
    return entries


def entry_fingerprint(e: dict) -> tuple:
    return (e["rule"], e["path"], e["context"], _norm(e["snippet"]))


def diff_baseline(findings: list, baseline: list,
                  scanned_paths: Optional[set] = None) -> tuple:
    """(new_findings, matched_findings, stale_entries).  Fingerprints are
    counted, not just set-matched: two identical new findings against one
    baseline entry leave one of them new.  ``scanned_paths`` (rel paths)
    limits STALE detection to files the scan actually covered — a
    partial scan not firing on an unscanned file is no evidence its
    entry is dead."""
    budget: dict = {}
    for e in baseline:
        budget[entry_fingerprint(e)] = budget.get(entry_fingerprint(e), 0) + 1
    new, matched = [], []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            matched.append(f)
        else:
            new.append(f)
    stale = []
    for e in baseline:
        if scanned_paths is not None and e["path"] not in scanned_paths:
            continue
        fp = entry_fingerprint(e)
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            stale.append(e)
    return new, matched, stale


def undocumented_entries(baseline: list) -> list:
    """Baseline entries whose ``reason`` is empty or a placeholder — the
    self-scan test turns these into failures (the baseline is for
    *documented* false positives only)."""
    bad = []
    for e in baseline:
        reason = str(e.get("reason", "")).strip()
        if not reason or reason.upper().startswith(("TODO", "FIXME")):
            bad.append(e)
    return bad


def baseline_entry(f: Finding, reason: str = "") -> dict:
    return {"rule": f.rule, "path": f.path, "context": f.context,
            "snippet": f.snippet, "reason": reason}


# --------------------------------------------------------------------------- #
# reporting
# --------------------------------------------------------------------------- #


def report_json(findings: list, new: list, matched: list, stale: list,
                elapsed_s: float) -> dict:
    return {
        "tool": "picolint",
        "rules": {rid: {"title": r.title, "rationale": r.rationale}
                  for rid, r in sorted(RULES.items())},
        "elapsed_s": round(elapsed_s, 3),
        "counts": {"total": len(findings), "new": len(new),
                   "baselined": len(matched), "stale_baseline": len(stale)},
        "findings": [f.to_dict() for f in findings],
        "new": [f.to_dict() for f in new],
        "stale_baseline": stale,
    }


def report_text(findings: list, new: list, matched: list, stale: list,
                elapsed_s: float) -> str:
    lines = []
    new_set = {id(f) for f in new}
    for f in findings:
        tag = "NEW " if id(f) in new_set else "base"
        lines.append(f"[{tag}] {f.render()}")
    for e in stale:
        lines.append(f"[stale baseline] {e['rule']} {e['path']} "
                     f"[{e['context']}] — no longer fires; remove the entry")
    lines.append(
        f"picolint: {len(findings)} finding(s) — {len(new)} new, "
        f"{len(matched)} baselined, {len(stale)} stale baseline "
        f"entr{'y' if len(stale) == 1 else 'ies'} ({elapsed_s:.2f}s)")
    return "\n".join(lines)


def run(root: str, files: Optional[list] = None,
        baseline_path: str = DEFAULT_BASELINE) -> dict:
    """One-call API for tests and the CLI: scan + baseline diff.
    Returns the ``report_json`` dict plus the raw finding lists under
    private keys."""
    t0 = time.monotonic()
    findings = run_suite(root, files)
    baseline = load_baseline(baseline_path)
    scanned = None
    if files is not None:
        absroot = os.path.abspath(root)
        scanned = {os.path.relpath(os.path.abspath(f), absroot)
                   .replace(os.sep, "/") for f in files}
    new, matched, stale = diff_baseline(findings, baseline, scanned)
    out = report_json(findings, new, matched, stale,
                      time.monotonic() - t0)
    out["_findings"], out["_new"], out["_stale"] = findings, new, stale
    out["_matched"] = matched
    out["_baseline"] = baseline
    return out
