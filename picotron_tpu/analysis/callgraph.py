"""Project model for picolint: parsed sources, symbol tables, call resolution.

Pure ``ast`` — scanning never imports the scanned code (so linting stays
fast, side-effect free, and runnable on files whose dependencies are
absent).  The model is deliberately shallow where Python is dynamic:

- functions are registered by qualname (``Class.method``,
  ``func.<locals>.inner``);
- imports are resolved only far enough to follow **intra-project** calls
  (``from picotron_tpu.models import llama; llama.decoder_layer(...)``);
  calls into third-party code are opaque;
- ``self.method()`` resolves within the lexically enclosing class.

That is exactly the precision the analyzers need: the JAX analyzer walks
the intra-package call graph from jitted entry points, the concurrency
analyzer follows same-class/method calls while tracking held locks.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Iterable, Optional

from picotron_tpu.analysis.findings import Suppressions


@dataclass
class FuncInfo:
    """One function/method definition (including nested defs)."""

    qualname: str  # e.g. "FrontEnd.submit", "f.<locals>.body"
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    module: "ModuleInfo"
    class_name: Optional[str] = None  # enclosing class, if a method

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]

    @property
    def params(self) -> list:
        a = self.node.args
        names = [p.arg for p in
                 getattr(a, "posonlyargs", []) + a.args + a.kwonlyargs]
        for extra in (a.vararg, a.kwarg):
            if extra is not None:
                names.append(extra.arg)
        return names


@dataclass
class ModuleInfo:
    """One parsed source file with its local symbol tables."""

    modname: str  # dotted, scan-root-relative ("picotron_tpu.tools.serve")
    rel: str  # posix relative path ("picotron_tpu/tools/serve.py")
    path: str
    tree: ast.Module
    lines: list
    suppressions: Suppressions
    functions: dict = field(default_factory=dict)  # qualname -> FuncInfo
    # local name -> dotted module it aliases ("llama" -> "...models.llama")
    module_aliases: dict = field(default_factory=dict)
    # local name -> (dotted module, attr) for `from mod import attr`
    from_imports: dict = field(default_factory=dict)

    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


def _index_module(mod: ModuleInfo) -> None:
    """Fill ``functions``/``module_aliases``/``from_imports`` for one file."""

    def walk(node: ast.AST, prefix: str, class_name: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                mod.functions[qual] = FuncInfo(qual, child, mod, class_name)
                walk(child, f"{qual}.<locals>.", class_name)
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.", child.name)
            else:
                walk(child, prefix, class_name)

    walk(mod.tree, "", None)

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                # `import a.b.c` binds `a`; `import a.b.c as x` binds x->a.b.c
                target = alias.name if alias.asname else alias.name.split(".")[0]
                mod.module_aliases[local] = target
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:  # relative import: anchor at this module's package
                pkg_parts = mod.modname.split(".")[: -node.level]
                base = ".".join(pkg_parts + ([node.module]
                                             if node.module else []))
            for alias in node.names:
                local = alias.asname or alias.name
                mod.from_imports[local] = (base, alias.name)


class Project:
    """All scanned modules plus cross-module call resolution."""

    def __init__(self, modules: Iterable[ModuleInfo]):
        self.modules = {m.modname: m for m in modules}

    # -- lookups ----------------------------------------------------------- #

    def module_for(self, dotted: str) -> Optional[ModuleInfo]:
        return self.modules.get(dotted)

    def resolve_name(self, mod: ModuleInfo,
                     name: str) -> Optional[FuncInfo]:
        """A bare name used as a callable: module-level def, or a
        ``from <project module> import f``."""
        fi = mod.functions.get(name)
        if fi is not None and fi.class_name is None and "." not in name:
            return fi
        if name in mod.from_imports:
            src_mod, attr = mod.from_imports[name]
            target = self.module_for(src_mod)
            if target is not None:
                return target.functions.get(attr)
        return None

    def resolve_call(self, mod: ModuleInfo, call: ast.Call,
                     self_class: Optional[str] = None) -> Optional[FuncInfo]:
        """Resolve a call's target to a scanned FuncInfo where possible:
        bare names, ``module.func`` through project imports, and
        ``self.method`` within ``self_class``."""
        return self.resolve_callee_expr(mod, call.func, self_class)

    def resolve_callee_expr(self, mod: ModuleInfo, func: ast.expr,
                            self_class: Optional[str] = None
                            ) -> Optional[FuncInfo]:
        if isinstance(func, ast.Name):
            return self.resolve_name(mod, func.id)
        if isinstance(func, ast.Attribute):
            value = func.value
            if (isinstance(value, ast.Name) and value.id == "self"
                    and self_class):
                return mod.functions.get(f"{self_class}.{func.attr}")
            dotted = dotted_name(func)
            if dotted is None:
                return None
            root, rest = dotted[0], dotted[1:]
            # alias for a scanned module (import picotron_tpu.x as y)
            target_mod = mod.module_aliases.get(root)
            if target_mod is None and root in mod.from_imports:
                src, attr = mod.from_imports[root]
                if self.module_for(f"{src}.{attr}") is not None:
                    target_mod = f"{src}.{attr}"
            if target_mod is None:
                return None
            # longest scanned-module prefix wins: with package __init__
            # files in the scan, `pkg` AND `pkg.sub.mod` are both modules,
            # and `pkg.sub.mod.f()` must resolve f in the deepest one
            for i in range(len(rest) - 1, -1, -1):
                target = self.module_for(".".join([target_mod] + rest[:i]))
                if target is not None:
                    remaining = rest[i:]
                    if len(remaining) == 1:
                        return target.functions.get(remaining[0])
                    return None  # attribute chain past a function: opaque
        return None


def dotted_name(node: ast.expr) -> Optional[list]:
    """``a.b.c`` -> ["a", "b", "c"]; None for non-name chains."""
    parts: list = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def enclosing_qualname(mod: ModuleInfo, target: ast.AST) -> str:
    """Qualname of the innermost function containing ``target`` (by line
    span), or "<module>"."""
    best = None
    for fi in mod.functions.values():
        node = fi.node
        if (getattr(node, "lineno", 1 << 30) <= target.lineno
                <= getattr(node, "end_lineno", -1)):
            if best is None or node.lineno > best.node.lineno:
                best = fi
    return best.qualname if best is not None else "<module>"


# --------------------------------------------------------------------------- #
# loading
# --------------------------------------------------------------------------- #


_PRUNE_DIRS = ("__pycache__", ".git", "_build")


def iter_python_files(root: str) -> list:
    """Every ``.py`` under ``root`` (sorted, ``_PRUNE_DIRS`` skipped) —
    the one file walk shared by the engine and the CLI, so the prune
    list cannot drift between them."""
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames) if d not in _PRUNE_DIRS]
        out += [os.path.join(dirpath, fn) for fn in sorted(filenames)
                if fn.endswith(".py")]
    return out


def load_project(root: str, files: Optional[list] = None) -> Project:
    """Parse every ``.py`` under ``root`` (or just ``files``) into a
    Project.  ``root`` should be the directory CONTAINING the package so
    module names come out fully dotted (``picotron_tpu.tools.serve``)."""
    root = os.path.abspath(root)
    if files is not None:
        # an explicit-but-empty list means "scan nothing" (the caller
        # resolved a scope with no .py files), NOT "fall back to root"
        paths = [os.path.abspath(f) for f in files]
    else:
        paths = iter_python_files(root)
    modules = []
    for path in paths:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
            tree = ast.parse(text, filename=path)
        except (OSError, SyntaxError):
            # unparseable files are someone else's problem (and a broken
            # scan must not mask every OTHER file's findings)
            continue
        modname = rel[:-3].replace("/", ".")
        if modname.endswith(".__init__"):
            modname = modname[: -len(".__init__")]
        mod = ModuleInfo(
            modname=modname, rel=rel, path=path, tree=tree,
            lines=text.splitlines(),
            suppressions=Suppressions.parse(text))
        _index_module(mod)
        modules.append(mod)
    return Project(modules)
