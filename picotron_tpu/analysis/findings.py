"""Finding model + rule catalog + suppression parsing for picolint.

Every analyzer emits ``Finding`` records tagged with a rule ID from
``RULES``.  IDs are stable API: they appear in baseline entries
(``analysis/baseline.json``), suppression comments
(``# picolint: disable=PICO-J001``), docs (docs/ANALYSIS.md), and in code
comments that cross-link a hazard to the rule enforcing it (e.g.
``ops/pallas/decode_attention.py`` ↔ PICO-J003).  Never renumber a rule;
retire IDs instead.

Baselines match findings by **fingerprint** — (rule, path, context,
snippet) — not by line number, so unrelated edits above a baselined
finding don't invalidate the baseline.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class Rule:
    id: str
    title: str
    rationale: str  # one line; the full story lives in docs/ANALYSIS.md


# The catalog. J = JAX hot-path rules, C = host-concurrency rules.
RULES = {
    r.id: r
    for r in [
        Rule(
            "PICO-J001",
            "host sync on a traced value",
            "float()/int()/bool()/.item()/np.asarray/jax.device_get on a "
            "traced value inside jit-reachable code forces a device->host "
            "transfer (or a ConcretizationTypeError) on the hot path",
        ),
        Rule(
            "PICO-J002",
            "host nondeterminism under trace",
            "time.*/random.*/np.random.*/uuid/datetime calls inside "
            "jit-reachable code are evaluated ONCE at trace time and baked "
            "into the compiled program — silently stale and nondeterministic "
            "across recompiles",
        ),
        Rule(
            "PICO-J003",
            "pl.program_id read inside a loop body",
            "the jax 0.4.37 Pallas interpreter cannot resolve pl.program_id "
            "inside a fori_loop/while_loop/scan body's sub-jaxpr; read grid "
            "ids once, outside the loop (the decode_attention.py incident)",
        ),
        Rule(
            "PICO-J004",
            "jit/pallas_call constructed inside a loop",
            "jax.jit/jax.pmap/pl.pallas_call evaluated in a loop body builds "
            "a fresh callable per iteration — every call recompiles unless "
            "the result is cached outside the loop",
        ),
        Rule(
            "PICO-J005",
            "make_async_copy started without a reachable wait",
            "a pltpu.make_async_copy whose .start() has no matching "
            ".wait() in scope — or whose per-iteration start inside a "
            "fori_loop body has its only wait outside that loop path — "
            "leaves DMAs in flight while compute reads the buffer (or "
            "imbalances the semaphore), the exact hazard double-buffered "
            "pipelining introduces",
        ),
        Rule(
            "PICO-J006",
            "model program dispatched outside _dispatch",
            "a compiled model program (a self._*_jit/_prog attribute "
            "called with params as its first operand) invoked outside "
            "self._dispatch(lambda: ...) skips the retry / flash-fallback "
            "fault wrapper every engine program family must inherit",
        ),
        Rule(
            "PICO-C001",
            "lock-order inversion",
            "two locks acquired in opposite orders on different code paths "
            "deadlock the first time the paths interleave (the PR 6 "
            "_next_uid-under-_mu incident class)",
        ),
        Rule(
            "PICO-C002",
            "blocking call while holding a lock",
            "sleep/join/subprocess/file-I/O/unbounded queue ops under a lock "
            "stall every thread contending for it — the serving admission "
            "path sheds on a 10s bound precisely because of this class",
        ),
        Rule(
            "PICO-C003",
            "guarded attribute mutated outside its lock",
            "an attribute mutated under a lock in one method and without it "
            "in another loses updates or tears reads the moment two threads "
            "interleave (the serve.py rejection-counter incident)",
        ),
        Rule(
            "PICO-C004",
            "cross-thread mutation with no lock",
            "an attribute mutated both by a background-thread method and by "
            "foreground methods with no lock anywhere has no ordering at "
            "all (the checkpoint.py mirror-error-list incident)",
        ),
    ]
}


@dataclass(frozen=True)
class Finding:
    """One analyzer hit, anchored to a source line.

    ``context`` is the enclosing qualname (``Class.method``, ``func``,
    ``func.<locals>.body``, or ``<module>``); ``snippet`` is the stripped
    source line.  Both feed the baseline fingerprint so line drift above
    the finding does not break the match.
    """

    rule: str
    path: str  # scan-root-relative, posix separators
    line: int
    context: str
    snippet: str
    message: str

    def fingerprint(self) -> tuple:
        return (self.rule, self.path, self.context, _norm(self.snippet))

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "title": RULES[self.rule].title if self.rule in RULES else "",
            "path": self.path,
            "line": self.line,
            "context": self.context,
            "snippet": self.snippet,
            "message": self.message,
        }

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} [{self.context}] "
                f"{self.message}")

    def sort_key(self) -> tuple:
        return (self.path, self.line, self.rule)


def _norm(s: str) -> str:
    return " ".join(s.split())


# --------------------------------------------------------------------------- #
# suppression comments
# --------------------------------------------------------------------------- #

# `# picolint: disable=PICO-J001[,PICO-C002|all]` on the flagged line
# silences those rules for that line; `disable-file=` anywhere silences
# them for the whole file.  The bare rule suffix ("J001") is accepted
# too.  The capture stops at the first token that isn't part of a
# comma-separated rule list, so trailing prose
# (`# picolint: disable=PICO-J002 — intended, see docs`) still suppresses.
_SUPPRESS_RE = re.compile(
    r"#\s*picolint:\s*(disable(?:-file)?)\s*=\s*"
    r"([A-Za-z0-9_\-*]+(?:\s*,\s*[A-Za-z0-9_\-*]+)*)")


def _canon(rule: str) -> str:
    rule = rule.strip().upper()
    if not rule:
        return ""
    if rule in ("ALL", "*"):
        return "*"
    if not rule.startswith("PICO-"):
        rule = "PICO-" + rule
    return rule


@dataclass
class Suppressions:
    """Per-file suppression table, parsed once from the raw source text."""

    by_line: dict = field(default_factory=dict)  # line -> set of rule ids/"*"
    whole_file: set = field(default_factory=set)

    @classmethod
    def parse(cls, text: str) -> "Suppressions":
        sup = cls()
        for lineno, line in enumerate(text.splitlines(), start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {_canon(r) for r in m.group(2).split(",")} - {""}
            if m.group(1) == "disable-file":
                sup.whole_file |= rules
            else:
                sup.by_line.setdefault(lineno, set()).update(rules)
        return sup

    def silences(self, finding: Finding) -> bool:
        for scope in (self.whole_file, self.by_line.get(finding.line, ())):
            if "*" in scope or finding.rule in scope:
                return True
        return False


def validate_rule_ids(ids) -> Optional[str]:
    """The first unknown rule ID in ``ids``, or None when all are known."""
    for r in ids:
        if r != "*" and r not in RULES:
            return r
    return None
