"""Host-concurrency analyzer: PICO-C001..C004.

Targets the threaded host subsystems (``tools/serve.py``, the
``checkpoint.py`` mirror worker, ``resilience/cluster.py``,
``resilience/preemption.py``, ``inference/batcher.py`` under the serve
front end).  Per class, the analyzer:

1. identifies **locks** (attributes assigned ``threading.Lock()`` /
   ``RLock`` / ``Condition`` / ``Semaphore``, module-level equivalents,
   plus name-pattern fallbacks like ``_mu``/``*_lock``) and walks every
   method tracking the *held set* through ``with lock:`` nesting and the
   ``acquire(timeout=...)`` / ``release()`` idiom;
2. builds a **lock-acquisition graph** — an edge A→B wherever B is
   acquired (directly or through a same-class/module call) while A is
   held — and reports cycles (PICO-C001);
3. reports **blocking calls under a lock** (PICO-C002): ``time.sleep``,
   ``.join()``, subprocess/os.system, file I/O (``open``, ``shutil.*``,
   ``os.rename``...), network clients, timeout-less ``.wait()``, and
   timeout-less queue ``.get()``;
4. tracks **attribute mutations vs the held set**: an attribute mutated
   under a lock in one place and without it in another is PICO-C003; an
   attribute mutated both by background-thread methods
   (``threading.Thread(target=self.m)`` closure) and by foreground
   methods with no lock at all is PICO-C004.

Thread-safe channel objects (``queue.Queue``, ``threading.Event``,
locks themselves) are exempt from the mutation rules — they are the
sanctioned way to share state.  Construction in ``__init__`` and the
thread-starting method are exempt too (happens-before ``Thread.start``).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Optional

from picotron_tpu.analysis.callgraph import (
    ModuleInfo, Project, dotted_name)
from picotron_tpu.analysis.findings import Finding

LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
THREADSAFE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
                    "Event", "deque"} | LOCK_CTORS
_LOCKISH_NAME = re.compile(r"(^|_)(mu|mutex|lock|cond|sem)\d*$")
# collection methods that mutate their receiver
MUTATORS = {"append", "appendleft", "extend", "insert", "pop", "popleft",
            "remove", "clear", "update", "add", "discard", "setdefault"}
_QUEUEISH_NAME = re.compile(r"(^|_)(q|queue|events|inbox|outbox)\d*$",
                            re.IGNORECASE)


def _ctor_name(value: ast.expr) -> Optional[str]:
    if isinstance(value, ast.Call):
        parts = dotted_name(value.func)
        if parts:
            return parts[-1]
    return None


@dataclass
class MethodSummary:
    name: str
    acquires: list = field(default_factory=list)  # (lock, held_before, line)
    blocking: list = field(default_factory=list)  # (desc, held, line)
    mutations: list = field(default_factory=list)  # (attr, held, line)
    calls: list = field(default_factory=list)  # (callee_name, held, line)
    thread_targets: list = field(default_factory=list)  # self-method names


class _MethodWalker:
    """Walk one method body tracking the held-lock set statement by
    statement.  Deliberately linear: loops are walked once, ``try`` bodies
    with their entry held set, ``finally`` releases applied in order."""

    def __init__(self, owner: "_ClassScan", method: str):
        self.o = owner
        self.sum = MethodSummary(method)

    # -- lock identity ------------------------------------------------------ #

    def _lock_id(self, expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            name = expr.attr
            if name in self.o.lock_attrs or _LOCKISH_NAME.search(name):
                return f"{self.o.class_name}.{name}"
        elif isinstance(expr, ast.Name):
            name = expr.id
            if name in self.o.module_locks or _LOCKISH_NAME.search(name):
                return f"<module>.{name}"
        return None

    # -- statement walk ----------------------------------------------------- #

    def walk(self, stmts: list, held: frozenset) -> frozenset:
        for stmt in stmts:
            held = self._stmt(stmt, held)
        return held

    def _stmt(self, stmt: ast.stmt, held: frozenset) -> frozenset:
        if isinstance(stmt, ast.With):
            return self._with(stmt, held)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs when called, not here; scan it with a
            # fresh held set under the same method context
            self.walk(stmt.body, frozenset())
            return held
        if isinstance(stmt, ast.If):
            return self._if(stmt, held)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr_events(stmt.iter, held)
            self.walk(stmt.body, held)
            self.walk(stmt.orelse, held)
            return held
        if isinstance(stmt, ast.While):
            self._expr_events(stmt.test, held)
            self.walk(stmt.body, held)
            self.walk(stmt.orelse, held)
            return held
        if isinstance(stmt, ast.Try):
            inner = self.walk(stmt.body, held)
            for h in stmt.handlers:
                self.walk(h.body, held)
            inner = self.walk(stmt.orelse, inner)
            return self.walk(stmt.finalbody, inner)
        if isinstance(stmt, (ast.ClassDef,)):
            return held
        # simple statement: record events, then apply acquire/release
        self._expr_events(stmt, held)
        return self._apply_acq_rel(stmt, held)

    def _with(self, stmt: ast.With, held: frozenset) -> frozenset:
        locks = []
        for item in stmt.items:
            lid = self._lock_id(item.context_expr)
            if lid is not None:
                locks.append(lid)
                self.sum.acquires.append((lid, held | frozenset(locks[:-1]),
                                          item.context_expr.lineno))
            else:
                self._expr_events(item.context_expr, held)
        inner = held | frozenset(locks)
        self.walk(stmt.body, inner)
        return held

    def _if(self, stmt: ast.If, held: frozenset) -> frozenset:
        self._expr_events(stmt.test, held)
        acq = self._acquire_in(stmt.test)
        if acq is not None:
            lid, negated = acq
            self.sum.acquires.append((lid, held, stmt.test.lineno))
            if negated:
                # `if not X.acquire(...): <shed/raise>` — the lock is held
                # from the statement AFTER the if on the success path
                self.walk(stmt.body, held)
                self.walk(stmt.orelse, held | {lid})
                return held | {lid}
            # `if X.acquire(...): <locked body>`
            self.walk(stmt.body, held | {lid})
            self.walk(stmt.orelse, held)
            return held
        self.walk(stmt.body, held)
        self.walk(stmt.orelse, held)
        return held

    def _acquire_in(self, test: ast.expr) -> Optional[tuple]:
        negated = False
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            negated, test = True, test.operand
        if isinstance(test, ast.Call) and \
                isinstance(test.func, ast.Attribute) \
                and test.func.attr == "acquire":
            lid = self._lock_id(test.func.value)
            if lid is not None:
                return lid, negated
        return None

    def _apply_acq_rel(self, stmt: ast.stmt, held: frozenset) -> frozenset:
        for node in ast.walk(stmt):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            lid = self._lock_id(node.func.value)
            if lid is None:
                continue
            if node.func.attr == "acquire":
                self.sum.acquires.append((lid, held, node.lineno))
                held = held | {lid}
            elif node.func.attr == "release":
                held = held - {lid}
        return held

    # -- events inside one statement/expression ----------------------------- #

    def _expr_events(self, node: ast.AST, held: frozenset) -> None:
        self._record_mutations(node, held)
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            if isinstance(sub, ast.Call):
                self._record_call(sub, held)

    def _record_mutations(self, node: ast.AST, held: frozenset) -> None:
        def attr_of_target(t: ast.expr) -> Optional[str]:
            while isinstance(t, ast.Subscript):
                t = t.value
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                return t.attr
            return None

        targets: list = []
        if isinstance(node, ast.Assign):
            for t in node.targets:
                targets.extend(t.elts if isinstance(t, (ast.Tuple, ast.List))
                               else [t])
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets.append(node.target)
        for t in targets:
            attr = attr_of_target(t)
            if attr is not None and not self.o.is_threadsafe_attr(attr):
                self.sum.mutations.append((attr, held, t.lineno))
        # mutating method calls: self.X.append(...) etc.
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in MUTATORS:
                recv = sub.func.value
                if isinstance(recv, ast.Attribute) and \
                        isinstance(recv.value, ast.Name) \
                        and recv.value.id == "self" \
                        and not self.o.is_threadsafe_attr(recv.attr):
                    self.sum.mutations.append((recv.attr, held, sub.lineno))

    def _record_call(self, call: ast.Call, held: frozenset) -> None:
        func = call.func
        # threading.Thread(target=self.m) — remember the thread entry
        parts = dotted_name(func)
        if parts and parts[-1] == "Thread":
            for kw in call.keywords:
                if kw.arg == "target" and isinstance(kw.value, ast.Attribute)\
                        and isinstance(kw.value.value, ast.Name) \
                        and kw.value.value.id == "self":
                    self.sum.thread_targets.append(kw.value.attr)
        # same-class call for the lock/blocking propagation
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and func.value.id == "self":
            self.sum.calls.append((func.attr, held, call.lineno))
        desc = self._blocking_desc(call, parts)
        if desc is not None:
            # recorded with an empty held set too: the one-hop propagation
            # needs to see a lock-free callee's blocking calls
            self.sum.blocking.append((desc, held, call.lineno))

    def _blocking_desc(self, call: ast.Call,
                       parts: Optional[list]) -> Optional[str]:
        kwargs = {kw.arg for kw in call.keywords}
        if parts:
            root, leaf = parts[0], parts[-1]
            if root == "time" and leaf == "sleep":
                return "time.sleep"
            if root == "subprocess" or (root, leaf) == ("os", "system"):
                return ".".join(parts)
            if root == "shutil":
                return ".".join(parts)
            if root == "os" and leaf in ("rename", "replace", "remove",
                                         "unlink", "makedirs", "rmdir",
                                         "listdir", "getmtime", "stat"):
                return ".".join(parts)
            if root in ("requests", "urllib", "socket"):
                return ".".join(parts)
            if len(parts) == 1 and leaf == "open":
                return "open()"
        func = call.func
        if isinstance(func, ast.Attribute):
            recv = func.value
            recv_name = (recv.attr if isinstance(recv, ast.Attribute)
                         else recv.id if isinstance(recv, ast.Name) else "")
            # thread/queue joins take no positional arg (or one numeric
            # timeout); str.join always takes exactly one iterable —
            # `sep.join(parts)` under a lock is string building, not a
            # blocking wait
            threadish_args = (not call.args or (
                len(call.args) == 1
                and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, (int, float))))
            if func.attr == "join" and threadish_args \
                    and not isinstance(recv, ast.Constant) \
                    and not (parts and len(parts) >= 2
                             and parts[-2] == "path"):
                return f"{recv_name or '<expr>'}.join"
            if func.attr == "wait" and "timeout" not in kwargs \
                    and not call.args and self._lock_id(recv) is None:
                return f"{recv_name or '<expr>'}.wait() without timeout"
            if func.attr == "get" and not call.args \
                    and "timeout" not in kwargs \
                    and _QUEUEISH_NAME.search(recv_name or ""):
                return f"{recv_name}.get() without timeout"
        return None


@dataclass
class _ClassScan:
    module: ModuleInfo
    class_name: str
    node: ast.ClassDef
    lock_attrs: set = field(default_factory=set)
    threadsafe_attrs: set = field(default_factory=set)
    module_locks: set = field(default_factory=set)
    methods: dict = field(default_factory=dict)  # name -> MethodSummary

    def is_threadsafe_attr(self, attr: str) -> bool:
        return attr in self.threadsafe_attrs or attr in self.lock_attrs \
            or bool(_LOCKISH_NAME.search(attr))

    def scan(self) -> None:
        # pass 1: classify attributes from `self.X = <ctor>()` assignments
        for sub in ast.walk(self.node):
            if not isinstance(sub, ast.Assign):
                continue
            ctor = _ctor_name(sub.value)
            if ctor is None:
                continue
            for t in sub.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and t.value.id == "self":
                    if ctor in LOCK_CTORS:
                        self.lock_attrs.add(t.attr)
                    if ctor in THREADSAFE_CTORS:
                        self.threadsafe_attrs.add(t.attr)
        # pass 2: walk each direct method
        for item in self.node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                w = _MethodWalker(self, item.name)
                w.walk(item.body, frozenset())
                self.methods[item.name] = w.sum

    # -- derived facts ------------------------------------------------------ #

    def locks_acquired_transitively(self, method: str,
                                    _seen: Optional[set] = None) -> set:
        _seen = _seen if _seen is not None else set()
        if method in _seen or method not in self.methods:
            return set()
        _seen.add(method)
        out = {lock for lock, _, _ in self.methods[method].acquires}
        for callee, _, _ in self.methods[method].calls:
            out |= self.locks_acquired_transitively(callee, _seen)
        return out

    def reachable_from(self, entries: list) -> set:
        seen: set = set()
        work = list(entries)
        while work:
            m = work.pop()
            if m in seen or m not in self.methods:
                continue
            seen.add(m)
            work.extend(c for c, _, _ in self.methods[m].calls)
        return seen


def _scan_module(mod: ModuleInfo) -> list:
    """All class scans for one module (module-level locks attached)."""
    module_locks = set()
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and \
                _ctor_name(stmt.value) in LOCK_CTORS:
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    module_locks.add(t.id)
    scans = []
    for stmt in ast.walk(mod.tree):
        if isinstance(stmt, ast.ClassDef):
            s = _ClassScan(mod, stmt.name, stmt, module_locks=module_locks)
            s.scan()
            scans.append(s)
    return scans


# --------------------------------------------------------------------------- #
# rules over the per-class summaries
# --------------------------------------------------------------------------- #


def _finding(mod: ModuleInfo, rule: str, line: int, context: str,
             message: str) -> Finding:
    return Finding(rule=rule, path=mod.rel, line=line, context=context,
                   snippet=mod.snippet(line), message=message)


def _lock_order(scan: _ClassScan, findings: list) -> None:
    """PICO-C001: cycles in the acquired-while-holding graph."""
    edges: dict = {}  # (A, B) -> (line, method)
    for name, summ in scan.methods.items():
        for lock, held, line in summ.acquires:
            for h in held:
                if h != lock:
                    edges.setdefault((h, lock), (line, name))
        for callee, held, line in summ.calls:
            if not held:
                continue
            for lock in scan.locks_acquired_transitively(callee):
                for h in held:
                    if h != lock:
                        edges.setdefault((h, lock),
                                         (line, f"{name} -> {callee}"))
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)

    def on_cycle(a: str, b: str) -> bool:
        """Whether edge a->b closes a cycle (i.e. b reaches back to a)."""
        seen, work = set(), [b]
        while work:
            n = work.pop()
            if n == a:
                return True
            if n in seen:
                continue
            seen.add(n)
            work.extend(graph.get(n, ()))
        return False

    reported: set = set()
    for (a, b), (line, where) in sorted(edges.items(),
                                        key=lambda kv: kv[1][0]):
        if frozenset((a, b)) in reported:
            continue
        if on_cycle(a, b):
            reported.add(frozenset((a, b)))
            findings.append(_finding(
                scan.module, "PICO-C001", line,
                f"{scan.class_name}.{where.split(' ')[0]}",
                f"lock-order inversion: {b} acquired while holding {a} "
                f"here, but another path acquires them in the opposite "
                f"order — the two paths deadlock when they interleave"))


def _blocking_under_lock(scan: _ClassScan, findings: list) -> None:
    """PICO-C002: direct blocking calls, plus one-hop propagation (a
    callee that blocks, called while the caller holds a lock)."""
    for name, summ in scan.methods.items():
        for desc, held, line in summ.blocking:
            if not held:
                continue
            findings.append(_finding(
                scan.module, "PICO-C002", line,
                f"{scan.class_name}.{name}",
                f"blocking call ({desc}) while holding "
                f"{', '.join(sorted(held))} — every thread contending for "
                f"the lock stalls behind it"))
        for callee, held, line in summ.calls:
            if not held or callee not in scan.methods:
                continue
            # a callee that blocks while itself holding a lock is already
            # reported at its own site; here we catch the lock-free callee
            # whose blocking call only becomes a hazard under OUR lock
            for desc, _inner_held, bline in [
                    (d, h, ln) for d, h, ln in scan.methods[callee].blocking
                    if not h]:
                findings.append(_finding(
                    scan.module, "PICO-C002", line,
                    f"{scan.class_name}.{name}",
                    f"call to self.{callee}() while holding "
                    f"{', '.join(sorted(held))} reaches a blocking "
                    f"{desc} (at line {bline})"))


def _guarded_mutations(scan: _ClassScan, findings: list) -> None:
    """PICO-C003: attr mutated under a lock somewhere, without it
    elsewhere."""
    if not scan.lock_attrs and not scan.module_locks:
        return
    # like C004: the thread-starting method's writes happen-before
    # Thread.start, so they need no lock (module docstring contract)
    exempt = {"__init__"} | {name for name, summ in scan.methods.items()
                             if summ.thread_targets}
    by_attr: dict = {}
    for name, summ in scan.methods.items():
        if name in exempt:
            continue
        for attr, held, line in summ.mutations:
            by_attr.setdefault(attr, []).append((held, name, line))
    for attr, sites in sorted(by_attr.items()):
        guarded = sorted({lock for held, _, _ in sites for lock in held})
        if not guarded:
            continue
        for held, name, line in sites:
            if held:
                continue
            findings.append(_finding(
                scan.module, "PICO-C003", line,
                f"{scan.class_name}.{name}",
                f"self.{attr} is mutated under {', '.join(guarded)} "
                f"elsewhere but without any lock here — concurrent "
                f"threads lose updates or tear reads"))


def _cross_thread_mutations(scan: _ClassScan, findings: list) -> None:
    """PICO-C004: attr mutated by background-thread methods AND by
    foreground methods, no lock on either side."""
    entries, starters = [], set()
    for name, summ in scan.methods.items():
        if summ.thread_targets:
            starters.add(name)
            entries.extend(summ.thread_targets)
    if not entries:
        return
    reachable = scan.reachable_from(entries)
    exempt = starters | {"__init__"}
    bg_sites: dict = {}
    fg_sites: dict = {}
    for name, summ in scan.methods.items():
        if name in exempt:
            continue
        bucket = bg_sites if name in reachable else fg_sites
        for attr, held, line in summ.mutations:
            if not held:
                bucket.setdefault(attr, []).append((name, line))
    for attr in sorted(set(bg_sites) & set(fg_sites)):
        bgm, bgl = bg_sites[attr][0]
        fgm, _ = fg_sites[attr][0]
        findings.append(_finding(
            scan.module, "PICO-C004", bgl, f"{scan.class_name}.{bgm}",
            f"self.{attr} is mutated by background-thread code here AND "
            f"by {scan.class_name}.{fgm} with no lock on either side — "
            f"there is no ordering between the threads at all"))


def analyze(project: Project) -> list:
    findings: list = []
    for mod in project.modules.values():
        for scan in _scan_module(mod):
            _lock_order(scan, findings)
            _blocking_under_lock(scan, findings)
            _guarded_mutations(scan, findings)
            _cross_thread_mutations(scan, findings)
    return findings
