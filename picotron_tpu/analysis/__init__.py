"""picolint: AST-based static analysis for the two regression classes that
actually bite this codebase — silent host syncs / recompiles on jitted hot
paths (PICO-J rules) and lock-discipline bugs in the threaded serving
stack (PICO-C rules).  Pure ``ast``: linting never imports the scanned
code and needs no jax.  CLI: ``python -m picotron_tpu.tools.lint``;
catalog + policy: docs/ANALYSIS.md; gate: tests/test_analysis.py.
"""

from picotron_tpu.analysis.findings import RULES, Finding, Suppressions
from picotron_tpu.analysis.engine import (
    DEFAULT_BASELINE, diff_baseline, load_baseline, run, run_suite)

__all__ = [
    "RULES", "Finding", "Suppressions", "DEFAULT_BASELINE",
    "diff_baseline", "load_baseline", "run", "run_suite",
]
