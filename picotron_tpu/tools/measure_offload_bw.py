"""Measure the host-offload economics of this chip: link bandwidth and
the end-to-end cost of ``remat="offload"`` against ``save_attn``.

The remat="offload" mode (models/llama.py:layers_forward) parks the
decoder layer's tagged residuals in pinned host memory instead of
recomputing them — a win exactly when the host link sustains the model's
bytes-per-FLOP: ≈ (12H + 6I) bytes per token-layer against
2(4H^2 + 3HI) FLOPs (docs/BENCH_7B.md derives the crossover: H ~ 14k at
an assumed ~16 GB/s PCIe, inversely proportional to the real bandwidth).
This tool replaces the assumption with measurements:

  1. d2h / h2d bandwidth — timed ``jax.device_put`` of a ~1 GB buffer
     between device HBM and a ``pinned_host``-memory-kind sharding;
  2. offload vs save_attn — a small-geometry train step (fits any chip)
     timed in both remat modes, same seed and batch.

Usage:
    python -m picotron_tpu.tools.measure_offload_bw [--small]

Prints a table plus one JSON line for the round record.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time

import jax
import jax.numpy as jnp

from picotron_tpu.config import Config
from picotron_tpu.utils import honor_cpu_env_pin


def _time(fn, *args, warmup=2, iters=10):
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts)


def measure_link_bandwidth(n_bytes: int) -> tuple[float, float]:
    """(d2h_GBps, h2d_GBps) via device_put between memory kinds."""
    dev = jax.devices()[0]
    device_s = jax.sharding.SingleDeviceSharding(dev, memory_kind="device")
    host_s = jax.sharding.SingleDeviceSharding(dev,
                                               memory_kind="pinned_host")
    x = jax.device_put(jnp.ones((n_bytes // 4,), jnp.float32), device_s)
    jax.block_until_ready(x)
    d2h = _time(lambda a: jax.device_put(a, host_s), x)
    xh = jax.device_put(x, host_s)
    jax.block_until_ready(xh)
    h2d = _time(lambda a: jax.device_put(a, device_s), xh)
    gb = n_bytes / 1e9
    return gb / d2h, gb / h2d


def _step_cfg(remat: str, small: bool) -> Config:
    if small:
        model = dict(name="tiny", num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, hidden_size=128,
                     intermediate_size=512, vocab_size=512,
                     max_position_embeddings=256, dtype="float32",
                     attention_impl="sdpa")
        seq, mbs = 128, 2
    else:
        # 7B-layer geometry, few layers: the regime the mode targets
        # (large H), sized to fit a 16 GB chip with room for host buffers
        model = dict(name="offload-probe", num_hidden_layers=4,
                     num_attention_heads=32, num_key_value_heads=32,
                     hidden_size=4096, intermediate_size=11008,
                     vocab_size=32000, max_position_embeddings=4096,
                     dtype="bfloat16")
        seq, mbs = 4096, 1
    return Config.from_dict({
        "distributed": {"dp_size": 1, "pp_size": 1, "cp_size": 1,
                        "tp_size": 1},
        "model": model,
        "training": {"seq_length": seq, "micro_batch_size": mbs,
                     "gradient_accumulation_steps": 1, "remat": remat,
                     "learning_rate": 1e-4},
        "dataset": {"name": "synthetic"},
    })


def measure_step(remat: str, small: bool) -> float:
    """Median seconds per train step at the probe geometry."""
    from picotron_tpu import train_step as ts
    from picotron_tpu.data import MicroBatchDataLoader
    from picotron_tpu.topology import topology_from_config

    cfg = _step_cfg(remat, small)
    topo = topology_from_config(cfg, devices=jax.devices()[:1])
    params, opt_state = ts.init_state(cfg, topo)
    step = ts.build_train_step(cfg, topo)
    tokens, targets = ts.shard_batch(
        next(MicroBatchDataLoader(cfg)), topo)

    # the step donates its state, so time a real carried training loop
    warmup, iters, times = 2, 5, []
    for i in range(warmup + iters):
        t0 = time.perf_counter()
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        jax.block_until_ready(loss)
        if i >= warmup:
            times.append(time.perf_counter() - t0)
    return statistics.median(times)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="tiny geometry + small buffer (CPU/CI)")
    args = ap.parse_args(argv)
    honor_cpu_env_pin()

    n = 16 << 20 if args.small else 1 << 30
    d2h, h2d = measure_link_bandwidth(n)
    print(f"# link bandwidth ({n / 1e9:.2f} GB buffer): "
          f"d2h {d2h:.1f} GB/s, h2d {h2d:.1f} GB/s", file=sys.stderr)

    t_save = measure_step("save_attn", args.small)
    t_off = measure_step("offload", args.small)
    print(f"# step time: save_attn {t_save * 1e3:.1f} ms, "
          f"offload {t_off * 1e3:.1f} ms "
          f"(offload/save_attn = {t_off / t_save:.2f}x)", file=sys.stderr)

    print(json.dumps({
        "metric": "offload_economics",
        "value": round(t_off / t_save, 3),
        "unit": "x_step_time_vs_save_attn",
        "d2h_gbps": round(d2h, 2), "h2d_gbps": round(h2d, 2),
        "save_attn_ms": round(t_save * 1e3, 2),
        "offload_ms": round(t_off * 1e3, 2),
        "vs_baseline": 0.0}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
