"""Bounded-restart watchdog: ``python -m picotron_tpu.tools.supervise [opts] -- cmd...``

The outermost layer of the resilience stack (docs/RESILIENCE.md): keeps a
trainer running across crashes and preemptions without ever looping forever.

- **bounded restarts** — a nonzero exit relaunches the command after an
  exponential backoff, at most ``--max-restarts`` times; then the child's
  final exit code is propagated (a scheduler sees the real failure, not a
  lying 0);
- **stall detection** — the child heartbeats a file (the trainer touches
  ``$PICOTRON_HEARTBEAT`` every dispatch); a heartbeat older than
  ``--stall-timeout`` means the run is wedged (deadlocked collective, hung
  remote mount): SIGTERM, a grace period, then SIGKILL, counted as a
  restart;
- **preemption aware** — exit code ``EXIT_PREEMPTED`` (75) means "resumable
  checkpoint written, re-run me"; it is restarted like any failure but the
  trainer's auto-resume makes the relaunch continue the run.

Typical use::

    python -m picotron_tpu.tools.supervise --max-restarts 5 \
        --heartbeat /tmp/hb --stall-timeout 600 -- \
        python -m picotron_tpu.train --config exp.json
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def _heartbeat_age(path: str) -> float:
    try:
        return time.time() - os.path.getmtime(path)
    except OSError:
        return 0.0  # no file yet: the launch touch below seeds it


def _touch(path: str) -> None:
    with open(path, "a"):
        os.utime(path, None)


def _terminate(proc: subprocess.Popen, grace: float) -> int:
    """SIGTERM, wait out the grace period, SIGKILL. Returns the exit code."""
    proc.send_signal(signal.SIGTERM)
    try:
        return proc.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        proc.kill()
        return proc.wait()


def run_supervised(cmd, max_restarts: int = 3, backoff: float = 1.0,
                   backoff_max: float = 60.0, heartbeat: str = "",
                   stall_timeout: float = 0.0, term_grace: float = 10.0,
                   poll_interval: float = 0.2) -> int:
    """Run ``cmd`` under supervision; returns the exit code to propagate.
    ``stall_timeout`` <= 0 disables stall detection. Importable so the chaos
    suite drives it in-process (the children are still real subprocesses)."""
    env = dict(os.environ)
    if heartbeat:
        env["PICOTRON_HEARTBEAT"] = heartbeat
    attempt = 0  # restarts used so far
    while True:
        if heartbeat:
            _touch(heartbeat)  # launch counts as liveness: startup gets a full window
        print(f"supervise: launching (restart {attempt}/{max_restarts}): "
              f"{' '.join(cmd)}", flush=True)
        proc = subprocess.Popen(cmd, env=env)
        stalled = False
        while True:
            rc = proc.poll()
            if rc is not None:
                break
            if (heartbeat and stall_timeout > 0
                    and _heartbeat_age(heartbeat) > stall_timeout):
                print(f"supervise: heartbeat stale for > {stall_timeout}s; "
                      f"killing the stalled trainer", flush=True)
                rc = _terminate(proc, term_grace)
                stalled = True
                break
            time.sleep(poll_interval)
        if rc == 0 and not stalled:
            print("supervise: trainer exited cleanly", flush=True)
            return 0
        attempt += 1
        if attempt > max_restarts:
            code = rc if rc >= 0 else 128 - rc  # shell convention for signal deaths
            print(f"supervise: exhausted {max_restarts} restarts; "
                  f"propagating exit code {code}", flush=True)
            return code
        delay = min(backoff * (2 ** (attempt - 1)), backoff_max)
        print(f"supervise: exit code {rc}{' (stall-killed)' if stalled else ''}; "
              f"restart {attempt}/{max_restarts} in {delay:.1f}s", flush=True)
        time.sleep(delay)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="bounded-restart watchdog around a trainer command "
                    "(everything after -- is the command line)")
    parser.add_argument("--max-restarts", type=int, default=3)
    parser.add_argument("--backoff", type=float, default=1.0,
                        help="first restart delay; doubles per restart")
    parser.add_argument("--backoff-max", type=float, default=60.0)
    parser.add_argument("--heartbeat", default="",
                        help="heartbeat file (exported as PICOTRON_HEARTBEAT)")
    parser.add_argument("--stall-timeout", type=float, default=0.0,
                        help="seconds of stale heartbeat before a stall kill "
                             "(0 = off)")
    parser.add_argument("--term-grace", type=float, default=10.0,
                        help="seconds between SIGTERM and SIGKILL on a stall")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="-- then the command to supervise")
    args = parser.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no command given (usage: supervise [opts] -- cmd ...)")
    if args.stall_timeout > 0 and not args.heartbeat:
        parser.error("--stall-timeout needs --heartbeat")
    return run_supervised(
        cmd, max_restarts=args.max_restarts, backoff=args.backoff,
        backoff_max=args.backoff_max, heartbeat=args.heartbeat,
        stall_timeout=args.stall_timeout, term_grace=args.term_grace)


if __name__ == "__main__":
    sys.exit(main())
