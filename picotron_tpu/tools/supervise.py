"""Bounded-restart watchdog: ``python -m picotron_tpu.tools.supervise [opts] -- cmd...``

The outermost layer of the resilience stack (docs/RESILIENCE.md): keeps a
trainer — one process, or a whole multi-host pod — running across crashes,
preemptions, and dead hosts without ever looping forever.

- **bounded restarts** — a nonzero exit relaunches the command after an
  exponential backoff, at most ``--max-restarts`` times; then the child's
  final exit code is propagated (a scheduler sees the real failure, not a
  lying 0). The budget REPLENISHES: after ``--healthy-reset`` seconds of
  uptime a failure counts from zero again, so a long run that hiccups once
  a day is not killed by arithmetic after a few weeks (0 = legacy
  never-replenish);
- **stall detection** — the child heartbeats a file (the trainer touches
  ``$PICOTRON_HEARTBEAT`` every dispatch); a heartbeat older than
  ``--stall-timeout`` — or MISSING after launch (deleting it must not
  silently disable the detector) — means the run is wedged (deadlocked
  collective, hung remote mount): SIGTERM, a grace period, then SIGKILL,
  counted as a restart;
- **preemption aware** — exit code ``EXIT_PREEMPTED`` (75) means "resumable
  checkpoint written, re-run me"; it is restarted like any failure but the
  trainer's auto-resume makes the relaunch continue the run;
- **serve mode** (``--serve``) — the child is a serving replica
  (``tools/serve.py``): clean drains (exit 0) relaunch WITHOUT charging
  the restart budget (a drain is a rollout, not a crash), nonzero exits
  (serve exits 1 when its dispatch loop dies) walk the normal ladder,
  and the supervisor's own SIGTERM/SIGINT forwards to the child and ends
  supervision with its exit code — one supervisor per fleet member keeps
  an N-replica router fabric (``tools/router.py``) populated;
- **spot-quota aware** — a launch that dies within ``--quota-window``
  seconds never produced a step (no capacity, quota exhausted, a dead
  coordinator): those retry on their own long, capped backoff ladder
  (``--quota-backoff`` doubling up to ``--quota-backoff-max``) WITHOUT
  burning the restart budget, bounded by ``--max-launch-retries``.

**Pod mode** (``--num-procs N``) supervises one multi-controller pod
locally: N copies of the command, each with ``JAX_PROCESS_ID`` /
``JAX_NUM_PROCESSES`` / ``PICOTRON_POD_RANK`` (and a per-rank heartbeat
``<hb>.p<i>``) in its environment. The pod lives and dies together —
that is what keeps collectives coherent:

- every rank exiting 0 ⇒ done;
- any rank exiting 75 (preempted — its peers follow via the consensus in
  resilience/cluster.py) ⇒ the stragglers get ``--term-grace`` to finish
  their own coordinated exit, then the pod restarts as resumable;
- any rank crashing or exiting ``EXIT_CLUSTER_FAILED`` (77: a peer died
  inside a collective) ⇒ terminate the stragglers, restart the pod
  together;
- any rank's heartbeat going stale ⇒ kill and restart the whole pod.

**Per-host pods** (one supervisor per host, e.g. under SLURM) coordinate
through ``--epoch-file`` on shared storage instead: a supervisor whose
child fails bumps the epoch; every supervisor polling a bumped epoch
terminates its own child (SIGTERM — the trainer still takes its emergency
save) and relaunches, so the pod restarts together without a shared
process table. Epoch restarts triggered by a PEER do not consume the
local restart budget — the failing host's supervisor accounts for them.

Typical use::

    python -m picotron_tpu.tools.supervise --max-restarts 5 \
        --heartbeat /tmp/hb --stall-timeout 600 -- \
        python -m picotron_tpu.train --config exp.json

    # a 2-process local pod with coordinated restarts
    python -m picotron_tpu.tools.supervise --num-procs 2 \
        --coordinator localhost:8476 -- \
        python -m picotron_tpu.train --config exp.json
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Optional

# Mirrors picotron_tpu.resilience.{EXIT_PREEMPTED, EXIT_CLUSTER_FAILED};
# duplicated so the supervisor never imports jax (tests pin the values in
# lockstep).
EXIT_PREEMPTED = 75
EXIT_CLUSTER_FAILED = 77


def _heartbeat_age(path: str, launched_at: float) -> float:
    """Age of the child's liveness signal. ``launched_at`` (wall clock) seeds
    the no-file case: the launch touch creates the file, so a missing file
    afterwards means it was DELETED — counting its age from launch makes
    deletion read as a growing stall instead of silently disabling the
    detector forever (the old behavior returned 0.0 = "perfectly fresh")."""
    try:
        return time.time() - os.path.getmtime(path)
    except OSError:
        return time.time() - launched_at


def _touch(path: str) -> None:
    with open(path, "a"):
        os.utime(path, None)


def _terminate(proc: subprocess.Popen, grace: float) -> int:
    """SIGTERM, wait out the grace period, SIGKILL. Returns the exit code."""
    proc.send_signal(signal.SIGTERM)
    try:
        return proc.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        proc.kill()
        return proc.wait()


def _shell_code(rc: int) -> int:
    """Shell convention for signal deaths: ``rc < 0`` → ``128 - rc``
    (SIGTERM → 143, SIGKILL → 137), so schedulers see the signal."""
    return rc if rc >= 0 else 128 - rc


def _read_epoch(path: str) -> int:
    try:
        with open(path) as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def _bump_epoch(path: str, beyond: int) -> None:
    """Advance the shared restart epoch past ``beyond`` (atomic rename;
    concurrent bumps from several hosts may collapse into one epoch, which
    is fine — one pod restart is exactly what they all asked for)."""
    nxt = max(_read_epoch(path), beyond) + 1
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            f.write(str(nxt))
        os.replace(tmp, path)
    except OSError as e:
        print(f"supervise: failed to bump epoch file {path}: {e}",
              flush=True)


class _RestartBudget:
    """Restart accounting shared by single and pod mode: bounded attempts,
    healthy-uptime replenishment, and the spot-quota launch-failure ladder.
    """

    def __init__(self, max_restarts: int, backoff: float, backoff_max: float,
                 healthy_reset: float = 600.0, quota_window: float = 0.0,
                 quota_backoff: float = 30.0, quota_backoff_max: float = 1800.0,
                 max_launch_retries: int = 120):
        self.max_restarts = max_restarts
        self.backoff = backoff
        self.backoff_max = backoff_max
        self.healthy_reset = healthy_reset
        self.quota_window = quota_window
        self.quota_backoff = quota_backoff
        self.quota_backoff_max = quota_backoff_max
        self.max_launch_retries = max_launch_retries
        self.attempt = 0  # restarts charged to the budget so far
        self.launch_failures = 0  # consecutive quota-style fast failures

    def record(self, uptime: float, preempted: bool = False,
               stalled: bool = False) -> Optional[tuple[str, float]]:
        """Classify one failed run given its uptime; returns ``(kind,
        delay_s)`` for the relaunch or None when the budget is exhausted.
        ``preempted`` runs are never quota failures — they held capacity
        and checkpointed; dying fast is the preemption's fault. ``stalled``
        runs never replenish (their uptime includes >= stall_timeout of
        DEAD time: with stall_timeout >= healthy_reset a permanently
        wedged trainer would otherwise reset the budget every cycle and
        relaunch forever) and never read as quota launch failures (they
        held capacity — they just hung)."""
        if (self.quota_window > 0 and uptime < self.quota_window
                and not preempted and not stalled):
            # never reached a working step: no-capacity/quota-style launch
            # failure — wait long (the pool refills in minutes, not
            # milliseconds), don't charge the crash budget
            self.launch_failures += 1
            if (self.max_launch_retries > 0
                    and self.launch_failures > self.max_launch_retries):
                return None
            delay = min(self.quota_backoff * 2 ** (self.launch_failures - 1),
                        self.quota_backoff_max)
            return (f"launch failure {self.launch_failures}"
                    f"/{self.max_launch_retries or 'inf'}", delay)
        self.launch_failures = 0
        if not stalled and self.healthy_reset > 0 and uptime >= self.healthy_reset:
            # the run was healthy long enough that prior failures are
            # stale history: replenish the budget and restart the ladder
            self.attempt = 0
        self.attempt += 1
        if self.attempt > self.max_restarts:
            return None
        delay = min(self.backoff * 2 ** (self.attempt - 1), self.backoff_max)
        return (f"restart {self.attempt}/{self.max_restarts}", delay)


def run_supervised(cmd, max_restarts: int = 3, backoff: float = 1.0,
                   backoff_max: float = 60.0, heartbeat: str = "",
                   stall_timeout: float = 0.0, term_grace: float = 10.0,
                   poll_interval: float = 0.2, healthy_reset: float = 600.0,
                   quota_window: float = 0.0, quota_backoff: float = 30.0,
                   quota_backoff_max: float = 1800.0,
                   max_launch_retries: int = 120, epoch_file: str = "",
                   metrics_jsonl: str = "", serve_mode: bool = False,
                   sleep=time.sleep) -> int:
    """Run ``cmd`` under supervision; returns the exit code to propagate.
    ``stall_timeout`` <= 0 disables stall detection; ``epoch_file`` joins a
    per-host pod (see the module docstring). Importable so the chaos suite
    drives it in-process (the children are still real subprocesses).

    ``serve_mode`` (the ``--serve`` flag) supervises a serving replica
    (``tools/serve.py``) instead of a trainer, with restart-ALWAYS fleet
    semantics: a clean drain (exit 0) relaunches the replica after
    ``backoff`` WITHOUT charging the restart budget — a drain is an
    intentional event (SIGTERM rollout, a router pulling the replica),
    not a crash — while nonzero exits (a dead dispatch loop exits 1)
    walk the existing budget/backoff ladder. The fleet is stopped
    through the SUPERVISOR: its own SIGTERM/SIGINT is forwarded to the
    child (which drains) and supervision ends with the child's exit
    code. Signal forwarding is installed only on the main thread."""
    env = dict(os.environ)
    if heartbeat:
        env["PICOTRON_HEARTBEAT"] = heartbeat
    if metrics_jsonl:
        # the trainer appends its per-step metrics JSONL here (the
        # structured surface extract_metrics.py prefers over the log
        # regex); append semantics make restarts stitch into one file
        env["PICOTRON_METRICS_JSONL"] = metrics_jsonl
    budget = _RestartBudget(
        max_restarts, backoff, backoff_max, healthy_reset=healthy_reset,
        quota_window=quota_window, quota_backoff=quota_backoff,
        quota_backoff_max=quota_backoff_max,
        max_launch_retries=max_launch_retries)
    # serve mode: the supervisor is the fleet's stop surface — forward
    # SIGTERM/SIGINT to the child (it drains) and end supervision with
    # its exit code. Only installable from the main thread (tests drive
    # this function from worker threads, where the default disposition
    # already applies).
    stop_req = {"flag": False, "proc": None}
    restore: dict = {}
    if serve_mode and threading.current_thread() is threading.main_thread():
        def _forward(signum, frame):
            stop_req["flag"] = True
            p = stop_req["proc"]
            if p is not None and p.poll() is None:
                p.send_signal(signal.SIGTERM)

        for s in (signal.SIGTERM, signal.SIGINT):
            restore[s] = signal.signal(s, _forward)
    try:
        return _run_supervised_loop(
            cmd, env, budget, stop_req, max_restarts=max_restarts,
            backoff=backoff, heartbeat=heartbeat,
            stall_timeout=stall_timeout, term_grace=term_grace,
            poll_interval=poll_interval, epoch_file=epoch_file,
            serve_mode=serve_mode, sleep=sleep)
    finally:
        for s, handler in restore.items():
            signal.signal(s, handler)


def _run_supervised_loop(cmd, env, budget, stop_req, *, max_restarts,
                         backoff, heartbeat, stall_timeout, term_grace,
                         poll_interval, epoch_file, serve_mode,
                         sleep) -> int:
    while True:
        if heartbeat:
            _touch(heartbeat)  # launch counts as liveness: startup gets a full window
        launch_epoch = _read_epoch(epoch_file) if epoch_file else 0
        launched_at = time.time()
        t0 = time.monotonic()
        print(f"supervise: launching (restarts used "
              f"{budget.attempt}/{max_restarts}): {' '.join(cmd)}",
              flush=True)
        proc = subprocess.Popen(cmd, env=env)
        stop_req["proc"] = proc
        if stop_req["flag"] and proc.poll() is None:
            # the stop signal landed between launches: this child never
            # saw the forward — deliver it now
            proc.send_signal(signal.SIGTERM)
        stalled = peer_restart = False
        next_epoch_poll = 0.0  # epoch lives on shared storage: poll it on
        # its own >= 1s cadence, not every child-liveness tick
        while True:
            rc = proc.poll()
            if rc is not None:
                break
            if (heartbeat and stall_timeout > 0
                    and _heartbeat_age(heartbeat, launched_at) > stall_timeout):
                print(f"supervise: heartbeat stale for > {stall_timeout}s; "
                      f"killing the stalled trainer", flush=True)
                rc = _terminate(proc, term_grace)
                stalled = True
                break
            if epoch_file and time.monotonic() >= next_epoch_poll:
                next_epoch_poll = time.monotonic() + max(poll_interval, 1.0)
                if _read_epoch(epoch_file) > launch_epoch:
                    print("supervise: pod restart epoch bumped by a peer "
                          "host; terminating for a coordinated relaunch",
                          flush=True)
                    rc = _terminate(proc, term_grace)
                    peer_restart = True
                    break
            sleep(poll_interval)
        if stop_req["flag"]:
            # operator stop: the forwarded SIGTERM drained the child —
            # propagate its verdict (0 on a clean drain), never relaunch
            code = _shell_code(rc)
            print(f"supervise: stop requested; child exited {code}",
                  flush=True)
            return code
        if rc == 0 and not stalled and not peer_restart:
            if serve_mode:
                # a replica drain is intentional, not a crash: keep the
                # fleet member alive without touching the restart budget
                print(f"supervise: replica drained cleanly (exit 0); "
                      f"relaunching in {backoff:.1f}s (not charged to "
                      f"the restart budget)", flush=True)
                sleep(backoff)
                continue
            print("supervise: trainer exited cleanly", flush=True)
            return 0
        if peer_restart:
            # the failing host's supervisor pays the budget; we just follow
            print(f"supervise: relaunching for peer-initiated pod restart "
                  f"in {backoff:.1f}s", flush=True)
            sleep(backoff)
            continue
        if epoch_file:
            if _read_epoch(epoch_file) > launch_epoch:
                # our failure is part of a pod-wide event a peer already
                # bumped for (coordinated preemption lands every child
                # within seconds): compounding the bump would advance the
                # epoch N times and SIGTERM peers' freshly resumed
                # trainers — follow the existing restart on their budget
                print("supervise: pod restart epoch already bumped for "
                      "this incarnation; following the peer-initiated "
                      f"restart in {backoff:.1f}s", flush=True)
                sleep(backoff)
                continue
            # our child failed first: tell the other hosts' supervisors to
            # restart their ranks too, so the pod relaunches together
            _bump_epoch(epoch_file, launch_epoch)
        verdict = budget.record(time.monotonic() - t0,
                                preempted=rc == EXIT_PREEMPTED,
                                stalled=stalled)
        if verdict is None:
            code = _shell_code(rc)
            print(f"supervise: restart budget exhausted; propagating exit "
                  f"code {code}", flush=True)
            return code
        kind, delay = verdict
        print(f"supervise: exit code {rc}"
              f"{' (stall-killed)' if stalled else ''}; {kind} in "
              f"{delay:.1f}s", flush=True)
        sleep(delay)


def _pod_exit_code(rcs, stalled: bool) -> int:
    """The single code a scheduler sees for a pod: a real crash wins over
    75 (something is wrong beyond preemption), 75 over a stall kill.
    Among crashes, a child's own verdict (77, then any other positive
    code) wins over codes synthesized from the supervisor's straggler
    SIGTERM — a reaped -15 must not mask the root cause."""
    crash = [rc for rc in rcs if rc not in (0, EXIT_PREEMPTED)]
    if crash:
        if EXIT_CLUSTER_FAILED in crash:
            return EXIT_CLUSTER_FAILED
        positive = [rc for rc in crash if rc > 0]
        return _shell_code(positive[0] if positive else crash[0])
    if any(rc == EXIT_PREEMPTED for rc in rcs):
        return EXIT_PREEMPTED
    return 1 if stalled else 0


def run_pod(cmd, num_procs: int, max_restarts: int = 3, backoff: float = 1.0,
            backoff_max: float = 60.0, heartbeat: str = "",
            stall_timeout: float = 0.0, term_grace: float = 10.0,
            poll_interval: float = 0.2, healthy_reset: float = 600.0,
            quota_window: float = 0.0, quota_backoff: float = 30.0,
            quota_backoff_max: float = 1800.0, max_launch_retries: int = 120,
            coordinator: str = "", metrics_jsonl: str = "",
            sleep=time.sleep) -> int:
    """Supervise an N-process local pod of ``cmd``; returns the exit code
    to propagate. The pod restarts as a unit (see the module docstring);
    restart accounting is shared across ranks through one budget."""
    budget = _RestartBudget(
        max_restarts, backoff, backoff_max, healthy_reset=healthy_reset,
        quota_window=quota_window, quota_backoff=quota_backoff,
        quota_backoff_max=quota_backoff_max,
        max_launch_retries=max_launch_retries)
    while True:
        launched_at = time.time()
        t0 = time.monotonic()
        print(f"supervise: launching pod of {num_procs} (restarts used "
              f"{budget.attempt}/{max_restarts}): {' '.join(cmd)}",
              flush=True)
        procs, hbs = [], []
        for i in range(num_procs):
            env = dict(os.environ)
            env["JAX_NUM_PROCESSES"] = str(num_procs)
            env["JAX_PROCESS_ID"] = str(i)
            env["PICOTRON_POD_RANK"] = str(i)
            if coordinator:
                env["JAX_COORDINATOR_ADDRESS"] = coordinator
            hb = f"{heartbeat}.p{i}" if heartbeat else ""
            if hb:
                env["PICOTRON_HEARTBEAT"] = hb
                _touch(hb)
            if metrics_jsonl:
                # only the controller rank writes metrics (train gates on
                # is_main_process), but export per-rank paths anyway so a
                # misconfigured pod can never interleave one file
                env["PICOTRON_METRICS_JSONL"] = (
                    metrics_jsonl if i == 0 else f"{metrics_jsonl}.p{i}")
            hbs.append(hb)
            procs.append(subprocess.Popen(cmd, env=env))
        rcs: list = [None] * num_procs
        stalled = False

        def _refresh() -> None:
            for i, p in enumerate(procs):
                if rcs[i] is None:
                    rcs[i] = p.poll()

        def _reap_stragglers() -> None:
            for i, p in enumerate(procs):
                if rcs[i] is None:
                    print(f"supervise: terminating straggler rank {i}",
                          flush=True)
                    rcs[i] = _terminate(p, term_grace)

        while True:
            _refresh()
            if all(rc is not None for rc in rcs):
                break
            if any(rc not in (None, 0) for rc in rcs):
                # one rank is down. Its peers normally follow on their own
                # — consensus exit 75, or the cluster monitor's 77 — so
                # give them the grace window to record THEIR verdicts
                # (and finish coordinated saves) before the hammer.
                deadline = time.monotonic() + term_grace
                while time.monotonic() < deadline:
                    _refresh()
                    if all(rc is not None for rc in rcs):
                        break
                    sleep(poll_interval)
                _reap_stragglers()
                break
            if heartbeat and stall_timeout > 0:
                stale = [i for i, hb in enumerate(hbs)
                         if rcs[i] is None
                         and _heartbeat_age(hb, launched_at) > stall_timeout]
                if stale:
                    print(f"supervise: rank(s) {stale} heartbeat stale for "
                          f"> {stall_timeout}s; killing the pod", flush=True)
                    stalled = True
                    _reap_stragglers()
                    break
            sleep(poll_interval)
        print(f"supervise: pod exit codes {rcs}"
              f"{' (stall-killed)' if stalled else ''}", flush=True)
        if all(rc == 0 for rc in rcs) and not stalled:
            print("supervise: pod exited cleanly", flush=True)
            return 0
        preempted = (any(rc == EXIT_PREEMPTED for rc in rcs)
                     and all(rc in (0, EXIT_PREEMPTED) for rc in rcs))
        verdict = budget.record(time.monotonic() - t0, preempted=preempted,
                                stalled=stalled)
        if verdict is None:
            code = _pod_exit_code(rcs, stalled)
            print(f"supervise: restart budget exhausted; propagating exit "
                  f"code {code}", flush=True)
            return code
        kind, delay = verdict
        what = "preempted (resumable)" if preempted else "failed"
        print(f"supervise: pod {what}; {kind} in {delay:.1f}s", flush=True)
        sleep(delay)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="bounded-restart watchdog around a trainer command "
                    "(everything after -- is the command line)")
    parser.add_argument("--max-restarts", type=int, default=3)
    parser.add_argument("--backoff", type=float, default=1.0,
                        help="first restart delay; doubles per restart")
    parser.add_argument("--backoff-max", type=float, default=60.0)
    parser.add_argument("--heartbeat", default="",
                        help="heartbeat file (exported as PICOTRON_HEARTBEAT;"
                             " pod mode appends .p<rank>)")
    parser.add_argument("--stall-timeout", type=float, default=0.0,
                        help="seconds of stale heartbeat before a stall kill "
                             "(0 = off)")
    parser.add_argument("--term-grace", type=float, default=10.0,
                        help="seconds between SIGTERM and SIGKILL on a stall "
                             "(pod mode: also how long peers may finish a "
                             "coordinated exit after a rank goes down)")
    parser.add_argument("--healthy-reset", type=float, default=600.0,
                        help="seconds of uptime after which the restart "
                             "budget and backoff reset (0 = never)")
    parser.add_argument("--quota-window", type=float, default=0.0,
                        help="a run dying within this many seconds of launch "
                             "is a quota-style launch failure: long backoff, "
                             "no restart-budget charge (0 = off)")
    parser.add_argument("--quota-backoff", type=float, default=30.0,
                        help="first launch-failure delay; doubles per failure")
    parser.add_argument("--quota-backoff-max", type=float, default=1800.0)
    parser.add_argument("--max-launch-retries", type=int, default=120,
                        help="consecutive launch failures before giving up "
                             "(0 = unlimited)")
    parser.add_argument("--serve", action="store_true",
                        help="the child is a serving replica "
                             "(tools/serve.py): clean drains (exit 0) "
                             "relaunch WITHOUT charging the restart "
                             "budget, nonzero exits walk the normal "
                             "ladder, and the supervisor's own "
                             "SIGTERM/SIGINT forwards to the child and "
                             "ends supervision after its drain")
    parser.add_argument("--num-procs", type=int, default=1,
                        help="N > 1 supervises a local N-process pod "
                             "(JAX_PROCESS_ID/JAX_NUM_PROCESSES per rank)")
    parser.add_argument("--coordinator", default="",
                        help="pod mode: exported as JAX_COORDINATOR_ADDRESS")
    parser.add_argument("--epoch-file", default="",
                        help="per-host pods: shared restart-epoch file; a "
                             "bump by any host restarts every host's child")
    parser.add_argument("--metrics-jsonl", default="",
                        help="per-step metrics JSONL path exported as "
                             "PICOTRON_METRICS_JSONL (point it next to the "
                             "run log; extract_metrics.py prefers it over "
                             "the log regex; pod ranks > 0 get .p<rank>)")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="-- then the command to supervise")
    args = parser.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no command given (usage: supervise [opts] -- cmd ...)")
    if args.stall_timeout > 0 and not args.heartbeat:
        parser.error("--stall-timeout needs --heartbeat")
    if args.serve and args.num_procs > 1:
        parser.error("--serve supervises one replica per supervisor "
                     "(run N supervisors for an N-replica fleet); it is "
                     "incompatible with --num-procs pods")
    if args.num_procs > 1 and args.epoch_file:
        parser.error("--epoch-file is for one-supervisor-per-host pods; "
                     "--num-procs already restarts its local pod together")
    if args.num_procs > 1 and not args.coordinator:
        # without JAX_COORDINATOR_ADDRESS the trainer never joins a pod:
        # N full DUPLICATE single-process runs would race on one save_dir
        parser.error("--num-procs needs --coordinator (host:port for the "
                     "ranks' jax.distributed rendezvous)")
    common = dict(
        max_restarts=args.max_restarts, backoff=args.backoff,
        backoff_max=args.backoff_max, heartbeat=args.heartbeat,
        stall_timeout=args.stall_timeout, term_grace=args.term_grace,
        healthy_reset=args.healthy_reset, quota_window=args.quota_window,
        quota_backoff=args.quota_backoff,
        quota_backoff_max=args.quota_backoff_max,
        max_launch_retries=args.max_launch_retries,
        metrics_jsonl=args.metrics_jsonl)
    if args.num_procs > 1:
        return run_pod(cmd, args.num_procs, coordinator=args.coordinator,
                       **common)
    return run_supervised(cmd, epoch_file=args.epoch_file,
                          serve_mode=args.serve, **common)


if __name__ == "__main__":
    sys.exit(main())
