"""Elastic fleet controller: self-resizing serving under spikes,
preemption, and capacity loss (docs/SERVING.md "Elastic fleet").

``tools/router.py`` gave the serving fabric placement, breakers, and
mid-stream failover over a FIXED replica set; every scale event — a spot
preemption, a dead decode worker, a prefill queue backing up — still
needed an operator. The ``FleetController`` closes that loop: it OWNS a
dynamic set of serve.py workers and runs a scrape → decide → actuate
control cycle against the live telemetry plane.

Control loop (one tick per ``fleet.scrape_interval_s``):

1. **Scrape** every worker's ``/readyz`` + ``/metrics`` directly (queue
   depth — the prefill queue on prefill-role workers — KV pool
   occupancy, active slots, TTFT p95). A failed metrics read with a live
   process is *stale*, never *dead*: a wedged scrape plane must not
   trigger a replacement storm. Death is a dead process handle or
   ``hysteresis`` consecutive connection-level failures.
2. **Decide** per role, walking a fixed ladder:
   - *replace* dead workers first — budget-gated (the ``_RestartBudget``
     ladder from tools/supervise.py: bounded attempts, exponential
     backoff, healthy-uptime replenishment), never cooloff-gated; lost
     capacity must not wait behind a scale decision;
   - *grow* when ANY high watermark is breached for ``hysteresis``
     consecutive ticks (queue > queue_high, pool > pool_high, TTFT p95
     over the SLO) and the role is under ``max_workers``;
   - *drain* the least-loaded worker when ALL signals sit below their
     low watermarks for ``hysteresis`` ticks and the role is above
     ``min_workers``. Grow/drain share a per-role ``cooloff_s`` (the
     PR 14 SpecController discipline lifted to fleet scale).
3. **Actuate** off the tick thread: launches go through a pluggable
   launcher (``SubprocessLauncher`` = serve.py under ``tools/supervise.py
   --serve``; ``_SmokeLauncher`` = in-process servers for the chaos
   drill) and register with the router through its dynamic replica-set
   admin API (``POST /replicas`` / ``DELETE /replicas/<name>``). A drain
   first relocates the victim's hottest radix prefixes to a survivor
   through the PR 15 page transport (GET /kv/prefixes → POST /kv/pages →
   POST /kv/import — soft: any failure just skips the export), then arms
   the worker's stop surface, POSTs ``/drain`` (202, or 409 when the
   stop signal already started one), waits for the in-flight work to
   finish, and only then deregisters — a scale-down loses zero requests.

Observability: every decision is counted
(``picotron_fleet_decisions_total{action=replace|grow|drain|
replace_exhausted}``), latencies land in
``picotron_fleet_scale_up_seconds`` / ``picotron_fleet_replace_seconds``
histograms, per-role worker counts in ``picotron_fleet_workers``, and
each actuation emits a tracer span — the accounting the chaos smoke
(`make fleet-chaos-smoke`) audits decision by decision.

Locking discipline (picolint PICO-C001..C004): ``_mu`` is a LEAF lock
guarding the worker registry and worker state transitions — never held
across scrape I/O, launches, joins, or another lock. Streak/budget state
is touched only by the controller tick thread and needs no lock at all.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Optional

from picotron_tpu.config import FleetConfig
from picotron_tpu.obs import Obs
from picotron_tpu.obs.metrics import parse_prometheus
from picotron_tpu.resilience.retry import retry
from picotron_tpu.tools.router import DuplicateReplica, hist_quantile
from picotron_tpu.tools.supervise import _RestartBudget

# how many ticks a scrape may miss before the reading is too old to
# steer a watermark decision (distinct from death: stale load is
# *unknown* load, and unknown load must park the streaks, not feed them)
_FRESH_TICKS = 3.0


# --------------------------------------------------------------------------- #
# stdlib HTTP helpers (the same close-delimited HTTP/1.0 clients the
# router's prober uses — the controller is a peer of that scrape plane)
# --------------------------------------------------------------------------- #


def _get_json(host: str, port: int, path: str, timeout: float):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def _get_text(host: str, port: int, path: str, timeout: float):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode("utf-8", "replace")
    finally:
        conn.close()


def _req_json(method: str, host: str, port: int, path: str, body=None,
              timeout: float = 5.0):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        payload = None if body is None else json.dumps(body)
        conn.request(method, path, payload,
                     {"Content-Type": "application/json"} if payload
                     else {})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def _post_json(host: str, port: int, path: str, body: dict,
               timeout: float = 5.0):
    return _req_json("POST", host, port, path, body, timeout)


def _free_port() -> int:
    s = socket.socket()
    try:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
    finally:
        s.close()


# --------------------------------------------------------------------------- #
# router admin clients
# --------------------------------------------------------------------------- #


class RouterAdmin:
    """HTTP client for the router's dynamic replica-set admin API
    (``POST /replicas``, ``DELETE /replicas/<name>``). Register is
    idempotent — a 409 means the replica is already in the set, which is
    exactly what a controller restarted over a live fleet wants."""

    def __init__(self, host: str, port: int, timeout: float = 5.0):
        self.host = host
        self.port = int(port)
        self.timeout = timeout

    def register(self, host: str, port: int) -> str:
        name = f"{host}:{port}"
        st, body = _post_json(self.host, self.port, "/replicas",
                              {"replica": name}, self.timeout)
        if st not in (200, 409):
            raise RuntimeError(f"router register {name}: HTTP {st}: "
                               f"{body.get('error', body)}")
        return name

    def deregister(self, name: str) -> None:
        # ':' is path-safe; the router unquotes, so no encoding needed
        st, body = _req_json("DELETE", self.host, self.port,
                             f"/replicas/{name}", None, self.timeout)
        if st not in (200, 404):  # 404 = already gone, the desired state
            raise RuntimeError(f"router deregister {name}: HTTP {st}: "
                               f"{body.get('error', body)}")

    def replicas(self) -> dict:
        st, body = _get_json(self.host, self.port, "/replicas",
                             self.timeout)
        if st != 200:
            raise RuntimeError(f"router GET /replicas: HTTP {st}")
        return body


class DirectRouterAdmin:
    """In-process adapter over a ``Router`` object — the unit-test seam
    (``RouterAdmin`` is the same three calls over the wire)."""

    def __init__(self, router):
        self.router = router

    def register(self, host: str, port: int) -> str:
        name = f"{host}:{port}"
        try:
            self.router.add_replica(name)
        except DuplicateReplica:
            pass
        return name

    def deregister(self, name: str) -> None:
        try:
            self.router.remove_replica(name)
        except KeyError:
            pass

    def replicas(self) -> dict:
        now = self.router._clock()
        return {n: r.snapshot(now)
                for n, r in self.router.replicas.items()}


# --------------------------------------------------------------------------- #
# worker handles + launchers
# --------------------------------------------------------------------------- #


class SubprocessHandle:
    """One worker = one process GROUP: ``supervise --serve`` plus the
    serve.py child it restarts. ``terminate`` SIGTERMs the supervisor
    (it forwards to the child, which drains, and does NOT relaunch a
    stop-requested exit); ``kill`` SIGKILLs the whole group — the crash
    flavor the controller's replace ladder exists for."""

    def __init__(self, proc: subprocess.Popen, host: str, port: int):
        self.proc = proc
        self.host = host
        self.port = int(port)

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        try:
            os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                self.proc.kill()
            except ProcessLookupError:
                pass

    def terminate(self) -> None:
        try:
            self.proc.terminate()
        except ProcessLookupError:
            pass

    def wait(self, timeout: Optional[float] = None) -> bool:
        try:
            self.proc.wait(timeout=timeout)
            return True
        except subprocess.TimeoutExpired:
            return False


def _drain_pipe(stream) -> None:
    for _ in iter(stream.readline, b""):
        pass


class SubprocessLauncher:
    """Launch serve.py workers as real subprocesses under ``tools/
    supervise.py --serve`` (in-worker crash/preempt restarts stay the
    supervisor's job; WHOLE-worker loss is the fleet controller's).
    ``launch`` blocks until the worker's health surface answers — model
    init and jit warm-up are part of the scale-up latency the fleet
    histograms measure."""

    def __init__(self, config_path: str, *, slots: int = 2,
                 max_seq_len: Optional[int] = None, serve_args=(),
                 supervise_args=("--max-restarts", "2",
                                 "--backoff", "0.25"),
                 python: str = "", startup_timeout_s: float = 180.0):
        self.config_path = config_path
        self.slots = int(slots)
        self.max_seq_len = max_seq_len
        self.serve_args = tuple(serve_args)
        self.supervise_args = tuple(supervise_args)
        self.python = python or sys.executable
        self.startup_timeout_s = startup_timeout_s

    def launch(self, name: str, role: str) -> SubprocessHandle:
        port = _free_port()
        py = self.python
        cmd = [py, "-m", "picotron_tpu.tools.supervise", "--serve",
               *self.supervise_args, "--",
               py, "-m", "picotron_tpu.tools.serve",
               "--config", self.config_path, "--random-init",
               "--port", str(port), "--slots", str(self.slots)]
        if self.max_seq_len:
            cmd += ["--max-seq-len", str(self.max_seq_len)]
        if role != "both":
            cmd += ["--role", role, "--kv-layout", "paged"]
        cmd += list(self.serve_args)
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True)  # own pgid: kill() takes the pair
        threading.Thread(target=_drain_pipe, args=(proc.stdout,),
                         name=f"fleet-pipe-{name}", daemon=True).start()
        deadline = time.monotonic() + self.startup_timeout_s
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"fleet worker {name} exited rc={proc.returncode} "
                    f"during startup")
            try:
                st, _ = _get_json("127.0.0.1", port, "/healthz", 2.0)
                if st == 200:
                    return SubprocessHandle(proc, "127.0.0.1", port)
            except OSError:
                pass
            time.sleep(0.25)
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except OSError:
            pass
        raise RuntimeError(f"fleet worker {name} not serving within "
                           f"{self.startup_timeout_s}s")


class _InProcHandle:
    """Worker handle over an in-process ``serve.Server`` — the smoke and
    test flavor of the ``SubprocessHandle`` protocol. ``kill`` is the
    RouterChaos dispatch-bomb (the in-process SIGKILL: the dispatch loop
    dies, waiters get terminal errors, the listener closes)."""

    def __init__(self, server):
        self.server = server
        self.host = "127.0.0.1"
        self.port = server.port

    def alive(self) -> bool:
        front = self.server.front
        return not front.dead and not front.stopped.is_set()

    def kill(self) -> None:
        from picotron_tpu.resilience.chaos import RouterChaos

        RouterChaos().kill(self.server)

    def terminate(self) -> None:
        self.server.front.begin_drain()

    def wait(self, timeout: Optional[float] = None) -> bool:
        try:
            self.server.drain_and_join(timeout=timeout)
        except OSError:
            pass  # a killed worker already closed its own listener
        return not self.alive()


class _SmokeLauncher:
    """In-process serve.Server workers over IDENTICAL tiny seed-0
    random-init models (same params → greedy outputs are a shared
    bit-exact oracle), streaming per token, on the paged KV layout so
    the drain-time prefix export path is live. The `make
    fleet-chaos-smoke` / test launcher."""

    def __init__(self, slots: int = 2):
        self.slots = int(slots)
        self.servers: dict = {}  # name -> serve.Server (chaos targeting)
        self._init = None

    def launch(self, name: str, role: str) -> _InProcHandle:
        import jax

        from picotron_tpu.config import Config
        from picotron_tpu.inference import InferenceEngine
        from picotron_tpu.models import llama
        from picotron_tpu.tools import serve
        from picotron_tpu.tools.generate import SMOKE_CONFIG
        from picotron_tpu.train import _ensure_devices

        cfg = Config.from_dict(SMOKE_CONFIG)
        cfg.inference.decode_block_len = 1
        cfg.inference.kv_layout = "paged"
        cfg.inference.kv_page_len = 8
        # an explicit, generous pool: the drill's admission spike must
        # queue (the watermark signal) rather than 429 on page pressure
        cfg.inference.kv_num_pages = 96
        if role != "both":
            cfg.inference.role = role
        _ensure_devices(cfg)
        engine = InferenceEngine(cfg, slots=self.slots, max_seq_len=64)
        if self._init is None:
            self._init = jax.jit(lambda k: llama.init_params(k, cfg.model))
        params = engine.shard_params(self._init(jax.random.PRNGKey(0)))
        # like the page pool above, the admission token budget must be
        # roomy enough that the spike QUEUES: the default slots *
        # max_seq_len (128) lets only ~3 of the drill's requests in per
        # worker before 429 — a shed the watermarks would never see
        srv = serve.Server(engine, params, port=0, token_budget=4096,
                           log=lambda *a, **k: None)
        srv.start()
        self.servers[name] = srv
        return _InProcHandle(srv)


# --------------------------------------------------------------------------- #
# the controller
# --------------------------------------------------------------------------- #


class FleetWorker:
    """One controller-owned worker. State machine::

        launching ──> up ──> draining ──> (removed)
             │         └───> dead ──────> (removed; budget-gated replace)
             └───────> failed ──────────> (removed; budget-gated replace)

    Transitions happen under the controller's ``_mu``; the scrape fields
    are written by the tick thread only."""

    __slots__ = ("name", "role", "state", "handle", "router_name",
                 "launched_t", "scrape", "scrape_t", "down_fails")

    def __init__(self, name: str, role: str):
        self.name = name
        self.role = role
        self.state = "launching"
        self.handle = None
        self.router_name = ""
        self.launched_t = 0.0
        self.scrape: dict = {}
        self.scrape_t = float("-inf")
        self.down_fails = 0


class FleetController:
    """Scrape → decide → actuate over a dynamic serve.py fleet (module
    docstring has the ladder). ``launcher`` provides ``launch(name,
    role) -> handle``; ``admin`` provides ``register/deregister``
    against the router; ``roles`` lists the roles managed independently
    (e.g. ``("prefill", "decode")`` for a disaggregated fleet)."""

    def __init__(self, cfg: FleetConfig, launcher, admin, *,
                 roles=("both",), chaos=None, obs: Optional[Obs] = None,
                 log=print, clock=time.monotonic):
        cfg.validate()
        self.cfg = cfg
        self.launcher = launcher
        self.admin = admin
        self.roles = tuple(roles)
        self.chaos = chaos
        self.obs = obs or Obs(enabled=True)
        self.registry = self.obs.registry
        self._log = log
        self._clock = clock
        self.workers: dict = {}  # name -> FleetWorker, guarded by _mu
        self._mu = threading.Lock()  # LEAF: state only, never I/O
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._threads: list = []  # launch/drain actuation threads
        self._seq = 0
        # tick-thread-only state (no lock by design, not oversight):
        # streaks, the replace budget, and the delayed-replace queue are
        # touched exclusively by the controller thread
        self._streaks = {r: {"high": 0, "low": 0, "last": float("-inf")}
                         for r in self.roles}
        self._budget = _RestartBudget(
            cfg.max_replaces, cfg.replace_backoff_s,
            cfg.replace_backoff_max_s, healthy_reset=cfg.healthy_reset_s)
        self._pending: list = []  # (role, due_t, reason, t0)

    # ---- lifecycle --------------------------------------------------------

    def start(self) -> None:
        for role in self.roles:
            for _ in range(self.cfg.min_workers):
                self._spawn_launch(role, "bootstrap", self._clock())
        self._thread = threading.Thread(target=self._run,
                                        name="fleet-controller",
                                        daemon=True)
        self._thread.start()

    def stop(self, drain_workers: bool = False,
             timeout: float = 60.0) -> None:
        """Stop the control loop (joins the tick + actuation threads).
        With ``drain_workers``, also walks every remaining worker through
        terminate → wait → deregister — the whole-fleet rollout."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        for t in list(self._threads):
            t.join(timeout=timeout)
        if not drain_workers:
            return
        with self._mu:
            remaining = list(self.workers.values())
        for w in remaining:
            h = w.handle
            if h is not None:
                try:
                    h.terminate()
                    h.wait(timeout)
                except Exception:  # noqa: BLE001 — best-effort teardown
                    pass
            if w.router_name:
                try:
                    self.admin.deregister(w.router_name)
                except Exception:  # noqa: BLE001
                    pass
            with self._mu:
                self.workers.pop(w.name, None)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must outlive
                # any single tick; a scrape/decide bug degrades to a
                # logged skip, never a silently dead autoscaler
                self._event("tick_error", error=repr(e))
            if self._stop.wait(self.cfg.scrape_interval_s):
                return

    # ---- observability ----------------------------------------------------

    def _event(self, evt: str, **fields) -> None:
        self._log(json.dumps({"evt": evt, "t": round(time.time(), 3),
                              **fields}), flush=True)

    def _decision(self, action: str, **fields) -> None:
        self.registry.counter(
            "picotron_fleet_decisions_total",
            "fleet scale decisions by action", action=action).inc()
        self._event(f"fleet_{action}", **fields)

    def decisions(self) -> dict:
        """{action: count} — the smoke/test accounting surface."""
        prom = parse_prometheus(self.registry.prometheus())
        out = {}
        for action in ("replace", "grow", "drain", "replace_exhausted"):
            v = prom.get('picotron_fleet_decisions_total'
                         f'{{action="{action}"}}')
            if v is not None:
                out[action] = int(v)
        return out

    # ---- scrape plane -----------------------------------------------------

    def _scrape(self, w: FleetWorker):
        """One worker's control-plane read → ``(status, scrape)`` with
        status ``ok`` | ``stale`` | ``down``. Stale (metrics unreadable,
        process not provably dead) parks the watermark streaks; only
        ``down`` — connection-level failure or a dead readyz — feeds the
        death counter."""
        if self.chaos is not None and self.chaos.scrape_stalls(w.name):
            return "stale", None
        h = w.handle
        t = self.cfg.scrape_timeout_s
        try:
            st, body = _get_json(h.host, h.port, "/readyz", t)
        except OSError:
            return "down", None
        if body.get("state") == "dead":
            return "down", None
        draining = (body.get("state") == "draining"
                    or bool(body.get("draining")))
        try:
            mst, text = _get_text(h.host, h.port, "/metrics", t)
        except OSError:
            return "stale", None
        if mst != 200:
            return "stale", None
        prom = parse_prometheus(text)
        queue_metric = ("picotron_prefill_queue_depth"
                        if w.role == "prefill"
                        else "picotron_queue_depth")
        return "ok", {
            "queue": prom.get(queue_metric, 0.0),
            "pool": prom.get("picotron_kv_pool_utilization", 0.0),
            "active": prom.get("picotron_active_slots", 0.0),
            "ttft_p95": hist_quantile(prom, "picotron_ttft_seconds",
                                      0.95),
            "draining": draining,
            # dp-sharded replicas: the controller sees one dp=N worker as
            # ONE bigger replica (capacity math scales by dp_size), not N
            # small ones; absent on old workers -> 1
            "dp_size": prom.get("picotron_dp_size", 1.0),
        }

    # ---- one control tick -------------------------------------------------

    def tick(self) -> None:
        """One scrape → decide → actuate pass (public so the unit tests
        drive the ladder deterministically with a fake clock)."""
        cfg = self.cfg
        now = self._clock()
        with self._mu:
            snapshot = list(self.workers.values())

        # 1. scrape (all I/O, no lock held)
        results = []
        for w in snapshot:
            if w.state not in ("up", "draining"):
                continue
            alive = w.handle is not None and w.handle.alive()
            status, scrape = ("down", None) if not alive \
                else self._scrape(w)
            results.append((w, alive, status, scrape))

        newly_dead = []
        with self._mu:
            for w, alive, status, scrape in results:
                if status == "ok":
                    w.scrape = scrape
                    w.scrape_t = now
                    w.down_fails = 0
                else:
                    if status == "down":
                        w.down_fails += 1
                    self.registry.counter(
                        "picotron_fleet_scrape_failures_total",
                        "failed worker scrapes", worker=w.name,
                        kind=status).inc()
                if w.state == "up" and (
                        not alive or w.down_fails >= cfg.hysteresis):
                    w.state = "dead"
                    newly_dead.append(w)
            failed = [w for w in self.workers.values()
                      if w.state == "failed"]
            for w in newly_dead + failed:
                self.workers.pop(w.name, None)

        # 2. ladder rung 1: replace dead/failed — budget-gated, never
        # cooloff-gated (lost capacity must not wait behind a scale
        # decision)
        for w in newly_dead + failed:
            if w.router_name:
                try:
                    self.admin.deregister(w.router_name)
                except Exception as e:  # noqa: BLE001 — router may be
                    # mid-restart; the replica is unroutable either way
                    self._event("deregister_failed", worker=w.name,
                                error=repr(e))
            uptime = max(0.0, now - w.launched_t) if w.launched_t else 0.0
            step = self._budget.record(uptime)
            if step is None:
                self._decision("replace_exhausted", worker=w.name,
                               role=w.role, was=w.state)
                continue
            kind, delay = step
            self._decision("replace", worker=w.name, role=w.role,
                           was=w.state, ladder=kind,
                           delay_s=round(delay, 3))
            self._pending.append((w.role, now + delay, "replace", now))

        # delayed replacements whose backoff has elapsed
        due = [p for p in self._pending if p[1] <= now]
        self._pending = [p for p in self._pending if p[1] > now]
        for role, _, reason, t0 in due:
            self._spawn_launch(role, reason, t0)

        # 3. rungs 2/3 per role: grow / drain on sustained watermarks
        with self._mu:
            workers_now = list(self.workers.values())
        fresh_horizon = (_FRESH_TICKS * cfg.scrape_interval_s
                         + cfg.scrape_timeout_s)
        for role in self.roles:
            mine = [w for w in workers_now
                    if w.role == role and w.state in ("launching", "up")]
            pending_n = sum(1 for r, _, _, _ in self._pending
                            if r == role)
            draining_n = sum(1 for w in workers_now
                             if w.role == role and w.state == "draining")
            fresh = [w for w in mine
                     if w.state == "up" and w.scrape
                     and now - w.scrape_t <= fresh_horizon]
            self.registry.gauge(
                "picotron_fleet_workers", "live workers by role",
                role=role).set(float(len(mine)))
            st = self._streaks[role]
            high = bool(fresh) and any(self._breach_high(w)
                                       for w in fresh)
            low = bool(fresh) and all(self._below_low(w) for w in fresh)
            st["high"] = st["high"] + 1 if high else 0
            st["low"] = st["low"] + 1 if (low and not high) else 0
            cooled = now - st["last"] >= cfg.cooloff_s
            if (st["high"] >= cfg.hysteresis and cooled
                    and len(mine) + pending_n + draining_n
                    < cfg.max_workers):
                st["last"] = now
                st["high"] = 0
                self._decision("grow", role=role, workers=len(mine))
                self._spawn_launch(role, "grow", now)
            elif (st["low"] >= cfg.hysteresis and cooled
                  and draining_n == 0 and pending_n == 0
                  and sum(1 for w in mine if w.state == "up")
                  > cfg.min_workers):
                victim = min(fresh, key=lambda w: (
                    w.scrape.get("queue", 0.0),
                    w.scrape.get("active", 0.0),
                    w.scrape.get("pool", 0.0)))
                st["last"] = now
                st["low"] = 0
                self._decision("drain", role=role, worker=victim.name)
                self._spawn_drain(victim)

    def _breach_high(self, w: FleetWorker) -> bool:
        s, cfg = w.scrape, self.cfg
        ttft = s.get("ttft_p95")
        return (s.get("queue", 0.0) > cfg.queue_high
                or s.get("pool", 0.0) > cfg.pool_high
                or (cfg.ttft_slo_s > 0 and ttft is not None
                    and ttft > cfg.ttft_slo_s))

    def _below_low(self, w: FleetWorker) -> bool:
        s, cfg = w.scrape, self.cfg
        return (s.get("queue", 0.0) < cfg.queue_low
                and s.get("pool", 0.0) < cfg.pool_low)

    # ---- actuation (off the tick thread) ----------------------------------

    def _spawn_launch(self, role: str, reason: str, t0: float) -> None:
        with self._mu:
            self._seq += 1
            w = FleetWorker(f"w{self._seq}-{role}", role)
            self.workers[w.name] = w
        t = threading.Thread(target=self._do_launch, args=(w, reason, t0),
                             name=f"fleet-launch-{w.name}", daemon=True)
        self._threads.append(t)
        t.start()

    def _spawn_drain(self, w: FleetWorker) -> None:
        with self._mu:
            w.state = "draining"
        t = threading.Thread(target=self._do_drain, args=(w,),
                             name=f"fleet-drain-{w.name}", daemon=True)
        self._threads.append(t)
        t.start()

    def _do_launch(self, w: FleetWorker, reason: str, t0: float) -> None:
        try:
            handle = retry(
                lambda: self.launcher.launch(w.name, w.role),
                attempts=self.cfg.launch_attempts, backoff=0.5,
                desc=f"fleet-launch-{w.role}")
        except Exception as e:  # noqa: BLE001 — every launch failure
            # (quota, port clash, dead config) walks the budget ladder
            with self._mu:
                w.state = "failed"
                self.workers[w.name] = w  # re-park for the tick to judge
            self._event("launch_failed", worker=w.name, role=w.role,
                        reason=reason, error=repr(e))
            return
        try:
            router_name = self.admin.register(handle.host, handle.port)
        except Exception as e:  # noqa: BLE001
            # an unregistered worker serves nothing: reap it and let the
            # budget ladder decide whether to try again
            self._event("register_failed", worker=w.name, error=repr(e))
            try:
                handle.terminate()
                handle.wait(10.0)
            except Exception:  # noqa: BLE001
                pass
            with self._mu:
                w.handle = handle
                w.state = "failed"
                self.workers[w.name] = w
            return
        now = self._clock()
        with self._mu:
            w.handle = handle
            w.router_name = router_name
            w.launched_t = now
            w.state = "up"
        hist = ("picotron_fleet_replace_seconds" if reason == "replace"
                else "picotron_fleet_scale_up_seconds")
        self.registry.histogram(
            hist, "decision-to-registered latency").observe(now - t0)
        self.obs.tracer.record(f"fleet_{reason}", t0, now, worker=w.name,
                               role=w.role, port=handle.port)
        self._event("worker_up", worker=w.name, role=w.role,
                    port=handle.port, reason=reason,
                    latency_s=round(now - t0, 3))

    def _do_drain(self, w: FleetWorker) -> None:
        """The drain protocol: export the victim's hottest prefixes to a
        survivor (soft), arm the stop surface, POST /drain (202, or 409
        when the stop signal already began one), wait out the in-flight
        work, deregister. A worker that blows ``drain_timeout_s`` is
        killed — a drain must terminate."""
        cfg = self.cfg
        h = w.handle
        t0 = self._clock()
        if cfg.export_prefixes:
            self._export_prefixes(w)
        try:
            h.terminate()
        except Exception:  # noqa: BLE001
            pass
        st = 0
        try:
            st, _ = _post_json(h.host, h.port, "/drain", {},
                               cfg.scrape_timeout_s)
        except OSError:
            pass  # drain already finished and closed the listener
        clean = False
        try:
            clean = h.wait(cfg.drain_timeout_s)
        except Exception:  # noqa: BLE001
            pass
        if not clean:
            try:
                h.kill()
                h.wait(10.0)
            except Exception:  # noqa: BLE001
                pass
        try:
            self.admin.deregister(w.router_name)
        except Exception as e:  # noqa: BLE001
            self._event("deregister_failed", worker=w.name,
                        error=repr(e))
        with self._mu:
            self.workers.pop(w.name, None)
        now = self._clock()
        self.obs.tracer.record("fleet_drain", t0, now, worker=w.name,
                               role=w.role, clean=clean)
        self._event("worker_drained", worker=w.name, role=w.role,
                    drain_status=st, clean=clean,
                    latency_s=round(now - t0, 3))

    def _export_prefixes(self, w: FleetWorker) -> int:
        """Relocate the victim's hottest radix prefixes to one surviving
        decode-capable worker through the PR 15 page transport. Soft by
        contract: any failure (contiguous layout, empty cache, dead
        survivor) skips the export — a drain never blocks on it."""
        cfg = self.cfg
        with self._mu:
            survivors = [x for x in self.workers.values()
                         if x.name != w.name and x.state == "up"
                         and x.role in ("both", "decode")]
        if not survivors:
            return 0
        tgt = survivors[0].handle
        t = max(cfg.scrape_timeout_s, 10.0)
        moved = 0

        def count(outcome: str) -> None:
            self.registry.counter(
                "picotron_fleet_prefix_exports_total",
                "drain-time prefix-relocation attempts by outcome",
                outcome=outcome).inc()

        try:
            pst, body = _get_json(
                w.handle.host, w.handle.port,
                f"/kv/prefixes?limit={cfg.export_prefix_limit}", t)
            if pst != 200:
                # contiguous layout (503) or a worker already gone: the
                # path RAN and chose to skip — count it so the drill can
                # pin the protocol without requiring a warm cache
                count("unsupported")
                return 0
            entries = body.get("prefixes", [])
            if not entries:
                count("empty")  # a cold victim has nothing to move
            for entry in entries:
                gst, pages = _post_json(
                    w.handle.host, w.handle.port, "/kv/pages",
                    {"ids": entry["ids"], "tenant": entry.get("tenant")},
                    t)
                if gst != 200 or not pages.get("matched"):
                    count("miss")
                    continue
                ist, _ = _post_json(tgt.host, tgt.port, "/kv/import",
                                    {"kv": pages["kv"]}, t)
                if ist == 200:
                    moved += 1
                    count("moved")
                else:
                    count("import_failed")
        except (OSError, ValueError, KeyError, TypeError) as e:
            count("error")
            self._event("prefix_export_skipped", worker=w.name,
                        error=repr(e))
        if moved:
            self._event("prefix_export", worker=w.name, moved=moved,
                        to=survivors[0].name)
        return moved


# --------------------------------------------------------------------------- #
# smoke drive (`make fleet-chaos-smoke`) + CLI
# --------------------------------------------------------------------------- #


def _smoke() -> int:
    """The ISSUE 17 acceptance drill end to end, zero operator actions:
    (1) SIGKILL a worker under a live stream → router replays the client
    stream exactly-once and greedy bit-identical, the controller
    replaces the dead worker within its budget ladder; (2) a stalled
    scrape plane does NOT trigger a replacement storm; (3) an injected
    admission spike → the controller grows within its cooloff window and
    nothing is shed; (4) the post-spike scale-down drain loses zero
    in-flight requests — with the ``picotron_fleet_*`` counters
    accounting for every decision. Returns an exit code."""
    from picotron_tpu.config import RouterConfig
    from picotron_tpu.resilience.chaos import FleetChaos, RouterChaos
    from picotron_tpu.tools import serve
    from picotron_tpu.tools.router import (
        RouterServer, _stream_post, _wait_for)

    fail: list = []

    def check(name: str, ok) -> None:
        print(f"fleet-chaos-smoke: {name}: {'ok' if ok else 'FAIL'}",
              flush=True)
        if not ok:
            fail.append(name)

    rchaos = RouterChaos()
    fchaos = FleetChaos()
    # probe/staleness tolerances are LOOSE here on purpose: the whole
    # fleet shares one interpreter, so 10 concurrent spike streams starve
    # prober threads past tight timeouts — breakers would open and
    # scrapes would stale out from GIL contention, not from any fault.
    # The breaker/staleness mechanics have their own drills (router
    # --smoke); this drill is about the CONTROLLER's decisions.
    rcfg = RouterConfig(
        probe_interval_s=0.05, probe_timeout_s=2.0, breaker_failures=5,
        breaker_backoff_s=0.05, breaker_backoff_max_s=0.4,
        breaker_probe_attempts=4, scrape_stale_s=10.0,
        stream_idle_timeout_s=60.0, connect_timeout_s=20.0)
    rs = RouterServer([], rcfg, chaos=rchaos, allow_empty=True,
                      log=lambda *a, **k: None)
    rs.start()
    router = rs.router
    launcher = _SmokeLauncher(slots=2)
    fcfg = FleetConfig(
        scrape_interval_s=0.05, scrape_timeout_s=2.0, hysteresis=2,
        cooloff_s=0.75, queue_high=0.5, queue_low=0.25, pool_high=0.9,
        pool_low=0.5, min_workers=3, max_workers=5, max_replaces=3,
        replace_backoff_s=0.05, replace_backoff_max_s=0.4,
        drain_timeout_s=60.0, export_prefixes=True,
        export_prefix_limit=2)
    ctl = FleetController(fcfg, launcher, RouterAdmin("127.0.0.1",
                                                      rs.port),
                          chaos=fchaos, log=lambda *a, **k: None)

    def up_workers():
        with ctl._mu:
            return [w for w in ctl.workers.values() if w.state == "up"]

    def fleet_prom(name: str) -> float:
        prom = parse_prometheus(ctl.registry.prometheus())
        return sum(v for k, v in prom.items() if k.startswith(name))

    client_errors: list = []

    def run_routed(spec: dict):
        st, rows = _stream_post(rs.port, spec)
        toks = [r["token"] for r in rows if r.get("event") == "token"]
        done = [r for r in rows if r.get("event") == "done"]
        ok = (st == 200 and len(done) == 1
              and done[0]["finish_reason"] == "length"
              and done[0]["tokens"] == toks)
        if not ok:
            client_errors.append((spec.get("request_id"), st, rows[-1:]))
        return ok, toks

    t_start = time.monotonic()
    ctl.start()
    try:
        # ---- bootstrap: controller grows the fleet to min_workers ----
        check("bootstrap_three_up", _wait_for(
            lambda: len(up_workers()) == 3, timeout=180))
        check("bootstrap_router_eligible",
              router.wait_eligible(3, timeout=30))
        scale_up_latency_s = time.monotonic() - t_start

        prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3]
        spec = {"prompt": prompt, "max_new_tokens": 24}

        # greedy oracle: all workers hold identical seed-0 params, so
        # any one of them is the bit-exact reference
        any_port = up_workers()[0].handle.port
        st, body = serve._post(any_port, spec)
        oracle = body.get("tokens") if st == 200 else None
        check("oracle", st == 200 and len(oracle) == 24)
        ok, toks = run_routed({**spec, "request_id": "flt-0"})
        check("routed_bit_identical", ok and toks == oracle)

        # seed every worker's radix cache so the eventual drain victim
        # has hot prefixes to relocate
        for w in up_workers():
            serve._post(w.handle.port, spec)

        # ---- drill 1: SIGKILL a worker holding an in-flight stream ----
        killed: dict = {}

        def kill_at(i, row) -> None:
            if i == 4 and not killed:
                busy = None
                for nm, rep in router.replicas.items():
                    with rep._mu:
                        if rep.inflight > 0:
                            busy = nm
                            break
                for w in up_workers():
                    if w.router_name == busy:
                        killed["worker"] = w.name
                        fchaos.kill_worker(w.handle)
                        return
                # stream placed before our snapshot: kill any up worker
                w = up_workers()[0]
                killed["worker"] = w.name
                fchaos.kill_worker(w.handle)

        t_kill = time.monotonic()
        st, rows = _stream_post(rs.port,
                                {**spec, "request_id": "flt-kill",
                                 "stream": True}, on_token=kill_at)
        toks = [r["token"] for r in rows if r.get("event") == "token"]
        done = [r for r in rows if r.get("event") == "done"]
        check("kill_exactly_once_bit_identical",
              st == 200 and killed and len(done) == 1
              and done[0]["replays"] == 1 and done[0]["tokens"] == toks
              and toks == oracle)
        check("kill_replaced_within_budget", _wait_for(
            lambda: (ctl.decisions().get("replace", 0) == 1
                     and len(up_workers()) == 3
                     and all(w.name != killed.get("worker")
                             for w in up_workers())), timeout=180))
        replace_latency_s = time.monotonic() - t_kill
        check("kill_router_reconverged", router.wait_eligible(3,
                                                              timeout=30))
        check("replace_histogram_counted",
              fleet_prom("picotron_fleet_replace_seconds_count") == 1)

        # ---- drill 2: stall the scrape — stale is NOT dead ----
        victim = up_workers()[0]
        fchaos.stall_scrape(victim.name)
        stall_fails0 = fleet_prom("picotron_fleet_scrape_failures_total")
        time.sleep(fcfg.scrape_interval_s * 8)  # >> hysteresis ticks
        with ctl._mu:
            still_up = ctl.workers.get(victim.name)
            still_up = still_up is not None and still_up.state == "up"
        check("scrape_stall_not_death",
              still_up and ctl.decisions().get("replace", 0) == 1
              and fleet_prom("picotron_fleet_scrape_failures_total")
              > stall_fails0)
        fchaos.unstall_scrape(victim.name)
        ok, toks = run_routed({**spec, "request_id": "flt-stall"})
        check("scrape_stall_serving_unaffected", ok and toks == oracle)

        # ---- drill 3: admission spike → grow within cooloff ----
        fchaos.inject_spike(10)
        n_spike = fchaos.take_spike()
        grow0 = ctl.decisions().get("grow", 0)
        t_spike = time.monotonic()
        spike_done: list = []
        spike_ttfts: list = []

        def spike_one(i: int) -> None:
            t0 = time.monotonic()
            first: dict = {}

            def on_tok(j, row):
                if j == 0:
                    first["t"] = time.monotonic() - t0

            ok, toks = run_routed({**spec,
                                   "request_id": f"flt-spike-{i}"})
            spike_done.append(ok and toks == oracle)
            if first.get("t") is not None:
                spike_ttfts.append(first["t"])

        threads = [threading.Thread(target=spike_one, args=(i,))
                   for i in range(n_spike)]
        for t in threads:
            t.start()
        grew = _wait_for(
            lambda: ctl.decisions().get("grow", 0) > grow0, timeout=30)
        grow_decision_s = time.monotonic() - t_spike
        for t in threads:
            t.join(timeout=300)
        check("spike_grow_decision", grew)
        # the slack term absorbs scheduler starvation on a loaded small
        # box (the spike itself steals the tick thread's CPU) — what the
        # check pins is that the grow lands DURING the spike, promptly
        # after the cooloff gate opens, not after the load has passed
        check("spike_grow_within_cooloff_window",
              grew and grow_decision_s
              <= fcfg.cooloff_s + 20 * fcfg.scrape_interval_s + 8.0)
        check("spike_nothing_shed",
              len(spike_done) == n_spike and all(spike_done)
              and router.stats()["requests"]["shed"] == 0)
        # the histogram count is MONOTONIC (bootstrap seeded it at 3):
        # polling len(up_workers()) >= 4 instead would race the drain
        # rung, which may take the grown worker back down the moment the
        # spike's load falls — before this thread ever observes 4 up
        check("spike_worker_joined", _wait_for(
            lambda: fleet_prom(
                "picotron_fleet_scale_up_seconds_count") >= 4,
            timeout=180))

        # ---- drill 4: scale-down drain loses zero in-flight ----
        # keep a trickle of live requests flowing while the controller
        # drains back to min_workers; every one must complete
        trickle_stop = threading.Event()
        trickle_ok: list = []

        def trickle() -> None:
            i = 0
            while not trickle_stop.is_set():
                ok, toks = run_routed(
                    {**spec, "request_id": f"flt-trk-{i}"})
                trickle_ok.append(ok and toks == oracle)
                i += 1

        tt = threading.Thread(target=trickle)
        tt.start()
        drained = _wait_for(
            lambda: (ctl.decisions().get("drain", 0) >= 1
                     and len(up_workers()) == 3), timeout=120)
        trickle_stop.set()
        tt.join(timeout=300)
        check("scale_down_drained", drained)
        check("drain_zero_inflight_lost",
              len(trickle_ok) > 0 and all(trickle_ok))
        check("drain_deregistered", _wait_for(
            lambda: len(router.replicas) == 3, timeout=30))
        check("drain_prefix_export",
              fleet_prom("picotron_fleet_prefix_exports_total") >= 1)

        # ---- accounting: every decision counted, nothing exhausted ----
        d = ctl.decisions()
        check("decision_accounting",
              d.get("replace", 0) == 1 and d.get("grow", 0) >= 1
              and d.get("drain", 0) >= 1
              and d.get("replace_exhausted", 0) == 0)
        check("workers_gauge",
              fleet_prom("picotron_fleet_workers") == 3.0)
        for err in client_errors[:5]:
            print(f"fleet-chaos-smoke: client error: {err}", flush=True)
        check("zero_client_errors", not client_errors)
        print(json.dumps({
            "scale_up_latency_s": round(scale_up_latency_s, 3),
            "replace_latency_s": round(replace_latency_s, 3),
            "grow_decision_s": round(grow_decision_s, 3),
            "spike_requests": n_spike,
        }), flush=True)
    finally:
        ctl.stop(drain_workers=True)
        rs.stop()
    return 1 if fail else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="elastic fleet controller over serve.py workers "
                    "(scrape/decide/actuate: grow, drain, replace "
                    "against a router's dynamic replica set)")
    ap.add_argument("--router", default="", metavar="HOST:PORT",
                    help="router admin address (POST/DELETE /replicas)")
    ap.add_argument("--config", default="",
                    help="serve.py experiment config JSON for launched "
                         "workers")
    ap.add_argument("--fleet-config", default="",
                    help="JSON file of FleetConfig overrides")
    ap.add_argument("--roles", default="both",
                    help="comma-separated roles to manage "
                         "(both | prefill,decode)")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--smoke", action="store_true",
                    help="in-process kill/spike/stall/drain chaos drill "
                         "(the `make fleet-chaos-smoke` target)")
    args = ap.parse_args(argv)

    if args.smoke:
        rc = _smoke()
        print(f"fleet-chaos-smoke: {'PASS' if rc == 0 else 'FAIL'}",
              flush=True)
        return rc

    if not args.router or not args.config:
        raise SystemExit("pass --router HOST:PORT and --config "
                         "CONFIG.json (or --smoke)")
    host, _, port = args.router.rpartition(":")
    if not host or not port:
        raise SystemExit(f"--router must be HOST:PORT, got "
                         f"{args.router!r}")
    if args.fleet_config:
        with open(args.fleet_config) as f:
            fcfg = FleetConfig.from_dict(json.load(f))
    else:
        fcfg = FleetConfig()
    launcher = SubprocessLauncher(args.config, slots=args.slots)
    ctl = FleetController(
        fcfg, launcher, RouterAdmin(host, int(port)),
        roles=tuple(r.strip() for r in args.roles.split(",") if r.strip()))
    ctl.start()
    ctl._event("fleet", router=args.router, roles=list(ctl.roles))
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        ctl.stop(drain_workers=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
