"""Summarize a jax.profiler trace: where does the step time go?

The reference has no profiler at all (SURVEY.md §5.1); this closes the
round-3 VERDICT's "profiler-driven MFU pass" loop on top of train.py's
trace window (logging.profile_start/stop). It reads the XPlane protobuf
that jax.profiler.start_trace writes under
``<dir>/plugins/profile/<run>/*.xplane.pb`` and prints a cost breakdown
by HLO category and by individual op, so the top HBM/compute consumer of
the winning bench config is a committed number instead of a guess.

Usage:
    python -m picotron_tpu.tools.analyze_trace <profile_dir> [--top N]

``<profile_dir>`` may be the directory passed to start_trace, the
``plugins/profile/<run>`` dir, or a direct ``*.xplane.pb`` path. Output is
a human-readable table plus one machine-readable JSON line (categories in
percent of device-active time) for docs/scripts to capture.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from collections import defaultdict


def find_xplane(path: str) -> str:
    if os.path.isfile(path):
        return path
    hits = sorted(glob.glob(os.path.join(path, "**", "*.xplane.pb"),
                            recursive=True))
    if not hits:
        raise FileNotFoundError(f"no *.xplane.pb under {path!r} — did the "
                                f"profiler window run?")
    return hits[-1]  # newest run sorts last (timestamped dirs)


def load_xspace(path: str):
    # tensorflow is in the image for its tsl protobufs only; defer the
    # (slow, noisy) import so --help and error paths stay instant
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xspace = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xspace.ParseFromString(f.read())
    return xspace


def device_planes(xspace):
    """TPU device planes if present, else the busiest non-host plane, else
    the host plane (CPU-only traces, used by the self-test)."""
    tpu = [p for p in xspace.planes if "/device:TPU" in p.name
           and "SparseCore" not in p.name]
    if tpu:
        return tpu

    def busiest(planes):
        pool = sorted(planes,
                      key=lambda p: sum(len(l.events) for l in p.lines))
        return pool[-1:] if pool and any(
            len(l.events) for l in pool[-1].lines) else []

    return (busiest([p for p in xspace.planes
                     if not p.name.startswith("/host")])
            or busiest(xspace.planes))


CATEGORY_RULES = (
    # (category, name substrings) — first match wins; names are lowercased.
    # tpu_custom_call is how Mosaic/Pallas kernels appear in XLA traces.
    ("pallas kernel", ("tpu_custom_call", "custom-call", "mosaic")),
    ("matmul", ("dot", "convolution", "einsum")),
    ("collective", ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all", "psum")),
    ("copy/transpose", ("copy", "transpose", "bitcast", "reshape")),
    ("host transfer", ("infeed", "outfeed", "send", "recv",
                       "host")),
    ("scatter/gather", ("scatter", "gather", "dynamic-slice",
                        "dynamic-update-slice")),
    ("elementwise/fusion", ("fusion", "loop", "add", "multiply", "select",
                            "exponential", "divide", "subtract", "rsqrt",
                            "maximum", "reduce", "broadcast", "iota",
                            "compare", "convert", "tanh", "log")),
)


def classify(name: str, hlo_category: str) -> str:
    """Prefer the profiler's own hlo category stat, fall back to name
    heuristics. Either way normalize into the coarse buckets above."""
    for probe in (hlo_category.lower(), name.lower()):
        if not probe:
            continue
        for cat, keys in CATEGORY_RULES:
            if any(k in probe for k in keys):
                return cat
    return "other"


def summarize(xspace, top: int = 15):
    """Aggregate per-op self time on device planes. Returns a dict with
    total_ms, per-category ms and the top ops."""
    op_ps: dict[str, int] = defaultdict(int)
    op_cat: dict[str, str] = {}
    plane_names = []
    t_min = t_max = None
    for plane in device_planes(xspace):
        plane_names.append(plane.name)
        stat_names = {i: m.name for i, m in plane.stat_metadata.items()}
        for line in plane.lines:
            lname = line.name.lower()
            # op-level lines only; step/module/scope lines double-count.
            # TPU planes call it "XLA Ops"; CPU traces (self-test path) put
            # op events on the PjRt client line.
            if not ("xla ops" in lname or lname == "ops"
                    or lname.startswith("tf_xlapjrt")):
                continue
            # XLine offsets are relative to the line's own start timestamp
            line_t0_ps = line.timestamp_ns * 1000
            for ev in line.events:
                md = plane.event_metadata.get(ev.metadata_id)
                name = md.name if md else f"op_{ev.metadata_id}"
                if name.startswith("end: ") or "::" in name:
                    continue  # CPU client region end/listener markers
                cat = ""
                for st in ev.stats:
                    if stat_names.get(st.metadata_id) == "hlo_category":
                        # the oneof fields live directly on XStat; a
                        # ref_value indexes the stat_metadata name table
                        cat = (st.str_value
                               or stat_names.get(st.ref_value, ""))
                op_ps[name] += ev.duration_ps
                if name not in op_cat:
                    op_cat[name] = classify(name, cat)
                start = line_t0_ps + ev.offset_ps
                end = start + ev.duration_ps
                t_min = start if t_min is None else min(t_min, start)
                t_max = end if t_max is None else max(t_max, end)
    span_ps = (t_max - t_min) if t_min is not None else 0
    total_ps = sum(op_ps.values())
    cat_ps: dict[str, int] = defaultdict(int)
    for name, ps in op_ps.items():
        cat_ps[op_cat[name]] += ps
    top_ops = sorted(op_ps.items(), key=lambda kv: -kv[1])[:top]
    return {
        "planes": plane_names,
        "total_ms": total_ps / 1e9,
        "span_ms": span_ps / 1e9,
        "categories_ms": {c: ps / 1e9 for c, ps in
                          sorted(cat_ps.items(), key=lambda kv: -kv[1])},
        "top_ops": [(n, ps / 1e9, op_cat[n]) for n, ps in top_ops],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("profile_dir")
    ap.add_argument("--top", type=int, default=15)
    args = ap.parse_args(argv)

    path = find_xplane(args.profile_dir)
    s = summarize(load_xspace(path), top=args.top)
    total = s["total_ms"]
    if total == 0:
        print(f"no device op events found in {path}", file=sys.stderr)
        return 1

    print(f"trace: {path}")
    print(f"planes: {', '.join(s['planes'])}")
    print(f"device-active op time: {total:.2f} ms over a {s['span_ms']:.2f} "
          f"ms span (gaps = host/dispatch idle)")
    print("\nby category (% of device-active time):")
    for cat, ms in s["categories_ms"].items():
        print(f"  {cat:<20} {ms:9.2f} ms  {100 * ms / total:5.1f}%")
    print(f"\ntop {args.top} ops:")
    for name, ms, cat in s["top_ops"]:
        print(f"  {ms:9.2f} ms  {100 * ms / total:5.1f}%  [{cat}] {name}")
    print()
    print(json.dumps({
        "trace": path,
        "active_ms": round(total, 3),
        "span_ms": round(s["span_ms"], 3),
        "categories_pct": {c: round(100 * ms / total, 2)
                           for c, ms in s["categories_ms"].items()},
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
