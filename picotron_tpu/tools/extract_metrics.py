"""Extract per-step training metrics into CSV benchmark tables.

Re-build of the reference's ``extract_metrics.py`` (:1-210): recover the
throughput fields from each run, drop the first 3 steps as compile/cache
warmup and average the rest (:82-89), write a per-run ``metrics.csv`` and a
sweep-level ``global_metrics.csv`` whose topology columns are parsed from
the run-folder naming convention ``...dp2_tp4_pp2_cp1_mbs1_ga8_sl2048...``
(:8-23,:147-195).

Two sources per run dir, structured preferred (docs/OBSERVABILITY.md):

1. ``metrics.jsonl`` — the per-step JSONL ``picotron_tpu.train`` writes
   (``$PICOTRON_METRICS_JSONL`` / ``obs.metrics_jsonl``): parsed directly,
   no regex, field names already ours.
2. The legacy log scrape — regex over the per-step log line
   (train.py log line; reference train.py:247-259) — ``Tokens/s/chip``
   instead of ``Tokens/s/GPU``, plus optional ``MFU:`` and
   ``Memory usage:`` fields. Kept for logs from runs that predate the
   JSONL (or had obs disabled).
"""

from __future__ import annotations

import argparse
import csv
import glob
import json
import os
import re
from typing import Optional

import numpy as np

_SUFFIX = {"T": 1e12, "B": 1e9, "M": 1e6, "K": 1e3}


def from_readable_format(s: str) -> float:
    """'1.23M' -> 1230000.0 (inverse of utils.to_readable_format)."""
    s = s.strip().upper()
    if s and s[-1] in _SUFFIX:
        return float(s[:-1]) * _SUFFIX[s[-1]]
    return float(s)


def parse_folder_name(folder_name: str) -> dict:
    """Pull topology numbers out of a run-dir name (reference :8-23), with a
    'cp' field added since CP is part of this framework's sweep axis set.
    Keys are anchored so one token can't match inside another (e.g. the 'p2'
    of 'warmup2' never reads as pp=2, 'sl' never matches inside 'mbsl...')."""
    out = {}
    for key, col in (("dp", "dp"), ("tp", "tp"), ("pp", "pp"), ("cp", "cp"),
                     ("mbs", "micro_batch_size"), ("ga", "grad_acc"),
                     ("sl", "seq_len")):
        m = re.search(rf"(?<![a-z0-9]){key}(\d+)(?![a-z0-9])",
                      folder_name.lower())
        out[col] = int(m.group(1)) if m else None
    return out


LINE_RE = re.compile(
    r"Step:\s*(?P<step>\d+).*?"
    r"Loss:\s*(?P<loss>[\d.]+(?:e[+-]?\d+)?).*?"
    r"Tokens/s:\s*(?P<tok_s>[\d.]+[KMBT]?)\s*\|\s*"
    r"Tokens/s/chip:\s*(?P<tok_s_chip>[\d.]+[KMBT]?)"
)
MFU_RE = re.compile(r"MFU:\s*([\d.]+)%")
MEM_RE = re.compile(r"Memory usage:\s*([\d.]+)GB")


def parse_log_line(line: str) -> Optional[dict]:
    m = LINE_RE.search(line)
    if not m:
        return None
    mfu = MFU_RE.search(line)
    mem = MEM_RE.search(line)
    return {
        "step": int(m.group("step")),
        "loss": float(m.group("loss")),
        "tokens_per_sec": from_readable_format(m.group("tok_s")),
        "tokens_per_sec_per_chip": from_readable_format(m.group("tok_s_chip")),
        "mfu_pct": float(mfu.group(1)) if mfu else None,
        "memory_gb": float(mem.group(1)) if mem else None,
    }


def parse_log_file(path: str) -> list[dict]:
    rows = []
    with open(path, errors="replace") as f:
        for line in f:
            row = parse_log_line(line)
            if row:
                rows.append(row)
    return rows


WARMUP_STEPS = 3  # reference extract_metrics.py:82-89


def summarize(rows: list[dict]) -> Optional[dict]:
    """Mean over steps after dropping the first WARMUP_STEPS (compile +
    cache-fill on TPU; CUDA-graph/alloc warmup in the reference)."""
    rows = rows[WARMUP_STEPS:]
    if not rows:
        return None

    def mean_of(key):
        vals = [r[key] for r in rows if r[key] is not None]
        return float(np.mean(vals)) if vals else None

    return {
        "num_steps": len(rows),
        "final_loss": rows[-1]["loss"],
        "tokens_per_sec": mean_of("tokens_per_sec"),
        "tokens_per_sec_per_chip": mean_of("tokens_per_sec_per_chip"),
        "mfu_pct": mean_of("mfu_pct"),
        "memory_gb": mean_of("memory_gb"),
    }


def find_log(run_dir: str) -> Optional[str]:
    for pat in ("log.out", "*.out", "*.log"):
        hits = sorted(glob.glob(os.path.join(run_dir, pat)))
        if hits:
            return hits[0]
    return None


JSONL_NAME = "metrics.jsonl"

_ROW_KEYS = ("loss", "tokens_per_sec", "tokens_per_sec_per_chip",
             "mfu_pct", "memory_gb")


def find_metrics_jsonl(run_dir: str) -> Optional[str]:
    """The structured per-step metrics file, when the run wrote one."""
    path = os.path.join(run_dir, JSONL_NAME)
    return path if os.path.isfile(path) else None


def parse_jsonl_file(path: str) -> list[dict]:
    """Rows in exactly ``parse_log_file``'s shape, read from the per-step
    JSONL instead of the log regex. Rows without a ``step`` (the terminal
    registry-summary row, future event rows) and unparseable lines are
    skipped — a truncated last line from a killed run must not lose the
    steps before it."""
    rows = []
    with open(path, errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict) or "step" not in rec:
                continue
            try:
                row = {"step": int(rec["step"])}
                for k in _ROW_KEYS:
                    v = rec.get(k)
                    row[k] = None if v is None else float(v)
            except (TypeError, ValueError):
                continue
            if row["loss"] is None:
                continue
            rows.append(row)
    return rows


def _write_csv(path: str, rows: list[dict]) -> None:
    if not rows:
        return
    with open(path, "w", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
        writer.writeheader()
        writer.writerows(rows)


def extract(inp_dir: str) -> list[dict]:
    """Per run dir: metrics.csv with per-step rows; at the sweep root:
    global_metrics.csv with one summary row per run (reference :147-195)."""
    global_rows = []
    for root, _dirs, files in sorted(os.walk(inp_dir)):
        # structured source first: a run that wrote the per-step JSONL is
        # parsed without the regex path (and without needing a log at all)
        jsonl = find_metrics_jsonl(root)
        rows = parse_jsonl_file(jsonl) if jsonl else []
        if not rows:
            # legacy path: regex-scrape the log (runs predating the
            # JSONL, obs disabled, or an empty/corrupt JSONL)
            has_log = find_log(root)
            rows = parse_log_file(has_log) if has_log else []
        if not rows:
            continue
        _write_csv(os.path.join(root, "metrics.csv"), rows)
        summary = summarize(rows)
        if summary is None:
            print(f"{root}: fewer than {WARMUP_STEPS + 1} steps, skipped")
            continue
        name = os.path.basename(os.path.normpath(root))
        global_rows.append({"run": name, **parse_folder_name(name), **summary})
    _write_csv(os.path.join(inp_dir, "global_metrics.csv"), global_rows)
    return global_rows


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="Extract metrics from training logs")
    p.add_argument("inp_dir", help="sweep directory containing run subdirs")
    args = p.parse_args(argv)
    rows = extract(args.inp_dir)
    for r in rows:
        tsc = r["tokens_per_sec_per_chip"]
        mfu = f"{r['mfu_pct']:.2f}%" if r["mfu_pct"] is not None else "n/a"
        print(f"{r['run']}: {tsc:,.0f} tokens/s/chip, MFU {mfu}, "
              f"final loss {r['final_loss']:.4f} over {r['num_steps']} steps")
    print(f"wrote {os.path.join(args.inp_dir, 'global_metrics.csv')} "
          f"({len(rows)} runs)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
