"""Experiment tooling CLIs (the reference's L6 layer, SURVEY.md §1):
create_config / submit_jobs / extract_metrics."""
