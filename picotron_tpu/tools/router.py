"""Multi-replica serving fabric: one logical endpoint over N serve.py replicas.

    python -m picotron_tpu.tools.router --replica 10.0.0.1:8000 \
        --replica 10.0.0.2:8000 --port 9000

`tools/serve.py` made ONE replica chaos-survivable; this is the layer that
makes a FLEET of them look like one endpoint (docs/SERVING.md
"Multi-replica fabric"). Stdlib only, like the front end it fronts. Three
responsibilities:

**Placement** — each request is scored onto a replica by prefix affinity
plus load. The affinity key is the longest page-aligned prompt prefix
(``RouterConfig.affinity_page_len``; match the fleet's
``inference.kv_page_len``): requests sharing a system prompt rendezvous-
hash to the same replica, which already holds those radix-cache pages, so
the shared prefix is prefilled once per CLUSTER instead of once per
request. The affinity pick wins only while its load score — queue depth +
router-inflight, active slots, KV pool occupancy, and TTFT p95, every
term scraped from the replica's own ``/metrics`` (the PR-10 instruments)
— stays within ``affinity_load_slack`` of the least-loaded candidate;
past that, least-loaded wins. Replicas whose last good scrape is older
than ``scrape_stale_s`` fall out of the candidate set entirely: unknown
load is unplaceable load.

**Failure handling** — a prober thread per replica walks
``/healthz`` + ``/readyz`` + ``/metrics`` on ``probe_interval_s``. A
readyz 503 whose body says ``{"state": "draining"}`` is GRACEFUL: the
replica leaves the candidate set but its circuit breaker is untouched
(that is the drain-vs-dead distinction serve.py's readyz body exists
for). Hard failures (unreachable, healthz 503, readyz stalled/dead)
count consecutively: at ``breaker_failures`` the breaker opens and the
prober switches to an exponential reprobe ladder driven by
``resilience.retry``; the first successful reprobe flips half-open,
where ONE trial request (or ``breaker_failures`` consecutive clean
probes) decides closed vs open again. A scrape-only failure is SOFT —
health state still updates, but the scrape goes stale and the replica
drops out of placement without tripping the breaker. When no replica is
eligible the router answers 503 with ``Retry-After``.

**Prefill/decode disaggregation** (``RouterConfig.disagg``,
docs/SERVING.md "Disaggregated prefill/decode") — when the fleet holds
``inference.role: prefill`` replicas, each prompt's prefill routes to
its affinity prefill worker (``POST /kv/export``), the finished KV pool
pages ride to the least-loaded DECODE placement inside the ``/generate``
body (the replica seats them + the first token with zero prefill
dispatches), and the token stream splices to the client as usual. A
prefill worker dying mid-export or a page stream severed mid-transfer
falls back to self-prefill at the decode placement — nothing was
streamed, so the client cannot tell. Prefill-only replicas are never
decode candidates (they would otherwise score as idle decode targets).
On a plain placement that escaped its affinity owner,
``RouterConfig.prefix_fetch`` pulls the owner's longest cached prefix
(``/kv/pages`` -> ``/kv/import``) so shared prefixes still prefill once
per cluster.

**Mid-stream failover replay** — the router always streams from the
replica and records every token it delivers to the client. When a
replica dies mid-stream (connection drop, torn NDJSON row, 5xx, a
``finish_reason: "error"`` from a dying dispatch loop), the router
re-submits the ORIGINAL prompt *plus the already-delivered tokens* as
the new prompt to a surviving replica with the token budget reduced by
what was delivered. The replayed prefix is prompt, not generation, on
the new replica — nothing is re-emitted — and the spliced stream hands
the client every token exactly once. Greedy requests are bit-identical
to an unfaulted run (the continuation is conditioned on exactly the
prefix the client already holds); stochastic requests are
prefix-consistent, not bit-identical (the surviving replica draws fresh
PRNG keys — docs/SERVING.md spells out the caveat). Failovers are
bounded by ``replay_budget``; refused placements (shed, drain-shed) by
``place_attempts``.

Client surface (mirrors serve.py): ``POST /generate`` (same body; adds
``request_id`` passthrough — echoed on every NDJSON row by router and
replica so replay dedup is observable end to end), ``GET /healthz``
``/readyz`` ``/statz`` ``/metrics`` ``/tracez``. Router responses carry
``replays`` / ``attempts`` / ``replica`` so a client can see a failover
happened without losing a token.

``--smoke`` is the ``make router-chaos-smoke`` drive: 2–3 in-process
serve.py replicas + this router + ``resilience.chaos.RouterChaos``
(kill a replica mid-stream, stall healthz past the probe timeout, flap
health, inject scrape failures, drain) with a bit-identical greedy
oracle and full accounting asserts.
"""

from __future__ import annotations

import argparse
import hashlib
import http.client
import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import unquote

from picotron_tpu.config import RouterConfig
from picotron_tpu.obs import GLOBAL_REGISTRY, Obs
from picotron_tpu.obs.metrics import parse_prometheus
from picotron_tpu.resilience.retry import retry


class DuplicateReplica(ValueError):
    """A dynamic registration named a replica already in the set (the
    admin API's 409, distinct from a malformed spec's 400)."""


class ReplicaFailure(Exception):
    """A hard per-replica failure: unreachable, sick health surface, or a
    broken /generate stream. Feeds the circuit breaker."""


class RouteRefused(Exception):
    """The router-level reject (the fabric's AdmissionError): nothing was
    streamed to the client and the caller turns this into an HTTP
    status + Retry-After."""

    def __init__(self, status: int, reason: str, retry_after: int = 0):
        super().__init__(reason)
        self.status = status
        self.reason = reason
        self.retry_after = retry_after


class _Stopped(Exception):
    """Router shutdown interrupting a prober sleep/backoff ladder."""


# --------------------------------------------------------------------------- #
# pure helpers (unit-tested directly)
# --------------------------------------------------------------------------- #


def prefix_key(prompt, page_len: int,
               tenant: str = "") -> Optional[str]:
    """Affinity key: hash of the longest page-aligned prompt prefix, or
    None when the prompt holds no whole page (nothing the radix cache
    could share — pure least-loaded placement). ``tenant`` salts the
    key exactly as it salts the replica-side radix domains
    (inference/tenancy.py): identical prompts under different tenants
    share no pages, so they must not share an affinity owner's cache
    bank either. Anonymous/base traffic ("") hashes as before."""
    n = (len(prompt) // page_len) * page_len
    if n <= 0:
        return None
    raw = ",".join(str(int(t)) for t in prompt[:n]).encode()
    if tenant:
        raw = tenant.encode() + b"|" + raw
    return hashlib.blake2b(raw, digest_size=8).hexdigest()


def _rendezvous(key: str, name: str) -> int:
    """Highest-random-weight hash: every router instance ranks the same
    replicas identically for one prefix, with no shared state and minimal
    disruption when the replica set changes."""
    h = hashlib.blake2b(f"{key}|{name}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big")


def tenant_scrape(prom: dict) -> dict:
    """Per-tenant load surfaced by one /metrics scrape: {tenant:
    {"queue_depth", "active_slots", "ttft_p95"}} off the labeled
    ``picotron_tenant_*`` families (tenancy-less replicas export none —
    an empty dict, and placement scores exactly as before)."""
    import re

    tenants = set()
    for k in prom:
        if k.startswith(("picotron_tenant_queue_depth{",
                         "picotron_tenant_ttft_seconds_count{")):
            m = re.search(r'tenant="([^"]*)"', k)
            if m:
                tenants.add(m.group(1))
    out = {}
    for t in sorted(tenants):
        label = f'tenant="{t}"'
        sub = {k: v for k, v in prom.items() if label in k}
        out[t] = {
            "queue_depth": sub.get(
                f"picotron_tenant_queue_depth{{{label}}}", 0.0),
            "active_slots": sub.get(
                f"picotron_tenant_active_slots{{{label}}}", 0.0),
            "ttft_p95": hist_quantile(
                sub, "picotron_tenant_ttft_seconds", 0.95),
        }
    return out


def hist_quantile(prom: dict, name: str, q: float) -> float:
    """Quantile estimate from a scraped Prometheus histogram: the upper
    bound of the first cumulative bucket covering ``q`` of the count
    (conservative — a bucket bound, not an interpolation). 0.0 when the
    histogram is absent or empty."""
    pts = []
    total = None
    prefix = f"{name}_bucket{{"
    for k, v in prom.items():
        if not k.startswith(prefix):
            continue
        i = k.find('le="')
        le = k[i + 4:k.rindex('"')]
        if le == "+Inf":
            total = v
        else:
            pts.append((float(le), v))
    if not total or not pts:
        return 0.0
    pts.sort()
    target = q * total
    for le, cum in pts:
        if cum >= target:
            return le
    return pts[-1][0]


# --------------------------------------------------------------------------- #
# transport (all failures normalized to ReplicaFailure)
# --------------------------------------------------------------------------- #

_TRANSPORT_ERRORS = (OSError, http.client.HTTPException, ValueError)


def _get_json(host: str, port: int, path: str, timeout: float) -> tuple:
    try:
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read() or b"{}")
        finally:
            conn.close()
    except _TRANSPORT_ERRORS as e:
        raise ReplicaFailure(
            f"GET {path}: {type(e).__name__}: {e}") from e


def _get_text(host: str, port: int, path: str, timeout: float) -> tuple:
    try:
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read().decode("utf-8", errors="replace")
        finally:
            conn.close()
    except _TRANSPORT_ERRORS as e:
        raise ReplicaFailure(
            f"GET {path}: {type(e).__name__}: {e}") from e


def _post_json(host: str, port: int, path: str, payload: dict,
               timeout: float, on_read=None) -> tuple:
    """POST a JSON body, read a JSON response. ``on_read`` fires between
    the response head and the body read — the chaos hook that severs a
    page stream mid-transfer."""
    try:
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request("POST", path, json.dumps(payload),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            if on_read is not None:
                on_read()
            return resp.status, json.loads(resp.read() or b"{}")
        finally:
            conn.close()
    except _TRANSPORT_ERRORS as e:
        raise ReplicaFailure(
            f"POST {path}: {type(e).__name__}: {e}") from e


# --------------------------------------------------------------------------- #
# replica record
# --------------------------------------------------------------------------- #


class Replica:
    """Per-replica state. Every mutable field is guarded by ``_mu`` — a
    LEAF lock (picolint PICO-C001/C003): taken for pure state reads and
    transitions only, never while doing I/O or waiting on another lock."""

    def __init__(self, name: str, host: str, port: int):
        self.name = name
        self.host = host
        self.port = int(port)
        self._mu = threading.Lock()
        # set when this replica leaves the set (deregistered by the admin
        # API) or the router stops: the prober's sleep/ladder waits on it,
        # so removal interrupts even a breaker-open reprobe backoff
        self.gone = threading.Event()
        self._prober: Optional[threading.Thread] = None
        self.breaker = "closed"  # closed | open | half_open
        self.fails = 0  # consecutive hard failures
        self.okays = 0  # consecutive clean probes (half-open recovery)
        self.trial = False  # half-open: one live trial request at a time
        self.ready = False
        self.draining = False
        self.role = "both"  # from the readyz body: prefill|decode|both
        self.scrape: dict = {}  # parsed load terms from /metrics
        self.scrape_t = float("-inf")  # monotonic time of last good scrape
        self.inflight = 0  # router-placed requests currently streaming

    def snapshot(self, now: float) -> dict:
        with self._mu:
            age = now - self.scrape_t
            return {
                "addr": f"{self.host}:{self.port}",
                "breaker": self.breaker,
                "ready": self.ready,
                "role": self.role,
                "draining": self.draining,
                "consecutive_failures": self.fails,
                "inflight": self.inflight,
                "scrape_age_s": None if age == float("inf") else round(age, 3),
                "scrape": dict(self.scrape),
            }


class Router:
    """Placement + breaker + failover brain (no HTTP server of its own —
    ``RouterServer`` adds that). Prober threads are started by
    ``start()``; the request path is driven by ``route()`` from any
    number of caller threads.

    Locking discipline (picolint PICO-C001–C004): each ``Replica._mu``
    and the counter-dict lock ``_ctr_mu`` are leaf locks — taken last,
    held only across pure state transitions, never across HTTP calls,
    sleeps, or each other. Registry instruments carry their own internal
    leaf locks."""

    def __init__(self, replicas, cfg: Optional[RouterConfig] = None, *,
                 obs: Optional[Obs] = None, chaos=None, log=print,
                 clock=time.monotonic, allow_empty: bool = False):
        self.cfg = cfg or RouterConfig()
        self.cfg.validate()
        self.replicas: dict = {}
        for spec in replicas:
            name, host, port = self._parse_spec(spec)
            if name in self.replicas:
                raise ValueError(f"duplicate replica name {name!r}")
            self.replicas[name] = Replica(name, host, port)
        if not self.replicas and not allow_empty:
            # allow_empty is the elastic bootstrap (tools/fleet.py): the
            # fleet controller starts an empty router and registers
            # workers through the admin API as they come up
            raise ValueError("router needs at least one replica")
        self.chaos = chaos
        self.obs = obs or Obs(enabled=True)
        self.registry = self.obs.registry
        self._log = log
        self._clock = clock
        # requests by terminal state; CounterDict writes are serialized by
        # the leaf lock _ctr_mu (handler threads finish concurrently)
        self.requests = self.registry.counter_dict(
            "picotron_router_requests_total",
            ("completed", "failed", "shed", "client_error", "abandoned"),
            help="routed requests by terminal state", label="state")
        self._ctr_mu = threading.Lock()
        self._replays = self.registry.counter(
            "picotron_router_replays_total",
            "mid-stream failovers replayed onto a surviving replica")
        self._placement_retries = self.registry.counter(
            "picotron_router_placement_retries_total",
            "placements refused (shed/unreachable) and retried elsewhere")
        self._route_hist = self.registry.histogram(
            "picotron_router_route_seconds", "accept -> terminal response")
        # disaggregation plane: handoff round trips (prefill worker ->
        # router -> decode worker) and cross-replica prefix fetches
        self._handoff_hist = self.registry.histogram(
            "picotron_router_handoff_seconds",
            "/kv/export round trip incl. the remote prefill")
        self._handoff_bytes = self.registry.counter(
            "picotron_router_handoff_bytes_total",
            "raw KV page bytes relayed through handoffs")
        self._handoffs = self.registry.counter_dict(
            "picotron_router_handoffs_total",
            ("served", "fallback"),
            help="prefill/decode handoffs by outcome", label="outcome")
        self._prefix_fetches = self.registry.counter_dict(
            "picotron_router_prefix_fetches_total",
            ("hit", "miss", "error"),
            help="cross-replica prefix-cache fetches by outcome",
            label="outcome")
        self._rid_mu = threading.Lock()
        self._rid_seq = 0
        self._stop = threading.Event()
        # replica-set mutation lock (leaf: pure dict copy-and-swap under
        # it, never I/O, never another lock). Reads DON'T take it: every
        # reader iterates whatever dict object self.replicas bound at
        # that moment, and mutations swap in a fresh dict (copy-on-write)
        # rather than mutating the one readers may be iterating.
        self._set_mu = threading.Lock()
        self._started = False
        self._threads: list = []
        self._start_t = clock()

    @staticmethod
    def _parse_spec(spec) -> tuple:
        """(name, host, port) from a replica spec: "host:port" (the name
        IS the address) or a (name, host, port) tuple. Raises ValueError
        on malformed input — the admin API's 400."""
        if isinstance(spec, str):
            host, _, port = spec.rpartition(":")
            if not host or not port:
                raise ValueError(
                    f"replica spec must be HOST:PORT, got {spec!r}")
            spec = (f"{host}:{port}", host, port)
        name, host, port = spec
        return str(name), str(host), int(port)

    # ---- lifecycle --------------------------------------------------------

    def start(self) -> None:
        with self._set_mu:
            self._started = True
            reps = list(self.replicas.values())
        for rep in reps:
            self._spawn_prober(rep)

    def _spawn_prober(self, rep: Replica) -> None:
        t = threading.Thread(target=self._probe_loop, args=(rep,),
                             name=f"router-probe-{rep.name}",
                             daemon=True)
        rep._prober = t
        with self._set_mu:
            self._threads.append(t)
        t.start()

    def stop(self) -> None:
        self._stop.set()
        with self._set_mu:
            reps = list(self.replicas.values())
            threads = list(self._threads)
        for rep in reps:
            rep.gone.set()  # wake probers parked in per-replica sleeps
        for t in threads:
            t.join(timeout=10)

    # ---- dynamic replica set (the fleet controller's admin surface) -------

    def add_replica(self, spec) -> Replica:
        """Register one replica at runtime (the POST /replicas surface).
        The set swap is copy-on-write under ``_set_mu`` so in-progress
        candidate scans never see a mutating dict; the new replica gets
        its prober thread immediately when the router is running. The
        rendezvous hash re-ranks automatically — affinity owners are
        recomputed per placement over the live set. Raises
        ``DuplicateReplica`` (409) on a name collision, ``ValueError``
        (400) on a malformed spec."""
        name, host, port = self._parse_spec(spec)
        rep = Replica(name, host, port)
        with self._set_mu:
            if name in self.replicas:
                raise DuplicateReplica(f"replica {name!r} already "
                                       f"registered")
            replicas = dict(self.replicas)
            replicas[name] = rep
            self.replicas = replicas
            started = self._started
        if started:
            self._spawn_prober(rep)
        self.registry.counter(
            "picotron_router_replica_set_total",
            "dynamic replica-set mutations", op="add").inc()
        self._event("replica_add", replica=name, addr=f"{host}:{port}")
        return rep

    def remove_replica(self, name: str, join_timeout: float = 10.0) -> dict:
        """Deregister one replica at runtime (the DELETE /replicas/<name>
        surface). Safe mid-stream: in-flight routes hold the Replica
        OBJECT, which stays valid — they finish (or fail over) on their
        own; only new placements stop seeing it. The prober thread is
        woken through ``rep.gone`` (it interrupts even a breaker-open
        backoff ladder) and joined, and the breaker/inflight state dies
        with the object — nothing leaks. Raises KeyError when unknown
        (the admin API's 404). Returns the final snapshot."""
        with self._set_mu:
            rep = self.replicas.get(name)
            if rep is None:
                raise KeyError(f"unknown replica {name!r}")
            replicas = dict(self.replicas)
            del replicas[name]
            self.replicas = replicas
        rep.gone.set()
        t = rep._prober
        if t is not None:
            t.join(timeout=join_timeout)
            with self._set_mu:
                if t in self._threads:
                    self._threads.remove(t)
        self.registry.counter(
            "picotron_router_replica_set_total",
            "dynamic replica-set mutations", op="remove").inc()
        self._event("replica_remove", replica=name,
                    prober_joined=t is None or not t.is_alive())
        return rep.snapshot(self._clock())

    def wait_eligible(self, n: int = 1, timeout: float = 30.0) -> bool:
        """Block until >= n replicas are placeable (startup convenience for
        the CLI and the smoke drive)."""
        deadline = self._clock() + timeout
        while self._clock() < deadline:
            if len(self._eligible()) >= n:
                return True
            if self._stop.wait(0.02):
                return False
        return False

    def _sleep(self, seconds: float, rep: Optional[Replica] = None) -> None:
        """Interruptible sleep. With ``rep``, waits on that replica's
        ``gone`` event so a deregistration wakes its prober even out of
        a breaker-open backoff ladder; either wake source (gone or
        router stop) raises ``_Stopped``."""
        ev = self._stop if rep is None else rep.gone
        if ev.wait(seconds) or self._stop.is_set():
            raise _Stopped()

    def _event(self, evt: str, **fields) -> None:
        self._log(json.dumps({"evt": evt, "t": round(time.time(), 3),
                              **fields}), flush=True)

    def _next_rid(self) -> str:
        with self._rid_mu:
            self._rid_seq += 1
            return f"rt{self._rid_seq}"

    # ---- probing + breaker ------------------------------------------------

    def _probe_loop(self, rep: Replica) -> None:
        try:
            while not self._stop.is_set() and not rep.gone.is_set():
                try:
                    self._probe_once(rep)
                except ReplicaFailure as e:
                    if self._probe_fail(rep, str(e)):
                        self._reprobe_open(rep)
                        continue
                self._sleep(self.cfg.probe_interval_s, rep)
        except _Stopped:
            pass

    def _probe_once(self, rep: Replica) -> None:
        """One probe cycle: hard failures (unreachable/sick healthz/
        stalled-or-dead readyz) raise ReplicaFailure; a drain is graceful
        (ready=False, breaker untouched); a scrape failure is soft (the
        scrape goes stale, placement drops the replica, no breaker
        action). All I/O happens before any lock is taken."""
        t = self.cfg.probe_timeout_s
        st, _ = _get_json(rep.host, rep.port, "/healthz", t)
        if st != 200:
            raise ReplicaFailure(f"{rep.name}: healthz {st}")
        st, body = _get_json(rep.host, rep.port, "/readyz", t)
        draining = (body.get("state") == "draining"
                    or bool(body.get("draining")))
        role = body.get("role") or "both"
        if st != 200 and not draining:
            raise ReplicaFailure(
                f"{rep.name}: readyz {st} (state="
                f"{body.get('state', '?')})")
        scrape = None
        try:
            if self.chaos is not None and self.chaos.scrape_fails(rep.name):
                raise ReplicaFailure(f"{rep.name}: injected scrape failure")
            mst, text = _get_text(rep.host, rep.port, "/metrics", t)
            if mst == 200:
                prom = parse_prometheus(text)
                scrape = {
                    "queue_depth": prom.get("picotron_queue_depth", 0.0),
                    "active_slots": prom.get("picotron_active_slots", 0.0),
                    "pool_utilization": prom.get(
                        "picotron_kv_pool_utilization", 0.0),
                    "ttft_p95": hist_quantile(
                        prom, "picotron_ttft_seconds", 0.95),
                    # per-tenant load (empty on tenancy-less replicas):
                    # placement adds the REQUESTING tenant's TTFT p95 on
                    # each candidate, steering an SLO tenant away from
                    # the replica that is slow for IT specifically
                    "tenants": tenant_scrape(prom),
                }
        except ReplicaFailure:
            scrape = None
        self._probe_ok(rep, ready=st == 200, draining=draining,
                       scrape=scrape, role=role)

    def _transition(self, rep: Replica, to: str) -> None:
        """Count + log one breaker transition. Called WITH ``rep._mu``
        held: the counter's own leaf lock nests strictly inside it (one
        direction only — no cycle)."""
        self.registry.counter(
            "picotron_router_breaker_transitions_total",
            "circuit-breaker state changes", replica=rep.name, to=to).inc()

    def _probe_ok(self, rep: Replica, ready: bool, draining: bool,
                  scrape: Optional[dict], role: str = "both") -> None:
        now = self._clock()
        opened_to = None
        with rep._mu:
            rep.ready = ready
            rep.draining = draining
            rep.role = role
            if scrape is not None:
                rep.scrape = scrape
                rep.scrape_t = now
            rep.fails = 0
            rep.okays += 1
            if rep.breaker == "open":
                rep.breaker = "half_open"
                rep.okays = 1
                self._transition(rep, "half_open")
                opened_to = "half_open"
            elif (rep.breaker == "half_open"
                  and rep.okays >= self.cfg.breaker_failures):
                # traffic-free recovery: enough consecutive clean probes
                # close the breaker without risking a trial request
                rep.breaker = "closed"
                self._transition(rep, "closed")
                opened_to = "closed"
        if opened_to:
            self._event("breaker", replica=rep.name, to=opened_to,
                        via="probe")

    def _probe_fail(self, rep: Replica, why: str) -> bool:
        """Record one hard probe failure; returns True when the breaker is
        now open (the caller switches to the reprobe ladder)."""
        opened = False
        with rep._mu:
            rep.ready = False
            rep.okays = 0
            rep.fails += 1
            if (rep.breaker == "half_open"
                    or (rep.breaker == "closed"
                        and rep.fails >= self.cfg.breaker_failures)):
                rep.breaker = "open"
                self._transition(rep, "open")
                opened = True
            is_open = rep.breaker == "open"
        self._event("probe_failure", replica=rep.name, why=why,
                    breaker_opened=opened)
        return is_open

    def _reprobe_open(self, rep: Replica) -> None:
        """Open-state reprobe ladder: ``resilience.retry`` drives
        exponentially backed-off probes (first delay
        ``breaker_backoff_s``, doubling, jittered); the first success
        lands in ``_probe_ok`` which flips half-open. An exhausted ladder
        parks at the cap and starts over — an open replica is reprobed
        forever, just never faster than the cap."""
        def capped_sleep(d: float) -> None:
            # retry()'s raw exponential has no cap of its own: clamp
            # every inter-reprobe delay at the configured ceiling
            self._sleep(min(d, self.cfg.breaker_backoff_max_s), rep)

        while not self._stop.is_set() and not rep.gone.is_set():
            try:
                retry(lambda: self._probe_once(rep),
                      attempts=self.cfg.breaker_probe_attempts,
                      backoff=self.cfg.breaker_backoff_s,
                      jitter=0.25, retry_on=(ReplicaFailure,),
                      desc=f"router-reprobe-{rep.name}",
                      sleep=capped_sleep)
                return
            except ReplicaFailure:
                self._sleep(self.cfg.breaker_backoff_max_s, rep)

    def _request_success(self, rep: Replica) -> None:
        closed = False
        with rep._mu:
            rep.inflight -= 1
            if rep.breaker == "half_open" and rep.trial:
                rep.breaker = "closed"
                rep.fails = 0
                self._transition(rep, "closed")
                closed = True
            rep.trial = False
        if closed:
            self._event("breaker", replica=rep.name, to="closed",
                        via="trial_request")

    def _request_failure(self, rep: Replica, why: str) -> None:
        opened = False
        with rep._mu:
            rep.inflight -= 1
            rep.fails += 1
            rep.okays = 0
            if (rep.breaker == "half_open"
                    or (rep.breaker == "closed"
                        and rep.fails >= self.cfg.breaker_failures)):
                if rep.breaker != "open":
                    rep.breaker = "open"
                    self._transition(rep, "open")
                    opened = True
            rep.trial = False
        self._event("request_failure", replica=rep.name, why=why,
                    breaker_opened=opened)

    def _request_refused(self, rep: Replica) -> None:
        """A shed/drain refusal: the replica is alive (that WAS its
        answer) — no breaker action, just release the slot."""
        with rep._mu:
            rep.inflight -= 1
            rep.trial = False

    # ---- placement --------------------------------------------------------

    def _load(self, rep: Replica, tenant: str = "") -> float:
        """Load score under ``rep._mu`` (caller holds it): scraped queue
        depth + the router's own in-flight placements (fresher than any
        scrape), active slots, pool occupancy, TTFT p95 — plus, for a
        named tenant, THAT tenant's scraped TTFT p95 on this replica
        (picotron_tenant_ttft_seconds): fleet-wide health can hide one
        replica serving one tenant badly (its adapter contending with a
        heavy co-tenant), and the per-tenant term is what routes around
        it."""
        c = self.cfg
        s = rep.scrape
        load = (c.load_queue_weight * (s.get("queue_depth", 0.0)
                                       + rep.inflight)
                + c.load_slot_weight * s.get("active_slots", 0.0)
                + c.load_pool_weight * s.get("pool_utilization", 0.0)
                + c.load_ttft_weight * s.get("ttft_p95", 0.0))
        if tenant:
            ts = s.get("tenants", {}).get(tenant)
            if ts:
                load += c.load_ttft_weight * ts.get("ttft_p95", 0.0)
        return load

    def _candidates(self, excluded=(), kind: str = "decode",
                    tenant: str = "") -> list:
        """[(replica, load)] of currently placeable replicas for ``kind``
        of work: "decode" (the /generate path — prefill-only replicas are
        NOT candidates, they would otherwise score as idle decode
        targets) or "prefill" (the /kv/export handoff — dedicated
        prefill workers only; a fleet without any simply serves
        colocated)."""
        now = self._clock()
        out = []
        for rep in self.replicas.values():
            if rep.name in excluded:
                continue
            with rep._mu:
                if kind == "decode" and rep.role == "prefill":
                    continue
                if kind == "prefill" and rep.role != "prefill":
                    continue
                if rep.breaker == "open":
                    continue
                if rep.breaker == "half_open" and rep.trial:
                    continue  # one trial at a time through a half-open door
                if not rep.ready or rep.draining:
                    continue
                if now - rep.scrape_t > self.cfg.scrape_stale_s:
                    continue  # unknown load is unplaceable load
                out.append((rep, self._load(rep, tenant)))
        return out

    def _eligible(self) -> list:
        return [rep for rep, _ in self._candidates()]

    def _affinity_owner(self, prompt,
                        tenant: str = "") -> Optional[Replica]:
        """The rendezvous-top decode candidate for ``prompt``'s prefix
        key (load ignored): the replica whose radix cache accumulates
        this prefix under affinity placement — the cross-replica lookup's
        source of truth. None for page-less prompts or an empty set."""
        key = prefix_key(prompt, self.cfg.affinity_page_len, tenant)
        if key is None:
            return None
        cands = self._candidates()
        if not cands:
            return None
        return max((rep for rep, _ in cands),
                   key=lambda rep: _rendezvous(key, rep.name))

    def place(self, prompt, excluded=(), kind: str = "decode",
              tenant: str = "") -> Optional[Replica]:
        """Pick a replica for ``prompt`` (None when nothing is eligible):
        the rendezvous affinity pick while it is within
        ``affinity_load_slack`` of the least-loaded candidate, else
        least-loaded. Reserves an inflight slot (and the half-open trial
        token) on the pick."""
        cands = self._candidates(excluded, kind=kind, tenant=tenant)
        key = prefix_key(prompt, self.cfg.affinity_page_len, tenant)
        while cands:
            best = min(load for _, load in cands)
            pick = None
            if key is not None:
                for rep, load in sorted(
                        cands, key=lambda c: _rendezvous(key, c[0].name),
                        reverse=True):
                    if load <= best + self.cfg.affinity_load_slack:
                        pick = rep
                        break
            if pick is None:
                pick = min(cands, key=lambda c: c[1])[0]
            with pick._mu:
                if pick.breaker == "half_open" and pick.trial:
                    # lost the race for the one half-open trial token
                    # (_candidates read it before another placement took
                    # it): fall through to the next candidate
                    reserved = False
                else:
                    pick.inflight += 1
                    if pick.breaker == "half_open":
                        pick.trial = True
                    reserved = True
            if not reserved:
                cands = [c for c in cands if c[0] is not pick]
                continue
            self.registry.counter(
                "picotron_router_placements_total",
                "requests placed, by replica", replica=pick.name).inc()
            return pick
        return None

    # ---- disaggregation: handoff export + cross-replica prefix fetch ------

    def _export_handoff(self, spec: dict, rid: str, prompt: list,
                        tracer, root) -> Optional[dict]:
        """Run the prompt's prefill at its affinity PREFILL worker and
        return the KV transport payload (POST /kv/export), or None — no
        prefill workers, all refused, or every attempt failed — in which
        case the caller falls back to self-prefill at the decode
        placement (nothing was streamed to the client, so this is the
        replay bookkeeping's zero-delivered path). Export failures feed
        the breaker exactly like request failures; sheds are graceful."""
        tried: set = set()
        tenant = str(spec.get("tenant") or "")
        for _ in range(self.cfg.place_attempts):
            rep = self.place(prompt, excluded=tried, kind="prefill",
                             tenant=tenant)
            if rep is None:
                break
            sub = {"prompt": prompt, "request_id": rid,
                   "uid": f"{rid}.pf{len(tried) + 1}"}
            for k in ("temperature", "top_k", "top_p", "eos_id",
                      "timeout_s", "tenant"):
                if k in spec:
                    sub[k] = spec[k]
            span = tracer.begin("handoff", parent=root, request_id=rid,
                                replica=rep.name)
            t0 = self._clock()
            try:
                if self.chaos is not None:
                    self.chaos.on_export(rep.name)
                st, body = _post_json(
                    rep.host, rep.port, "/kv/export", sub,
                    self.cfg.handoff_timeout_s,
                    on_read=(None if self.chaos is None else
                             lambda: self.chaos.on_export_read(rep.name)))
                if st in (429, 503):
                    self._request_refused(rep)
                    tried.add(rep.name)
                    tracer.end(span, outcome="refused")
                    continue
                if st == 400:
                    # the CLIENT's bad request, not the replica's fault
                    # (the same discipline as _attempt's client_error):
                    # no breaker feedback — fall back so the decode
                    # placement's /generate returns the client-visible
                    # 400 through the normal path
                    self._request_refused(rep)
                    tracer.end(span, outcome="client_error")
                    return None
                if st != 200 or not isinstance(body.get("kv"), dict):
                    raise ReplicaFailure(
                        f"{rep.name}: POST /kv/export {st}")
                payload = body["kv"]
                self._request_success(rep)
                dt = self._clock() - t0
                self._handoff_hist.observe(dt)
                self._handoff_bytes.inc(int(payload.get("bytes_total", 0)))
                with self._ctr_mu:
                    self._handoffs["served"] += 1
                tracer.end(span, outcome="served",
                           tokens=len(payload.get("token_ids", ())),
                           bytes=int(payload.get("bytes_total", 0)))
                return payload
            except ReplicaFailure as e:
                # prefill-worker death (or a severed page stream)
                # mid-handoff: breaker feedback, then the next prefill
                # worker — or the caller's re-prefill fallback
                self._request_failure(rep, str(e))
                tried.add(rep.name)
                tracer.end(span, outcome="failed", error=str(e)[:200])
                self._event("handoff_failed", request_id=rid,
                            replica=rep.name, why=str(e))
                continue
        # prefill workers exist in the fleet but none produced a payload
        # (refused, failed, breaker-open, draining): the decode placement
        # self-prefills — the degradation signal an operator watches.
        # A fleet with NO prefill-role replicas is colocated by design,
        # not degraded, and counts nothing.
        has_prefill = False
        for rep in self.replicas.values():
            with rep._mu:
                if rep.role == "prefill":
                    has_prefill = True
                    break
        if tried or has_prefill:
            with self._ctr_mu:
                self._handoffs["fallback"] += 1
        return None

    def _prefix_fetch(self, owner: Replica, rep: Replica,
                      prompt: list, tenant: str = "") -> None:
        """Cross-replica prefix-cache lookup: pull ``owner``'s longest
        cached page-aligned prefix of ``prompt`` and import it at
        ``rep`` — a placement that escaped its affinity owner still
        reuses the cluster's one prefill of the shared prefix. SOFT end
        to end: every failure is counted and skipped, never a breaker
        verdict or a client error (the worst case is the prefill the
        escape would have paid anyway)."""
        outcome = "error"
        try:
            lookup = {"ids": prompt}
            if tenant:
                # scope the lookup to the tenant's radix domain — a
                # lookup must never vouch pages across the isolation
                # boundary (the payload itself carries the tenant, so
                # the import lands in the right domain at ``rep``)
                lookup["tenant"] = tenant
            st, body = _post_json(owner.host, owner.port, "/kv/pages",
                                  lookup, self.cfg.probe_timeout_s)
            if st != 200 or body.get("matched", 0) \
                    < self.cfg.affinity_page_len:
                outcome = "miss"
                return
            st, _ = _post_json(rep.host, rep.port, "/kv/import",
                               {"kv": body["kv"]},
                               self.cfg.handoff_timeout_s)
            if st == 200:
                outcome = "hit"
                self._handoff_bytes.inc(
                    int(body["kv"].get("bytes_total", 0)))
        except ReplicaFailure:
            pass
        finally:
            with self._ctr_mu:
                self._prefix_fetches[outcome] += 1

    # ---- request path -----------------------------------------------------

    def route(self, spec: dict, rid: str, on_token=None) -> dict:
        """Serve one request against the fleet, failing over mid-stream as
        needed. ``on_token(tok)`` fires once per delivered token (the
        streaming splice); returns the terminal payload
        ``{request_id, tokens, finish_reason, replays, attempts,
        replica}``. Raises RouteRefused when nothing was streamed and no
        replica served (the caller maps it to 400/503)."""
        prompt = spec.get("prompt")
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) for t in prompt)):
            raise RouteRefused(
                400, "prompt must be a non-empty list of token ids")
        try:
            max_new = int(spec.get("max_new_tokens", 32))
        except (TypeError, ValueError) as e:
            raise RouteRefused(400, f"bad max_new_tokens: {e}") from e
        if max_new < 1:
            raise RouteRefused(400, "max_new_tokens must be >= 1")
        tenant = spec.get("tenant")
        if tenant is not None and not isinstance(tenant, str):
            raise RouteRefused(400, "tenant must be a string")
        tenant = tenant or ""
        t0 = self._clock()
        tracer = self.obs.tracer
        root = tracer.begin("route", request_id=rid)
        delivered: list = []
        excluded: set = set()
        replays = 0
        refusals = 0
        attempt = 0
        finish = None
        last_replica = None
        state = "failed"
        prefix_fetched = False
        try:
            # disaggregated prefill: hand the prompt to its affinity
            # prefill worker FIRST — the decode placement then seats the
            # returned pages instead of burning dispatch rounds on the
            # prefill (None = no prefill workers / export failed: the
            # decode placement self-prefills, nothing client-visible)
            kv_payload = None
            if self.cfg.disagg:
                kv_payload = self._export_handoff(spec, rid, prompt,
                                                  tracer, root)
            while True:
                if delivered:
                    # failover landed exactly on a finished generation:
                    # synthesize the terminal the dead replica owed us
                    eos = spec.get("eos_id")
                    if eos is not None and delivered[-1] == int(eos):
                        finish = "eos"
                        break
                    if len(delivered) >= max_new:
                        finish = "length"
                        break
                rep = self.place(prompt + delivered, excluded,
                                 tenant=tenant)
                if rep is None:
                    if delivered:
                        finish = "error"  # mid-stream with no survivor
                        break
                    raise RouteRefused(
                        503, "no replica eligible",
                        self.cfg.retry_after_s)
                attempt += 1
                last_replica = rep.name
                if (kv_payload is None and not delivered and not
                        prefix_fetched and self.cfg.prefix_fetch):
                    # no handoff payload to seat: if the placement escaped
                    # its affinity owner, pull the owner's cached prefix
                    # so the shared prefix still prefills once per cluster
                    prefix_fetched = True
                    owner = self._affinity_owner(prompt, tenant)
                    if owner is not None and owner.name != rep.name:
                        self._prefix_fetch(owner, rep, prompt, tenant)
                try:
                    outcome, detail = self._attempt(
                        rep, spec, rid, attempt, prompt, delivered,
                        max_new, on_token, root, tracer,
                        kv_payload=kv_payload)
                except BaseException:
                    # a non-replica abort (the CLIENT dropped its
                    # connection mid-splice): release the placement slot
                    # without a breaker verdict — the replica did nothing
                    # wrong
                    self._request_refused(rep)
                    raise
                if outcome == "served":
                    self._request_success(rep)
                    finish = detail
                    break
                if outcome == "refused":
                    self._request_refused(rep)
                    self._placement_retries.inc()
                    excluded.add(rep.name)
                    refusals += 1
                    if refusals >= self.cfg.place_attempts:
                        if delivered:
                            finish = "error"
                            break
                        raise RouteRefused(
                            503,
                            f"every placement refused ({detail})",
                            self.cfg.retry_after_s)
                    continue
                if outcome == "client_error":
                    self._request_refused(rep)
                    if delivered:
                        # a replay the fleet can no longer express (e.g.
                        # the replayed prompt+delivered fills the
                        # replica's window): the client keeps every
                        # delivered token and gets a terminal — never a
                        # torn stream, never a 400 that eats partials
                        finish = "error"
                        break
                    raise RouteRefused(400, detail)
                # hard failure: breaker feedback, then replay (tokens
                # were delivered) or placement retry (none were)
                self._request_failure(rep, detail)
                excluded.add(rep.name)
                if delivered:
                    replays += 1
                    if replays > self.cfg.replay_budget:
                        finish = "error"
                        break
                    self._replays.inc()
                    tracer.record("replay", self._clock(), self._clock(),
                                  parent=root, request_id=rid,
                                  from_replica=rep.name,
                                  delivered=len(delivered), why=detail)
                    self._event("replay", request_id=rid,
                                from_replica=rep.name,
                                delivered=len(delivered), why=detail)
                else:
                    self._placement_retries.inc()
                    refusals += 1
                    if refusals >= self.cfg.place_attempts:
                        raise RouteRefused(
                            503, f"every placement failed ({detail})",
                            self.cfg.retry_after_s)
            state = "completed" if finish in ("eos", "length", "timeout") \
                else "failed"
            return {"request_id": rid, "tokens": list(delivered),
                    "finish_reason": finish, "replays": replays,
                    "attempts": attempt, "replica": last_replica}
        except RouteRefused as e:
            state = "client_error" if e.status == 400 else "shed"
            raise
        except BaseException:
            # a non-replica abort (the client dropped its connection):
            # its own ledger state — "failed" is reserved for requests
            # the FLEET could not finish, the signal operators page on
            state = "abandoned"
            raise
        finally:
            with self._ctr_mu:
                self.requests[state] += 1
            self._route_hist.observe(self._clock() - t0)
            tracer.end(root, finish_reason=finish or "refused",
                       tokens=len(delivered), replays=replays,
                       state=state)
            self._event("request", request_id=rid, state=state,
                        finish_reason=finish, tokens=len(delivered),
                        replays=replays, attempts=attempt,
                        replica=last_replica)

    def _attempt(self, rep: Replica, spec: dict, rid: str, n: int,
                 prompt: list, delivered: list, max_new: int,
                 on_token, root, tracer, kv_payload=None) -> tuple:
        """One placement attempt: stream ``/generate`` from ``rep``,
        appending tokens to ``delivered`` as they arrive. Returns
        ``(outcome, detail)`` with outcome one of ``served`` (detail =
        finish_reason), ``refused`` (shed — nothing streamed), ``failed``
        (hard failure; ``delivered`` may have grown), ``client_error``.

        ``kv_payload`` is the disaggregated handoff: on the first
        attempt (nothing delivered) the replica seats it — first token
        included — with zero prefill dispatches; on a replay the payload
        rides along WITHOUT its first token as a prefix hint, so the
        survivor radix-hits the prompt and prefills only the delivered
        continuation (bit-identical greedy either way)."""
        sub = {"prompt": prompt + delivered,
               "max_new_tokens": max_new - len(delivered),
               "stream": True, "uid": f"{rid}.a{n}", "request_id": rid}
        for k in ("temperature", "top_k", "top_p", "eos_id", "timeout_s",
                  "tenant"):
            if k in spec:
                sub[k] = spec[k]
        if kv_payload is not None:
            kv = dict(kv_payload)
            if delivered:
                # the first token was already delivered: the payload now
                # vouches for pages only, never a token
                kv.pop("first_token", None)
            sub["kv"] = kv
        span = tracer.begin("attempt", parent=root, request_id=rid,
                            replica=rep.name, n=n)
        got = 0
        conn = None
        try:
            try:
                conn = http.client.HTTPConnection(
                    rep.host, rep.port,
                    timeout=self.cfg.connect_timeout_s)
                conn.request("POST", "/generate", json.dumps(sub),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                if resp.status in (429, 503):
                    body = json.loads(resp.read() or b"{}")
                    return ("refused",
                            f"{resp.status}: {body.get('error', 'shed')}")
                if resp.status == 400:
                    body = json.loads(resp.read() or b"{}")
                    return ("client_error",
                            body.get("error", "bad request"))
                if resp.status != 200:
                    raise ReplicaFailure(
                        f"{rep.name}: POST /generate {resp.status}")
                if conn.sock is not None:
                    # connect deadline served its purpose; from here the
                    # idle timeout bounds a silently wedged stream
                    conn.sock.settimeout(self.cfg.stream_idle_timeout_s)
                while True:
                    line = resp.readline()
                    if not line:
                        raise ReplicaFailure(
                            f"{rep.name}: stream ended without done")
                    if self.chaos is not None:
                        self.chaos.on_stream_row(rep.name, got)
                    row = json.loads(line)
                    ev = row.get("event")
                    if ev == "token":
                        if row.get("request_id", rid) != rid:
                            # a foreign row can only mean a replica-side
                            # routing bug: drop it, keep the count visible
                            self.registry.counter(
                                "picotron_router_row_mismatch_total",
                                "stream rows whose request_id was not "
                                "ours").inc()
                            continue
                        tok = int(row["token"])
                        delivered.append(tok)
                        got += 1
                        if on_token is not None:
                            on_token(tok)
                        continue
                    if ev == "done":
                        fr = row.get("finish_reason")
                        if fr == "error":
                            raise ReplicaFailure(
                                f"{rep.name}: replica finished 'error'")
                        if fr == "shed":
                            if got:
                                raise ReplicaFailure(
                                    f"{rep.name}: shed after streaming "
                                    f"{got} tokens")
                            return ("refused", "shed at drain")
                        if fr not in ("eos", "length", "timeout"):
                            raise ReplicaFailure(
                                f"{rep.name}: unknown finish_reason "
                                f"{fr!r}")
                        return ("served", fr)
            except _TRANSPORT_ERRORS as e:
                # connection drop, torn NDJSON row, idle timeout: the
                # mid-stream death the replay path exists for
                raise ReplicaFailure(
                    f"{rep.name}: {type(e).__name__}: {e}") from e
        except ReplicaFailure as e:
            return ("failed", str(e))
        finally:
            if conn is not None:
                conn.close()
            tracer.end(span, tokens=got)

    # ---- observability ----------------------------------------------------

    def stats(self) -> dict:
        now = self._clock()
        reps = {name: rep.snapshot(now)
                for name, rep in self.replicas.items()}
        eligible = [rep.name for rep in self._eligible()]
        self.registry.gauge(
            "picotron_router_replicas_eligible",
            "replicas currently placeable").set(len(eligible))
        with self._ctr_mu:
            requests = dict(self.requests)
            handoffs = dict(self._handoffs)
            prefix_fetches = dict(self._prefix_fetches)
        return {
            "replicas": reps,
            "eligible": eligible,
            "requests": requests,
            "replays": int(self._replays.value),
            "placement_retries": int(self._placement_retries.value),
            "route_s": self._route_hist.percentiles(),
            "handoffs": handoffs,
            "handoff_bytes": int(self._handoff_bytes.value),
            "handoff_s": self._handoff_hist.percentiles(),
            "prefix_fetches": prefix_fetches,
            "uptime_s": round(now - self._start_t, 3),
        }

    def metrics_text(self) -> str:
        self.stats()  # refresh the eligibility gauge for scrapers
        return self.registry.prometheus() + GLOBAL_REGISTRY.prometheus()


# --------------------------------------------------------------------------- #
# HTTP surface (mirrors tools/serve.py)
# --------------------------------------------------------------------------- #

MAX_BODY_BYTES = 8 << 20


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.0"  # close-delimited NDJSON streaming

    @property
    def router(self) -> Router:
        return self.server.router

    def log_message(self, *a):  # the router's JSON lines replace these
        pass

    def _json(self, status: int, payload: dict, headers=()) -> None:
        body = (json.dumps(payload) + "\n").encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:
        r = self.router
        if self.path == "/healthz":
            self._json(200, {"ok": True})
        elif self.path == "/readyz":
            n = len(r._eligible())
            self._json(200 if n else 503,
                       {"ok": n > 0, "eligible_replicas": n})
        elif self.path == "/statz":
            self._json(200, r.stats())
        elif self.path == "/metrics":
            body = r.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/tracez":
            self._json(200, r.obs.tracer.chrome_trace())
        elif self.path == "/replicas":
            now = r._clock()
            self._json(200, {name: rep.snapshot(now)
                             for name, rep in sorted(r.replicas.items())})
        else:
            self._json(404, {"error": f"unknown path {self.path}"})

    def do_POST(self) -> None:
        if self.path not in ("/generate", "/replicas"):
            self._json(404, {"error": f"unknown path {self.path}"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
        except ValueError as e:
            self._json(400, {"error": f"bad Content-Length: {e}"})
            return
        if n < 0 or n > MAX_BODY_BYTES:
            self._json(400 if n < 0 else 413,
                       {"error": f"bad body length {n}"})
            return
        try:
            spec = json.loads(self.rfile.read(n) or b"{}")
        except (ValueError, json.JSONDecodeError) as e:
            self._json(400, {"error": f"bad request body: {e}"})
            return
        if not isinstance(spec, dict):
            self._json(400, {"error": "request body must be a JSON object"})
            return
        if self.path == "/replicas":
            self._add_replica(spec)
            return
        r = self.router
        rid = str(spec.get("request_id") or r._next_rid())
        if spec.get("stream"):
            self._stream(spec, rid)
        else:
            try:
                payload = r.route(spec, rid)
            except RouteRefused as e:
                headers = ([("Retry-After", str(e.retry_after))]
                           if e.retry_after else [])
                self._json(e.status,
                           {"error": e.reason, "request_id": rid,
                            "shed": e.status != 400}, headers)
                return
            status = 500 if payload["finish_reason"] == "error" else 200
            self._json(status, payload)

    def _add_replica(self, spec: dict) -> None:
        """POST /replicas — the fleet controller's registration surface.
        Body: {"replica": "host:port"} or {"replica": {"name", "host",
        "port"}}. 200 with the new snapshot, 409 on a duplicate name,
        400 on a malformed spec."""
        raw = spec.get("replica")
        if isinstance(raw, dict):
            try:
                raw = (raw.get("name") or f"{raw['host']}:{raw['port']}",
                       raw["host"], raw["port"])
            except KeyError as e:
                self._json(400, {"error": f"replica spec missing {e}"})
                return
        try:
            rep = self.router.add_replica(raw)
        except DuplicateReplica as e:
            self._json(409, {"error": str(e)})
            return
        except (ValueError, TypeError) as e:
            self._json(400, {"error": f"bad replica spec: {e}"})
            return
        self._json(200, {"ok": True, "replica": rep.name,
                         **rep.snapshot(self.router._clock())})

    def do_DELETE(self) -> None:
        if not self.path.startswith("/replicas/"):
            self._json(404, {"error": f"unknown path {self.path}"})
            return
        name = unquote(self.path[len("/replicas/"):])
        try:
            snap = self.router.remove_replica(name)
        except KeyError:
            self._json(404, {"error": f"unknown replica {name!r}"})
            return
        self._json(200, {"ok": True, "replica": name, **snap})

    def _stream(self, spec: dict, rid: str) -> None:
        """NDJSON splice: the header is deferred until the route either
        delivers a first token or refuses outright, so a full-fleet
        outage is still a clean 503 + Retry-After instead of a 200 that
        dies."""
        started = threading.Event()

        def emit(obj) -> None:
            self.wfile.write((json.dumps(obj) + "\n").encode())
            self.wfile.flush()

        def on_token(tok: int) -> None:
            if not started.is_set():
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.end_headers()
                started.set()
            try:
                emit({"event": "token", "request_id": rid, "token": tok})
            except (BrokenPipeError, ConnectionResetError):
                # the CLIENT went away: abort the route (counted
                # "abandoned"; the replica finishes the in-flight
                # generation under its own timeout contract)
                started.set()
                raise _ClientGone()

        try:
            try:
                payload = self.router.route(spec, rid, on_token=on_token)
            except RouteRefused as e:
                if not started.is_set():
                    headers = ([("Retry-After", str(e.retry_after))]
                               if e.retry_after else [])
                    self._json(e.status,
                               {"error": e.reason, "request_id": rid,
                                "shed": e.status != 400}, headers)
                return
            if not started.is_set():
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.end_headers()
                started.set()
            emit({"event": "done", **payload})
        except _ClientGone:
            pass
        except (BrokenPipeError, ConnectionResetError):
            pass


class _ClientGone(Exception):
    """The downstream client dropped its connection mid-stream."""


class RouterServer:
    """Router + ThreadingHTTPServer on background threads — the embedding
    entry point for the CLI, the smoke drive, and the tests."""

    def __init__(self, replicas, cfg: Optional[RouterConfig] = None, *,
                 host: str = "127.0.0.1", port: int = 0, **router_kw):
        self.router = Router(replicas, cfg, **router_kw)
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.router = self.router
        self.port = self.httpd.server_address[1]
        self._http_thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self.router.start()
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever, name="router-http",
            daemon=True)
        self._http_thread.start()

    def stop(self) -> None:
        self.router.stop()
        self.httpd.shutdown()
        if self._http_thread is not None:
            self._http_thread.join(timeout=10)
        self.httpd.server_close()


# --------------------------------------------------------------------------- #
# smoke drive (`make router-chaos-smoke`) + CLI
# --------------------------------------------------------------------------- #


def _stream_post(port: int, spec: dict, on_token=None,
                 host: str = "127.0.0.1", timeout: float = 300.0):
    """Incremental NDJSON client: POSTs with stream=True, fires
    ``on_token(i, row)`` per token row as it ARRIVES (the hook the chaos
    drills key their kill timing off), returns (status, [rows])."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/generate", json.dumps({**spec, "stream": True}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            return resp.status, [json.loads(resp.read() or b"{}")]
        rows = []
        i = 0
        while True:
            line = resp.readline()
            if not line:
                return resp.status, rows
            row = json.loads(line)
            rows.append(row)
            if row.get("event") == "token":
                if on_token is not None:
                    on_token(i, row)
                i += 1
            if row.get("event") == "done":
                return resp.status, rows
    finally:
        conn.close()


def _wait_for(cond, timeout: float = 20.0, poll: float = 0.02) -> bool:
    """Poll ``cond()`` until true (True) or the deadline passes (False)."""
    deadline = time.monotonic() + timeout
    while True:
        if cond():
            return True
        if time.monotonic() >= deadline:
            return False
        time.sleep(poll)


def _breaker(router: Router, name: str) -> str:
    rep = router.replicas[name]
    with rep._mu:
        return rep.breaker


def _smoke_fleet(n: int, roles=None):
    """n in-process serve.py replicas over IDENTICAL tiny random-init
    models (same seed -> same params -> greedy outputs are a shared
    bit-exact oracle), streaming per token (decode_block_len 1).
    ``roles`` (e.g. ``("prefill", "decode")``) builds a disaggregated
    fleet on the paged layout — the KV-page transport's requirement."""
    import jax

    from picotron_tpu.config import Config
    from picotron_tpu.inference import InferenceEngine
    from picotron_tpu.models import llama
    from picotron_tpu.tools import serve
    from picotron_tpu.tools.generate import SMOKE_CONFIG
    from picotron_tpu.train import _ensure_devices

    servers = []
    cfg0 = Config.from_dict(SMOKE_CONFIG)
    jit_init = jax.jit(lambda k: llama.init_params(k, cfg0.model))
    for i in range(n):
        cfg = Config.from_dict(SMOKE_CONFIG)
        cfg.inference.decode_block_len = 1
        if roles is not None:
            cfg.inference.role = roles[i]
            cfg.inference.kv_layout = "paged"
            cfg.inference.kv_page_len = 8
        _ensure_devices(cfg)
        engine = InferenceEngine(cfg, slots=2, max_seq_len=64)
        params = engine.shard_params(jit_init(jax.random.PRNGKey(0)))
        srv = serve.Server(engine, params, port=0,
                           log=lambda *a, **k: None)
        srv.start()
        servers.append(srv)
    return servers


def _smoke_disagg(check) -> None:
    """The disaggregation rungs of `make router-chaos-smoke` (ISSUE 15):
    a prefill + decode two-role fleet behind a fresh router — the happy
    handoff (decode worker seats pages, zero prefill dispatches), then
    the chaos pair: sever the page stream mid-transfer and kill the
    prefill worker mid-export. In every case the client gets every token
    exactly once, greedy bit-identical to the decode worker's own
    self-prefilled run."""
    from picotron_tpu.resilience.chaos import RouterChaos
    from picotron_tpu.tools import serve

    servers = _smoke_fleet(2, roles=("prefill", "decode"))
    pre, dec = servers
    names = [f"127.0.0.1:{s.port}" for s in servers]
    chaos = RouterChaos()
    cfg = RouterConfig(
        probe_interval_s=0.05, probe_timeout_s=2.0, breaker_failures=3,
        breaker_backoff_s=0.05, breaker_backoff_max_s=0.4,
        scrape_stale_s=2.0, connect_timeout_s=5.0)
    rs = RouterServer(names, cfg, chaos=chaos, log=lambda *a, **k: None)
    rs.start()
    router = rs.router
    try:
        check("disagg_fleet_eligible", _wait_for(
            lambda: len(router._candidates(kind="prefill")) == 1
            and len(router._eligible()) == 1, timeout=30))
        check("disagg_roles_probed",
              router.replicas[names[0]].snapshot(0)["role"] == "prefill"
              and router.replicas[names[1]].snapshot(0)["role"] == "decode")

        def run(prompt, rid):
            st, rows = _stream_post(
                rs.port, {"prompt": prompt, "max_new_tokens": 12,
                          "request_id": rid})
            toks = [r["token"] for r in rows if r.get("event") == "token"]
            done = [r for r in rows if r.get("event") == "done"]
            ok = (st == 200 and len(done) == 1
                  and done[0]["finish_reason"] == "length"
                  and done[0]["tokens"] == toks and len(toks) == 12)
            return ok, toks

        def oracle(prompt):
            # the decode worker self-prefills a direct request: the
            # greedy oracle for the same prompt (prefix sharing is
            # output-invariant — pinned in tests/test_paged_kv.py)
            st, body = serve._post(dec.port, {"prompt": prompt,
                                              "max_new_tokens": 12})
            return st == 200 and body["finish_reason"] == "length", \
                body.get("tokens")

        # happy handoff: prefill worker exports, decode worker seats
        p1 = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3, 2, 3]
        ok, toks = run(p1, "dg-1")
        stz = serve._get(dec.port, "/statz")[1]
        check("disagg_handoff_served",
              ok and stz["handoff_seated"] == 1
              and stz["prefill_dispatches"] == 0
              and router.stats()["handoffs"]["served"] == 1
              and router.stats()["handoff_bytes"] > 0)
        ook, otoks = oracle(p1)
        check("disagg_bit_identical", ook and otoks == toks)
        pstz = serve._get(pre.port, "/statz")[1]
        check("disagg_prefill_worker_prefilled",
              pstz["admitted"] == 1 and pstz["completed"] == 1)

        # sever the page stream mid-transfer: fallback self-prefill,
        # exactly-once tokens, bit-identical
        chaos.sever_export(names[0])
        p2 = [11, 12, 13, 14, 15, 16, 17, 18, 11, 12, 13, 14, 15, 16,
              17, 18, 19, 20]
        ok, toks = run(p2, "dg-sever")
        ook, otoks = oracle(p2)
        check("disagg_sever_exactly_once",
              ok and ook and otoks == toks
              and router.stats()["handoffs"]["fallback"] >= 1)

        # kill the prefill worker mid-export: same client contract
        chaos.kill_on_export(names[0], pre)
        p3 = [21, 22, 23, 24, 25, 26, 27, 28, 21, 22, 23, 24, 25, 26,
              27, 28, 29, 30]
        ok, toks = run(p3, "dg-kill")
        ook, otoks = oracle(p3)
        check("disagg_kill_mid_export_exactly_once",
              ok and ook and otoks == toks)
    finally:
        rs.stop()
        try:
            dec.drain_and_join(timeout=60)
        except OSError:
            pass


def _smoke() -> int:
    """The `make router-chaos-smoke` drive — the ISSUE 12 acceptance
    drill end to end. Returns an exit code."""
    from picotron_tpu.resilience.chaos import RouterChaos
    from picotron_tpu.tools import serve

    fail: list = []

    def check(name: str, ok) -> None:
        print(f"router-chaos-smoke: {name}: {'ok' if ok else 'FAIL'}",
              flush=True)
        if not ok:
            fail.append(name)

    servers = _smoke_fleet(3)
    ports = [s.port for s in servers]
    names = [f"127.0.0.1:{p}" for p in ports]
    by_name = dict(zip(names, servers))
    chaos = RouterChaos()
    cfg = RouterConfig(
        probe_interval_s=0.05, probe_timeout_s=0.4,
        breaker_failures=3, breaker_backoff_s=0.05,
        breaker_backoff_max_s=0.4, breaker_probe_attempts=4,
        scrape_stale_s=1.0, stream_idle_timeout_s=60.0,
        connect_timeout_s=5.0)
    rs = RouterServer(names, cfg, chaos=chaos, log=lambda *a, **k: None)
    rs.start()
    router = rs.router
    killed: dict = {}
    try:
        check("fleet_eligible", router.wait_eligible(3, timeout=30))
        check("healthz", serve._get(rs.port, "/healthz")[0] == 200)
        check("readyz", serve._get(rs.port, "/readyz")[0] == 200)

        # greedy oracle: one unfaulted single-replica run (all replicas
        # hold identical params, so any one of them is the oracle)
        prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3]
        spec = {"prompt": prompt, "max_new_tokens": 24}
        st, body = serve._post(ports[0], spec)
        oracle = body["tokens"]
        check("oracle", st == 200 and len(oracle) == 24)

        # routed request matches the oracle; request_id echoes end to end
        st, body = serve._post(rs.port, {**spec, "request_id": "smk-1"})
        check("routed_generate", st == 200 and body["tokens"] == oracle
              and body["request_id"] == "smk-1" and body["replays"] == 0)

        # prefix affinity: page-aligned shared prefixes land on ONE replica
        k = prefix_key(prompt, cfg.affinity_page_len)
        want = router.place(prompt)
        router._request_refused(want)  # release the probe placement
        again = router.place(prompt)
        router._request_refused(again)
        check("affinity_stable",
              k is not None and want is not None
              and again.name == want.name)

        # ---- the acceptance drill: SIGKILL (in-process) one replica ----
        # holding an in-flight greedy stream; the spliced client stream
        # must equal the unfaulted oracle bit for bit, with replays == 1.
        def kill_at(i, row) -> None:
            if i == 4 and not killed:
                victim = None
                for nm, rep in router.replicas.items():
                    with rep._mu:
                        busy = rep.inflight > 0
                    if busy:
                        victim = nm
                        break
                killed["name"] = victim or names[0]
                chaos.kill(by_name[killed["name"]])

        st, rows = _stream_post(rs.port, {**spec, "request_id": "smk-kill"},
                                on_token=kill_at)
        toks = [r["token"] for r in rows if r.get("event") == "token"]
        done = [r for r in rows if r.get("event") == "done"]
        check("kill_mid_stream_spliced",
              st == 200 and len(done) == 1 and killed
              and done[0]["finish_reason"] == "length"
              and done[0]["replays"] == 1
              and done[0]["tokens"] == toks)
        check("kill_bit_identical", toks == oracle)
        check("kill_request_id",
              all(r.get("request_id") == "smk-kill" for r in rows))

        # the dead replica's breaker opens once the prober sees it
        check("dead_breaker_open", _wait_for(
            lambda: _breaker(router, killed["name"]) == "open"))

        survivors = [nm for nm in names if nm != killed["name"]]

        # ---- flap + stall drill on one survivor: breaker opens, then ----
        # recovers through half-open, with zero client-visible errors.
        flappy = survivors[0]
        chaos.flap(by_name[flappy], down=True)
        check("flap_breaker_open", _wait_for(
            lambda: _breaker(router, flappy) == "open"))
        st, body = serve._post(rs.port, {**spec, "request_id": "smk-flap"})
        check("flap_requests_survive",
              st == 200 and body["tokens"] == oracle)
        chaos.flap(by_name[flappy], down=False)
        check("flap_recovered_closed", _wait_for(
            lambda: _breaker(router, flappy) == "closed"))

        # stall past the probe timeout: reads as a hard failure ladder
        chaos.stall(by_name[flappy], seconds=cfg.probe_timeout_s * 2)
        check("stall_breaker_open", _wait_for(
            lambda: _breaker(router, flappy) == "open"))
        chaos.unstall(by_name[flappy])
        check("stall_recovered_closed", _wait_for(
            lambda: _breaker(router, flappy) == "closed"))

        # ---- scrape-failure injection: candidate drop WITHOUT a ----
        # breaker trip, recovery once the scrape path heals
        scrapey = survivors[1]

        def scrapey_eligible() -> bool:
            return scrapey in [r.name for r in router._eligible()]

        chaos.fail_scrape(scrapey, on=True)
        check("scrape_stale_drops_candidate",
              _wait_for(lambda: not scrapey_eligible())
              and _breaker(router, scrapey) == "closed")
        st, body = serve._post(rs.port, {**spec, "request_id": "smk-scr"})
        check("scrape_requests_survive",
              st == 200 and body["tokens"] == oracle)
        chaos.fail_scrape(scrapey, on=False)
        check("scrape_recovers", _wait_for(scrapey_eligible))

        # ---- drain drill: DURING the drain window (an in-flight ----
        # request still finishing) the prober reads "draining" as
        # graceful — candidate drop, breaker untouched. Once the drain
        # completes the listener closes like the process exited, so the
        # window needs a slow request holding it open.
        slow: dict = {}

        def bg() -> None:
            slow["resp"] = serve._post(
                by_name[flappy].port,
                {"prompt": [9, 8, 7], "max_new_tokens": 48})

        t = threading.Thread(target=bg)
        t.start()
        _wait_for(lambda: serve._get(by_name[flappy].port,
                                     "/statz")[1].get("active_slots", 0)
                  > 0, timeout=60)
        by_name[flappy].front.begin_drain()
        _wait_for(lambda: router.replicas[flappy].snapshot(
            time.monotonic())["draining"])
        snap = router.replicas[flappy].snapshot(time.monotonic())
        check("drain_graceful",
              snap["draining"] and snap["breaker"] == "closed"
              and flappy not in [r.name for r in router._eligible()])
        t.join(120)
        check("drain_inflight_served",
              slow.get("resp", (0, {}))[0] == 200)
        st, body = serve._post(rs.port, {**spec, "request_id": "smk-end"})
        check("post_drain_served",
              st == 200 and body["tokens"] == oracle)

        # ---- accounting: the router's own registry holds the story ----
        mst, mtext = serve._get_text(rs.port, "/metrics")
        prom = parse_prometheus(mtext)
        stats = router.stats()
        check("metrics_accounting",
              mst == 200
              and prom.get("picotron_router_replays_total") == 1
              and prom.get(
                  'picotron_router_requests_total{state="completed"}')
              == stats["requests"]["completed"]
              and stats["requests"]["completed"] == 5
              and stats["requests"]["failed"] == 0
              and stats["requests"]["shed"] == 0)
        trace = router.obs.tracer.chrome_trace()
        evs = trace["traceEvents"]
        routes = [e for e in evs if e["name"] == "route"]
        attempts = [e for e in evs if e["name"] == "attempt"]
        replay_ids = {e["args"].get("parent") for e in evs
                      if e["name"] == "replay"}
        kill_roots = [e["args"]["id"] for e in routes
                      if e["args"].get("request_id") == "smk-kill"]
        check("trace_route_attempt_replay_chain",
              len(routes) >= 5
              and kill_roots and kill_roots[0] in replay_ids
              and sum(1 for a in attempts
                      if a["args"].get("parent") == kill_roots[0]) == 2)

        # ---- disaggregation rungs (ISSUE 15): two-role fleet, happy ----
        # handoff, severed page stream, prefill-worker death mid-export
        _smoke_disagg(check)
    finally:
        rs.stop()
        for nm, srv in by_name.items():
            if nm != killed.get("name"):
                try:
                    srv.drain_and_join(timeout=60)
                except OSError:
                    pass
    return 1 if fail else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="prefix-affinity router over N serve.py replicas "
                    "(least-loaded placement, circuit breakers, "
                    "mid-stream failover replay)")
    ap.add_argument("--replica", action="append", default=[],
                    metavar="HOST:PORT",
                    help="one serve.py replica (repeatable)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9000,
                    help="0 = ephemeral (printed at startup)")
    ap.add_argument("--router-config", default="",
                    help="JSON file of RouterConfig overrides")
    ap.add_argument("--smoke", action="store_true",
                    help="in-process 3-replica chaos drill (the `make "
                         "router-chaos-smoke` target)")
    args = ap.parse_args(argv)

    if args.smoke:
        rc = _smoke()
        print(f"router-chaos-smoke: {'PASS' if rc == 0 else 'FAIL'}",
              flush=True)
        return rc

    if not args.replica:
        raise SystemExit("pass at least one --replica HOST:PORT "
                         "(or --smoke)")
    if args.router_config:
        with open(args.router_config) as f:
            cfg = RouterConfig.from_dict(json.load(f))
    else:
        cfg = RouterConfig()
    rs = RouterServer(args.replica, cfg, host=args.host, port=args.port)
    rs.start()
    rs.router._event("routing", port=rs.port,
                     replicas=list(rs.router.replicas))
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        rs.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
