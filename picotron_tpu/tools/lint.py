"""picolint CLI: run the static-analysis suite over the package.

    python -m picotron_tpu.tools.lint               # scan picotron_tpu/
    python -m picotron_tpu.tools.lint --json        # machine-readable
    python -m picotron_tpu.tools.lint path/to/file.py path/to/dir

Exit codes: 0 = clean (every finding baselined), 1 = new non-baselined
findings, 2 = bad invocation.  ``--fail-on-new`` is the default contract
(kept as an explicit flag so `make lint` reads as policy); pass
``--no-fail-on-new`` for an advisory run.

The scan is pure AST — no jax import, no code execution — so the full
package lints in a couple of seconds on CPU.  Rule catalog, baseline
policy, and suppression syntax: docs/ANALYSIS.md.

``--write-baseline`` appends the current NEW findings to the baseline
with a placeholder reason.  The self-scan test
(tests/test_analysis.py::test_baseline_reasons_documented) fails on
placeholder reasons, so the written entries must be documented (or the
finding fixed) before they can ship — baselining is for documented false
positives, never a parking lot for real bugs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from picotron_tpu.analysis import engine
from picotron_tpu.analysis.callgraph import iter_python_files
from picotron_tpu.analysis.findings import _canon, validate_rule_ids


def _scan_spec(paths: list) -> tuple:
    """(root, files|None) for the engine: default is the repo checkout
    scanning the picotron_tpu package; explicit paths are resolved
    relative to cwd and scanned under their common root."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_root = os.path.dirname(pkg_dir)
    if not paths:
        return repo_root, iter_python_files(pkg_dir)
    files, anchors = [], []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isdir(p):
            files += iter_python_files(p)
            anchors.append(p)
        elif os.path.isfile(p):
            files.append(p)
            anchors.append(os.path.dirname(p))
        else:
            raise SystemExit(f"lint: no such path: {p}")
    # keep module names package-rooted when the paths live in the repo;
    # outside it, root on the ARGUMENTS (a dir arg is its own root), not
    # on commonpath(files) — `lint proj` and `lint proj/bad.py` must
    # fingerprint the same file identically or baselines go stale with
    # the invocation shape
    common = os.path.commonpath(anchors)
    in_repo = os.path.commonpath([common, repo_root]) == repo_root
    root = repo_root if in_repo else common
    return root, files


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="picolint",
        description="JAX/Pallas hot-path + host-concurrency static "
                    "analysis (docs/ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: picotron_tpu/)")
    ap.add_argument("--baseline", default=engine.DEFAULT_BASELINE,
                    help="baseline file (default: analysis/baseline.json)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable report on stdout")
    ap.add_argument("--fail-on-new", dest="fail_on_new",
                    action="store_true", default=True,
                    help="exit 1 on any non-baselined finding (default)")
    ap.add_argument("--no-fail-on-new", dest="fail_on_new",
                    action="store_false",
                    help="advisory run: report, always exit 0")
    ap.add_argument("--rules", nargs="*", default=None,
                    help="restrict the printed report to these rule IDs "
                         "(the exit-code gate still considers every rule)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="append current NEW findings to the baseline "
                         "with a placeholder reason (document them!)")
    args = ap.parse_args(argv)

    if args.rules:
        # same spelling rules as suppression comments: bare suffixes
        # ("J001") canonicalize, "*"/"all" means every rule (no filter)
        args.rules = [r for r in (_canon(r) for r in args.rules) if r]
        bad = validate_rule_ids(args.rules)
        if bad is not None:
            print(f"lint: unknown rule id {bad}", file=sys.stderr)
            return 2
        if "*" in args.rules:
            args.rules = None

    try:
        root, files = _scan_spec(args.paths)
    except SystemExit as e:
        print(e, file=sys.stderr)
        return 2

    try:
        out = engine.run(root, files, baseline_path=args.baseline)
    except ValueError as e:  # malformed baseline file
        print(f"lint: {e}", file=sys.stderr)
        return 2
    findings, new, stale = out["_findings"], out["_new"], out["_stale"]
    matched = out["_matched"]
    all_new = new  # the gate and --write-baseline see every rule;
    if args.rules:  # --rules narrows the REPORT only
        keep = set(args.rules)
        findings = [f for f in findings if f.rule in keep]
        new = [f for f in new if f.rule in keep]
        matched = [f for f in matched if f.rule in keep]

    if args.write_baseline and all_new:
        baseline = out["_baseline"] + [
            engine.baseline_entry(
                f, reason="TODO: document why this is a false positive "
                          "(or fix it)") for f in all_new]
        with open(args.baseline, "w", encoding="utf-8") as f:
            json.dump({"findings": baseline}, f, indent=2)
            f.write("\n")
        print(f"lint: wrote {len(all_new)} new entr"
              f"{'y' if len(all_new) == 1 else 'ies'} to {args.baseline} — "
              f"fill in the reasons before shipping", file=sys.stderr)

    if args.as_json:
        print(json.dumps(engine.report_json(
            findings, new, matched, stale, out["elapsed_s"]), indent=2))
    else:
        print(engine.report_text(findings, new, matched, stale,
                                 out["elapsed_s"]))

    if args.fail_on_new and all_new and not args.write_baseline:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
