"""Experiment config generator: ``python create_config.py --dp 2 --tp 2 ...``.

Re-build of the reference's ``create_config.py`` (:40-136): copy
``template/base_config.json``, override the distributed/model/training fields
from CLI flags, compute and print the global batch size (:71-73), and write
``<out_dir>/<exp_name>/config.json`` (:78-83). Model shape defaults come from
HF ``AutoConfig`` when the hub is reachable (:51-54); because TPU pods are
often air-gapped there is also a built-in shape table for the models the
reference benchmarks, so the generator works fully offline. The reference's
trailing safetensors download (:134) becomes opt-in ``--download`` (it needs
network and is not required for pre-training from scratch).
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Optional

# Known model shapes so config generation works with zero egress.
# Values mirror each model's HF config.json.
KNOWN_MODEL_SHAPES = {
    "HuggingFaceTB/SmolLM-135M": dict(
        num_hidden_layers=30, num_attention_heads=9, num_key_value_heads=3,
        hidden_size=576, intermediate_size=1536, vocab_size=49152,
        rms_norm_eps=1e-5, rope_theta=10000.0, max_position_embeddings=2048),
    "HuggingFaceTB/SmolLM-360M": dict(
        num_hidden_layers=32, num_attention_heads=15, num_key_value_heads=5,
        hidden_size=960, intermediate_size=2560, vocab_size=49152,
        rms_norm_eps=1e-5, rope_theta=10000.0, max_position_embeddings=2048),
    "HuggingFaceTB/SmolLM-1.7B": dict(
        num_hidden_layers=24, num_attention_heads=32, num_key_value_heads=32,
        hidden_size=2048, intermediate_size=8192, vocab_size=49152,
        rms_norm_eps=1e-5, rope_theta=10000.0, max_position_embeddings=2048),
    "meta-llama/Llama-2-7b-hf": dict(
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=32,
        hidden_size=4096, intermediate_size=11008, vocab_size=32000,
        rms_norm_eps=1e-5, rope_theta=10000.0, max_position_embeddings=4096),
    "meta-llama/Meta-Llama-3-8B": dict(
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
        hidden_size=4096, intermediate_size=14336, vocab_size=128256,
        rms_norm_eps=1e-5, rope_theta=500000.0, max_position_embeddings=8192),
    "meta-llama/Llama-2-13b-hf": dict(
        num_hidden_layers=40, num_attention_heads=40, num_key_value_heads=40,
        hidden_size=5120, intermediate_size=13824, vocab_size=32000,
        rms_norm_eps=1e-5, rope_theta=10000.0, max_position_embeddings=4096),
    # (Llama-3.1/3.2 and Mistral are deliberately absent: they need
    # rope_scaling / sliding-window attention, which this architecture
    # does not implement — listing them would be a silent divergence.)
    "TinyLlama/TinyLlama_v1.1": dict(
        num_hidden_layers=22, num_attention_heads=32, num_key_value_heads=4,
        hidden_size=2048, intermediate_size=5632, vocab_size=32000,
        rms_norm_eps=1e-5, rope_theta=10000.0,
        max_position_embeddings=2048),
}
# Instruct variants share the base shapes.
for _base in list(KNOWN_MODEL_SHAPES):
    KNOWN_MODEL_SHAPES[_base + "-Instruct"] = KNOWN_MODEL_SHAPES[_base]

# Canonical templates ship inside the package (picotron_tpu/templates/) so
# pip-installed entry points work; the repo-root template/ dir symlinks here.
TEMPLATE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "templates", "base_config.json")


# Shape fields a config must resolve one way or another; everything else
# (rms_norm_eps, rope_theta, ...) has sane template defaults.
REQUIRED_SHAPE_FIELDS = (
    "num_hidden_layers", "num_attention_heads", "num_key_value_heads",
    "hidden_size", "intermediate_size", "vocab_size",
)


def model_shape_defaults(model_name: str, overrides: dict) -> dict:
    """Shape fields for a model: built-in table first, HF AutoConfig as the
    online fallback (the reference always fetches, create_config.py:51-54).
    A fully-overridden unknown model needs neither — the air-gapped path."""
    if model_name in KNOWN_MODEL_SHAPES:
        return dict(KNOWN_MODEL_SHAPES[model_name])
    if all(overrides.get(k) is not None for k in REQUIRED_SHAPE_FIELDS):
        return {}
    try:
        from transformers import AutoConfig

        hf = AutoConfig.from_pretrained(model_name)
        return dict(
            num_hidden_layers=hf.num_hidden_layers,
            num_attention_heads=hf.num_attention_heads,
            num_key_value_heads=getattr(
                hf, "num_key_value_heads", hf.num_attention_heads),
            hidden_size=hf.hidden_size,
            intermediate_size=hf.intermediate_size,
            vocab_size=hf.vocab_size,
            rms_norm_eps=getattr(hf, "rms_norm_eps", 1e-5),
            rope_theta=getattr(hf, "rope_theta", 10000.0),
            max_position_embeddings=hf.max_position_embeddings,
        )
    except Exception as e:  # pragma: no cover - network-dependent
        missing = [k for k in REQUIRED_SHAPE_FIELDS if overrides.get(k) is None]
        raise SystemExit(
            f"model {model_name!r} is not in the built-in shape table and "
            f"AutoConfig fetch failed ({e}); pass explicit "
            + " ".join(f"--{k}" for k in missing)) from e


def create_single_config(
    out_dir: str,
    exp_name: str,
    *,
    tp: int = 1, cp: int = 1, dp: int = 1, pp: int = 1,
    pp_engine: str = "1f1b",
    pp_interleave: Optional[int] = None,
    cp_zigzag: Optional[bool] = None,
    cp_impl: Optional[str] = None,
    tp_sequence_parallel: Optional[bool] = None,
    zero1: Optional[bool] = None,
    fsdp: Optional[bool] = None,
    model_name: str = "HuggingFaceTB/SmolLM-360M-Instruct",
    num_hidden_layers: Optional[int] = None,
    num_attention_heads: Optional[int] = None,
    num_key_value_heads: Optional[int] = None,
    hidden_size: Optional[int] = None,
    intermediate_size: Optional[int] = None,
    vocab_size: Optional[int] = None,
    grad_acc_steps: int = 1,
    mbs: int = 1,
    seq_len: int = 1024,
    subset_name: Optional[str] = None,
    dataset_name: Optional[str] = None,
    use_wandb: bool = False,
    use_cpu: bool = False,
    learning_rate: Optional[float] = None,
    lr_schedule: Optional[str] = None,
    lr_warmup_steps: Optional[int] = None,
    lr_min_ratio: Optional[float] = None,
    lr_decay_steps: Optional[int] = None,
    total_train_steps: Optional[int] = None,
    seed: Optional[int] = None,
    remat: Optional[str] = None,
    grad_accum_dtype: Optional[str] = None,
    steps_per_call: Optional[int] = None,
    template_path: str = TEMPLATE_PATH,
    exist_ok: bool = False,
) -> str:
    """Write <out_dir>/<exp_name>/config.json; returns its path."""
    with open(template_path) as f:
        content = json.load(f)

    d = content["distributed"]
    d.update(tp_size=tp, cp_size=cp, dp_size=dp, pp_size=pp,
             pp_engine=pp_engine, use_cpu=use_cpu)
    if pp_interleave is not None:  # None = keep the template's value
        d["pp_interleave"] = pp_interleave
    if cp_zigzag is not None:
        d["cp_zigzag"] = cp_zigzag
    if cp_impl is not None:
        d["cp_impl"] = cp_impl
    if tp_sequence_parallel is not None:
        d["tp_sequence_parallel"] = tp_sequence_parallel
    if zero1 is not None:
        d["zero1"] = zero1
    if fsdp is not None:
        d["fsdp"] = fsdp

    m = content["model"]
    m["name"] = model_name
    # Explicit overrides win over fetched/known shapes (reference
    # create_config.py:55-60); a fully-overridden unknown model never
    # touches the network.
    overrides = {k: v for k, v in dict(
        num_hidden_layers=num_hidden_layers,
        num_attention_heads=num_attention_heads,
        num_key_value_heads=num_key_value_heads,
        hidden_size=hidden_size,
        intermediate_size=intermediate_size,
        vocab_size=vocab_size,
    ).items() if v is not None}
    m.update(model_shape_defaults(model_name, overrides))
    m.update(overrides)

    t = content["training"]
    t.update(gradient_accumulation_steps=grad_acc_steps,
             micro_batch_size=mbs, seq_length=seq_len)
    if seq_len > m["max_position_embeddings"]:
        m["max_position_embeddings"] = seq_len
    if learning_rate is not None:
        t["learning_rate"] = learning_rate
    if lr_schedule is not None:
        t["lr_schedule"] = lr_schedule
    if lr_warmup_steps is not None:
        t["lr_warmup_steps"] = lr_warmup_steps
    if lr_min_ratio is not None:
        t["lr_min_ratio"] = lr_min_ratio
    if lr_decay_steps is not None:
        t["lr_decay_steps"] = lr_decay_steps
    if total_train_steps is not None:
        t["total_train_steps"] = total_train_steps
    if seed is not None:
        t["seed"] = seed
    if remat is not None:
        t["remat"] = remat
    if grad_accum_dtype is not None:
        t["grad_accum_dtype"] = grad_accum_dtype
    if steps_per_call is not None:
        t["steps_per_call"] = steps_per_call

    if dataset_name is not None:
        content["dataset"]["name"] = dataset_name
    if subset_name is not None:
        content["dataset"]["subset_name"] = subset_name
    content["logging"]["use_wandb"] = use_wandb
    content["logging"]["run_name"] = exp_name

    gbs = mbs * grad_acc_steps * dp
    print(f"global batch size: {gbs} samples, {gbs * seq_len} tokens "
          f"(mbs {mbs} x grad_acc {grad_acc_steps} x dp {dp})")

    # Validate before writing so a bad topology fails here, not at launch.
    from picotron_tpu.config import Config

    Config.from_dict(content)

    run_path = os.path.join(out_dir, exp_name)
    os.makedirs(run_path, exist_ok=exist_ok)
    cfg_path = os.path.join(run_path, "config.json")
    with open(cfg_path, "w") as f:
        json.dump(content, f, indent=2)
    return cfg_path


def build_parser() -> argparse.ArgumentParser:
    # Flag surface mirrors the reference (create_config.py:86-107).
    p = argparse.ArgumentParser(description="Create experiment config.json files")
    p.add_argument("--out_dir", type=str, default="tmp")
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--cp", type=int, default=1)
    p.add_argument("--dp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--pp_engine", type=str, default="1f1b")
    p.add_argument("--pp_interleave", type=int, default=None,
                   help="virtual pipeline stages per device (interleaved "
                        "1F1B; shrinks the bubble by this factor)")
    p.add_argument("--cp_zigzag", action="store_true", default=None,
                   help="load-balanced zigzag context-parallel layout")
    p.add_argument("--cp_impl", type=str, default=None,
                   choices=("ring", "ulysses"),
                   help="context-parallel algorithm: ppermute K/V ring or "
                        "Ulysses all-to-all seq<->head resharding")
    p.add_argument("--tp_sequence_parallel", action="store_true", default=None,
                   help="Megatron sequence parallelism: seq-shard the "
                        "residual stream over tp between TP blocks")
    p.add_argument("--zero1", action="store_true", default=None,
                   help="ZeRO-1: shard optimizer state over dp "
                        "(reduce-scatter grads, chunked update, all-gather)")
    p.add_argument("--fsdp", action="store_true", default=None,
                   help="FSDP/ZeRO-3 for the layer stack: params rest "
                        "dp-sharded, gathered just in time per layer")
    p.add_argument("--model_name", type=str,
                   default="HuggingFaceTB/SmolLM-360M-Instruct")
    p.add_argument("--num_hidden_layers", type=int, default=None)
    p.add_argument("--num_attention_heads", type=int, default=None)
    p.add_argument("--num_key_value_heads", type=int, default=None)
    p.add_argument("--hidden_size", type=int, default=None)
    p.add_argument("--intermediate_size", type=int, default=None)
    p.add_argument("--vocab_size", type=int, default=None)
    p.add_argument("--grad_acc_steps", type=int, default=1)
    p.add_argument("--mbs", type=int, default=1)
    p.add_argument("--seq_len", type=int, default=1024)
    p.add_argument("--dataset_name", type=str, default=None)
    p.add_argument("--subset_name", type=str, default=None)
    p.add_argument("--exp_name", type=str, default="dummy_exp")
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--lr_schedule", type=str, default=None,
                   choices=("constant", "cosine", "linear"))
    p.add_argument("--lr_warmup_steps", type=int, default=None)
    p.add_argument("--lr_min_ratio", type=float, default=None)
    p.add_argument("--lr_decay_steps", type=int, default=None,
                   help="decay horizon in steps (default: total_train_steps)")
    p.add_argument("--total_train_steps", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--remat", type=str, default=None,
                   choices=("none", "full", "save_attn"))
    p.add_argument("--grad_accum_dtype", type=str, default=None,
                   choices=("float32", "param"),
                   help="microbatch grad accumulator dtype: float32 (the "
                        "reference's main-grad policy, default) or 'param' "
                        "(bf16 — halves grad memory + dp sync wire)")
    p.add_argument("--steps_per_call", type=int, default=None,
                   help="optimizer steps fused per device dispatch")
    p.add_argument("--use_wandb", action="store_true")
    p.add_argument("--use_cpu", action="store_true")
    p.add_argument("--template", type=str, default=TEMPLATE_PATH)
    p.add_argument("--overwrite", action="store_true",
                   help="allow regenerating into an existing experiment dir")
    p.add_argument("--download", action="store_true",
                   help="also download the model's safetensors from HF "
                        "(needs network; reference create_config.py:134)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    path = create_single_config(
        out_dir=args.out_dir, exp_name=args.exp_name,
        tp=args.tp, cp=args.cp, dp=args.dp, pp=args.pp,
        pp_engine=args.pp_engine, pp_interleave=args.pp_interleave,
        cp_zigzag=args.cp_zigzag,
        cp_impl=args.cp_impl,
        tp_sequence_parallel=args.tp_sequence_parallel, zero1=args.zero1,
        fsdp=args.fsdp,
        model_name=args.model_name,
        num_hidden_layers=args.num_hidden_layers,
        num_attention_heads=args.num_attention_heads,
        num_key_value_heads=args.num_key_value_heads,
        hidden_size=args.hidden_size,
        intermediate_size=args.intermediate_size,
        vocab_size=args.vocab_size,
        grad_acc_steps=args.grad_acc_steps, mbs=args.mbs, seq_len=args.seq_len,
        dataset_name=args.dataset_name, subset_name=args.subset_name,
        use_wandb=args.use_wandb, use_cpu=args.use_cpu,
        learning_rate=args.lr, lr_schedule=args.lr_schedule,
        lr_warmup_steps=args.lr_warmup_steps, lr_min_ratio=args.lr_min_ratio,
        lr_decay_steps=args.lr_decay_steps,
        total_train_steps=args.total_train_steps,
        seed=args.seed, remat=args.remat,
        grad_accum_dtype=args.grad_accum_dtype,
        steps_per_call=args.steps_per_call,
        template_path=args.template, exist_ok=args.overwrite,
    )
    print(f"config created: {path}")
    if args.download:
        from picotron_tpu.checkpoint import download_model

        download_model(args.model_name, "./hf_model_safetensors/")
        print("safetensors downloaded")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
