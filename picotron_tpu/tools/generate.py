"""Text generation CLI — the serving path end-to-end.

    python -m picotron_tpu.tools.generate --config exp.json \
        --load-path checkpoints --prompt-ids 5,276,388 --max-new-tokens 64

Weights come from one of:
  --load-path    orbax training checkpoint dir (params-only restore;
                 pp/interleave-trained stacks are remapped to the engine's
                 contiguous layout at load — checkpoint.load_params)
  --hf-path      HF-format safetensors file/dir (checkpoint.load_hf_safetensors)
  --random-init  seed-derived random weights (plumbing smoke runs)

Prompts are repeatable --prompt-ids (comma-separated token ids — works
air-gapped) or repeatable --prompt (text; needs the transformers tokenizer
for model.name). All prompts run through one ContinuousBatcher, so a mixed
batch exercises admission, slot recycling, and per-request sampling params.

``--smoke`` is the `make decode-smoke` target: a built-in tiny CPU model
with random weights generates from fixed prompts in seconds and exits
nonzero on any malfunction — no config, checkpoint, or network needed.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from functools import partial
from typing import Optional

SMOKE_CONFIG = {
    "distributed": {"tp_size": 1, "use_cpu": True},
    "model": dict(
        name="tiny-smoke", num_hidden_layers=4, num_attention_heads=8,
        num_key_value_heads=4, hidden_size=64, intermediate_size=128,
        vocab_size=256, max_position_embeddings=128, dtype="float32",
        attention_impl="sdpa"),
    "training": {"seq_length": 64},
    "dataset": {"name": "synthetic"},
}


def _load_weights(args, cfg, engine):
    """Resolve --load-path / --hf-path / --random-init to sharded params.
    An ``engine`` built with ``weight_dtype="int8"`` gets the per-channel
    quantized tree: the HF path quantizes as it streams off the file,
    the orbax path quantizes off the restore, the random-init path
    quantizes the fresh tree — all three land as the same
    ``{"q", "s"}``-leaf form the engine's matmul sites dispatch on."""
    import jax

    from picotron_tpu import checkpoint as ckpt
    from picotron_tpu.models import llama
    from picotron_tpu.topology import named_shardings

    quant = getattr(engine, "quant_weights", False)
    wdt = "int8" if quant else "bf16"
    if args.hf_path:
        return ckpt.load_hf_safetensors(args.hf_path, cfg.model, engine.topo,
                                        weight_dtype=wdt)
    if args.load_path:
        # the restore is SHARDED for both weight formats (checkpoints
        # store dense, so the dense pspecs describe what orbax reads);
        # the int8 path then quantizes leaf by leaf on the sharded tree
        # — sharding, not donation, is what keeps a big model's dense
        # tree and fp32 quantization transients off any single device
        # (llama.quantize_params explains why donation is rejected)
        like = jax.eval_shape(partial(llama.init_params, m=cfg.model),
                              jax.random.PRNGKey(0))
        shardings = named_shardings(engine.topo,
                                    llama.param_pspecs(cfg.model))
        like = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                               sharding=sh),
            like, shardings)
        mgr = ckpt.CheckpointManager(
            args.load_path, mirror_dir=cfg.resilience.ckpt_mirror_dir)
        params, step, tokens = mgr.load_params(
            like, layout=(cfg.model.num_hidden_layers, 1), weight_dtype=wdt)
        mgr.close()
        print(f"loaded step {step} ({tokens} trained tokens) "
              f"from {args.load_path}")
        return engine.shard_params(params) if quant else params
    params = jax.jit(lambda k: llama.init_params(k, cfg.model))(
        jax.random.PRNGKey(args.seed))
    if quant:
        params = llama.quantize_params(params)
    return engine.shard_params(params)


def _build_requests(args, tokenizer) -> list:
    from picotron_tpu.inference import Request

    prompts = []
    for spec in args.prompt_ids or ():
        prompts.append([int(t) for t in spec.replace(" ", "").split(",") if t])
    for text in args.prompt or ():
        prompts.append(list(tokenizer(text)["input_ids"]))
    if not prompts:
        raise SystemExit("no prompts: pass --prompt-ids and/or --prompt")
    return [
        Request(uid=f"req{i}", prompt=p, max_new_tokens=args.max_new_tokens,
                temperature=args.temperature, top_k=args.top_k,
                top_p=args.top_p, eos_id=args.eos_id)
        for i, p in enumerate(prompts)
    ]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="generate from a picotron-tpu checkpoint "
                    "(continuous-batched KV-cache decode)")
    ap.add_argument("--config", help="training config.json (model shape, tp)")
    ap.add_argument("--load-path", default="", help="orbax checkpoint dir")
    ap.add_argument("--hf-path", default="", help="HF safetensors file/dir")
    ap.add_argument("--random-init", action="store_true",
                    help="seed-derived random weights (plumbing smoke)")
    ap.add_argument("--prompt-ids", action="append",
                    help="comma-separated token ids (repeatable)")
    ap.add_argument("--prompt", action="append",
                    help="text prompt (repeatable; needs the HF tokenizer)")
    ap.add_argument("--max-new-tokens", type=int, default=32)
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="0 = greedy")
    ap.add_argument("--top-k", type=int, default=0, help="<= 0 disables")
    ap.add_argument("--top-p", type=float, default=1.0, help=">= 1 disables")
    ap.add_argument("--eos-id", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slots", type=int, default=4,
                    help="decode batch width (engine slots)")
    ap.add_argument("--max-seq-len", type=int, default=None)
    ap.add_argument("--decode-block-len", type=int, default=None,
                    help="decode steps fused per dispatch (default: "
                         "config inference.decode_block_len; 1 = per-token "
                         "loop)")
    ap.add_argument("--kv-cache-dtype", choices=["auto", "int8"],
                    default=None,
                    help="KV cache storage (default: config "
                         "inference.kv_cache_dtype; int8 = quantized "
                         "cache, ~2x slots/context per HBM byte)")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="chunked-prefill width for prompts longer than "
                         "this (default: config inference.prefill_chunk)")
    ap.add_argument("--spec-len", type=int, default=None,
                    help="speculative decoding: draft tokens per verify "
                         "dispatch (default: config inference.spec_len; "
                         "0 = off)")
    ap.add_argument("--spec-ngram", type=int, default=None,
                    help="longest suffix n-gram the prompt-lookup drafter "
                         "matches (default: config inference.spec_ngram)")
    ap.add_argument("--kv-layout", choices=["contiguous", "paged"],
                    default=None,
                    help="KV cache layout (default: config "
                         "inference.kv_layout; paged = block-table pool "
                         "with refcounted prefix sharing + COW)")
    ap.add_argument("--kv-page-policy", choices=["uniform", "hot_bf16"],
                    default=None,
                    help="per-page storage policy (paged layout only; "
                         "default: config inference.kv_page_policy) — "
                         "hot_bf16 reads radix-shared prefix pages at "
                         "full precision, exclusive tails as int8")
    ap.add_argument("--weight-dtype", choices=["bf16", "int8"],
                    default=None,
                    help="weight storage (default: config "
                         "inference.weight_dtype) — int8 = per-channel "
                         "quantized matmul weights served through the "
                         "fused dequant matmul, ~half the bf16 bytes")
    ap.add_argument("--check-weight-parity", action="store_true",
                    help="run the batch again on a bf16 engine fed the "
                         "FAKE-QUANT reference (dequantized int8 weights "
                         "through the dense matmul) and fail unless every "
                         "request's tokens match — the `make quant-smoke` "
                         "gate proving the fused int8 pipeline implements "
                         "fake-quant semantics exactly")
    ap.add_argument("--adapter", default=None,
                    metavar="RANK[:SEED[:SCALE]]",
                    help="serve every request through one seed-derived "
                         "LoRA adapter (tenancy.AdapterPack, slot 1) "
                         "over the base weights — the multi-tenant "
                         "segmented dispatch with a single tenant")
    ap.add_argument("--check-adapter-parity", action="store_true",
                    help="run the batch again on an adapter-less dense "
                         "engine fed the MERGED reference (W + A @ B, "
                         "llama.merge_adapter; an int8 primary merges "
                         "into its fake-quant dense twin) and fail "
                         "unless every request's tokens match — the "
                         "`make tenant-smoke` gate proving the "
                         "segmented adapter matmul implements "
                         "merged-weight semantics exactly (greedy-only, "
                         "same exactness rule as --check-weight-parity)")
    ap.add_argument("--sample-on-device", action="store_true",
                    help="fused sampling epilogue: prefill/decode "
                         "dispatches sample inside the jitted program "
                         "and ship token ids, never [B, vocab] logits "
                         "(seeded-identical to the host sampler)")
    ap.add_argument("--check-layout-parity", action="store_true",
                    help="run the batch again under the OTHER kv layout "
                         "and fail unless every request's tokens match — "
                         "the `make paged-smoke` equivalence gate")
    ap.add_argument("--smoke", action="store_true",
                    help="built-in tiny CPU model + random init + fixed "
                    "prompts (the `make decode-smoke` target)")
    args = ap.parse_args(argv)

    from picotron_tpu.config import Config
    from picotron_tpu.train import _ensure_devices

    if args.smoke:
        cfg = Config.from_dict(SMOKE_CONFIG)
        args.random_init = True
        if not args.prompt_ids and not args.prompt:
            args.prompt_ids = ["1,2,3,4,5,6,7,8", "9,10,11", "12,13,14,15,16"]
        args.max_new_tokens = min(args.max_new_tokens, 16)
    elif args.config:
        with open(args.config) as f:
            cfg = Config.from_dict(json.load(f))
    else:
        ap.error("pass --config (or --smoke)")
    if not (args.load_path or args.hf_path or args.random_init):
        ap.error("pass one of --load-path / --hf-path / --random-init")
    _ensure_devices(cfg)

    from picotron_tpu.inference import ContinuousBatcher, InferenceEngine

    tokenizer = None
    if args.prompt:
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(cfg.model.name)

    if args.kv_cache_dtype is not None:
        cfg.inference.kv_cache_dtype = args.kv_cache_dtype
    if args.kv_layout is not None:
        cfg.inference.kv_layout = args.kv_layout
    if args.kv_page_policy is not None:
        cfg.inference.kv_page_policy = args.kv_page_policy
    if args.sample_on_device:
        cfg.inference.sample_on_device = True
    if args.weight_dtype is not None:
        cfg.inference.weight_dtype = args.weight_dtype
    if args.check_weight_parity and cfg.inference.weight_dtype != "int8":
        ap.error("--check-weight-parity compares int8 against the "
                 "fake-quant reference; pass --weight-dtype int8")
    if args.check_weight_parity and args.temperature != 0.0:
        # the gate's contract is token IDENTITY, which only greedy decode
        # guarantees: the fused and dense matmuls agree to allclose, not
        # bitwise, so a seeded categorical draw can flip at a near-tie —
        # same exactness rule as --check-layout-parity's hot_bf16 guard
        ap.error("--check-weight-parity is a greedy-only gate (fused vs "
                 "dense logits are allclose, not bit-equal; sampling can "
                 "flip at near-ties); drop --temperature")
    if args.check_adapter_parity and args.adapter is None:
        ap.error("--check-adapter-parity compares the segmented adapter "
                 "dispatch against its merged-weight oracle; pass "
                 "--adapter RANK[:SEED[:SCALE]]")
    if args.check_adapter_parity and args.temperature != 0.0:
        ap.error("--check-adapter-parity is a greedy-only gate (segmented "
                 "vs merged logits are allclose, not bit-equal; sampling "
                 "can flip at near-ties); drop --temperature")
    if args.check_weight_parity and args.adapter is not None:
        ap.error("--check-weight-parity's reference engine is "
                 "adapter-less; run it without --adapter (adapter "
                 "correctness has its own gate, --check-adapter-parity)")
    adapter_rank, adapter_seed, adapter_scale = 0, 0, None
    if args.adapter is not None:
        from picotron_tpu.inference import tenancy as _tenancy

        parts = str(args.adapter).split(":")
        try:
            adapter_rank = int(parts[0])
            adapter_seed = int(parts[1]) if len(parts) > 1 else 0
            adapter_scale = (float(parts[2]) if len(parts) > 2
                             else _tenancy.DEFAULT_ADAPTER_SCALE)
        except ValueError as e:
            ap.error(f"bad --adapter spec {args.adapter!r} "
                     f"(want RANK[:SEED[:SCALE]]): {e}")
        if adapter_rank < 1:
            ap.error("--adapter rank must be >= 1")
    if args.check_layout_parity and cfg.inference.kv_page_policy != "uniform":
        # checked on the EFFECTIVE config (flag or config file): mixed
        # pages quantize cold tails, so contiguous-vs-paged would be
        # allclose, not token-equal — the parity gate is a uniform check
        ap.error("--check-layout-parity needs kv_page_policy 'uniform' "
                 "(hot_bf16 int8 tails make parity allclose, not exact)")
    t0 = time.perf_counter()
    adapters = adapter_leaves = None
    if args.adapter is not None:
        adapters = _tenancy.AdapterPack(cfg.model, slots=2,
                                        rank=adapter_rank)
        adapter_leaves = adapters.random_leaves(
            adapter_rank, adapter_seed, adapter_scale)
        adapters.set_slot(1, adapter_leaves)
    engine = InferenceEngine(cfg, slots=args.slots,
                             max_seq_len=args.max_seq_len,
                             decode_block_len=args.decode_block_len,
                             prefill_chunk=args.prefill_chunk,
                             spec_len=args.spec_len,
                             spec_ngram=args.spec_ngram,
                             adapters=adapters)
    params = _load_weights(args, cfg, engine)
    requests = _build_requests(args, tokenizer)
    if adapters is not None:
        for r in requests:
            r.adapter_slot = 1
    setup_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    batcher = ContinuousBatcher(engine, params, seed=args.seed)
    results = batcher.run(requests)
    gen_s = time.perf_counter() - t0

    if args.check_weight_parity:
        # same batch, same seed, a bf16 engine fed the FAKE-QUANT
        # reference (quantize -> dequantize through the dense matmul):
        # every request's tokens must match exactly. The quantization
        # error is identical on both sides, so any difference is the
        # fused int8 pipeline itself (kernel/fallback, scale sharding,
        # dispatch wiring) — the weight-side counterpart of
        # --check-layout-parity's equivalence gate.
        import jax.numpy as jnp

        from picotron_tpu.models import llama

        eng2 = InferenceEngine(cfg, slots=args.slots,
                               max_seq_len=args.max_seq_len,
                               decode_block_len=args.decode_block_len,
                               prefill_chunk=args.prefill_chunk,
                               spec_len=args.spec_len,
                               spec_ngram=args.spec_ngram,
                               weight_dtype="bf16")
        dense = _load_weights(args, cfg, eng2)
        fakeq = llama.dequantize_params(llama.quantize_params(dense),
                                        jnp.dtype(cfg.model.dtype))
        results2 = ContinuousBatcher(
            eng2, eng2.shard_params(fakeq), seed=args.seed,
        ).run(_build_requests(args, tokenizer))
        bad = [u for u in results if results[u].tokens != results2[u].tokens]
        if bad:
            print(f"FAILED: weight parity mismatch (int8 vs fake-quant "
                  f"bf16) for {bad}", file=sys.stderr)
            return 1
        print(f"weight parity: int8 == fake-quant reference for "
              f"{len(results)} requests")

    if args.check_adapter_parity:
        # same batch, same seed, an ADAPTER-LESS dense engine fed the
        # merged tree W + A @ B (llama.merge_adapter): every request's
        # tokens must match exactly. The segmented gather (per-row A/B
        # pair through the lora matmul, residual added before the tp
        # collective) and the merged matmul compute the same values to
        # fp32 tolerance; greedy pins the tokens. An int8 primary merges
        # into its FAKE-QUANT dense twin — the same reference recipe as
        # --check-weight-parity, so one run gates both the adapter path
        # and its int8 composition.
        import jax.numpy as jnp

        from picotron_tpu.models import llama

        eng2 = InferenceEngine(cfg, slots=args.slots,
                               max_seq_len=args.max_seq_len,
                               decode_block_len=args.decode_block_len,
                               prefill_chunk=args.prefill_chunk,
                               spec_len=args.spec_len,
                               spec_ngram=args.spec_ngram,
                               weight_dtype="bf16")
        dense = _load_weights(args, cfg, eng2)
        if engine.weight_dtype == "int8":
            dense = llama.dequantize_params(
                llama.quantize_params(dense), jnp.dtype(cfg.model.dtype))
        merged = llama.merge_adapter(dense, adapter_leaves)
        results2 = ContinuousBatcher(
            eng2, eng2.shard_params(merged), seed=args.seed,
        ).run(_build_requests(args, tokenizer))
        bad = [u for u in results if results[u].tokens != results2[u].tokens]
        if bad:
            print(f"FAILED: adapter parity mismatch (segmented vs "
                  f"merged-weight reference) for {bad}", file=sys.stderr)
            return 1
        print(f"adapter parity: segmented adapter == merged-weight "
              f"reference for {len(results)} requests "
              f"(rank={adapter_rank}, weights={engine.weight_dtype})")

    if args.check_layout_parity:
        # same batch, same seed/weights, the OTHER cache layout: every
        # request's token stream must match exactly (the paged layout's
        # equivalence gate — prefix sharing and COW must be invisible in
        # the output)
        other = ("contiguous" if engine.kv_layout == "paged" else "paged")
        eng2 = InferenceEngine(cfg, slots=args.slots,
                               max_seq_len=args.max_seq_len,
                               decode_block_len=args.decode_block_len,
                               prefill_chunk=args.prefill_chunk,
                               spec_len=args.spec_len,
                               spec_ngram=args.spec_ngram,
                               kv_layout=other,
                               # hot_bf16 is defined over pool pages; the
                               # contiguous side of the parity pair runs
                               # uniform (and the comparison is only run
                               # with a uniform primary — mixed tails
                               # quantize, parity would be allclose not ==)
                               kv_page_policy="uniform")
        results2 = ContinuousBatcher(
            eng2, _load_weights(args, cfg, eng2), seed=args.seed,
        ).run(_build_requests(args, tokenizer))
        bad = [u for u in results
               if results[u].tokens != results2[u].tokens]
        if bad:
            print(f"FAILED: layout parity mismatch "
                  f"({engine.kv_layout} vs {other}) for {bad}",
                  file=sys.stderr)
            return 1
        print(f"layout parity: {engine.kv_layout} == {other} for "
              f"{len(results)} requests")

    n_tokens = 0
    failed = False
    for req in requests:
        r = results[req.uid]
        n_tokens += len(r.tokens)
        ok = (len(r.tokens) > 0
              and all(0 <= t < cfg.model.vocab_size for t in r.tokens))
        failed |= not ok
        line = (f"[{r.uid}] prompt={r.prompt} -> {r.tokens} "
                f"({r.finish_reason})")
        if tokenizer is not None:
            line += f"\n  text: {tokenizer.decode(r.prompt + r.tokens)!r}"
        print(line)
    dpt = batcher.decode_dispatches / max(batcher.generated_tokens, 1)
    spec = (f"spec={engine.spec_len} "
            f"accept={batcher.accept_rate:.2f} " if engine.spec_len > 0
            and batcher.accept_rate is not None else "")
    print(f"{n_tokens} tokens in {gen_s:.2f}s "
          f"({n_tokens / max(gen_s, 1e-9):.1f} tok/s, "
          f"setup {setup_s:.1f}s, slots={engine.slots}, "
          f"tp={engine.topo.tp_size}, block={engine.decode_block_len}, "
          f"kv={'int8' if engine.quantized else str(engine.cache_dtype)}, "
          f"weights={engine.weight_dtype}, "
          f"{spec}{batcher.decode_dispatches} decode dispatches = "
          f"{dpt:.3f}/token)")
    if failed:
        print("FAILED: some request produced no/invalid tokens",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
