"""One-shot on-chip work agenda for a flaky-tunnel site.

The TPU behind this rig's tunnel dies for hours at a time (rounds 3-4);
when it comes back there may be only a short window. This tool runs the
whole chip-blocked agenda unattended, in priority order, saving every
artifact under ``docs/chip_runs/<utc-stamp>/`` so one live window converts
into committed evidence:

1. kernel parity  — PICOTRON_TEST_TPU=1 pytest tests/test_tpu_kernels.py
2. bench          — python bench.py          (includes the bshd A/B)
3. bench_7b       — python bench_7b.py       (includes the bshd A/B)
4. profile        — a jax.profiler trace of the winning SmolLM config
                    (via train.py's profiler window on a short run)
5. cond_gating    — measure_cond_gating: the on-hardware cost of
                    lax.cond stage gating vs compute-both masking

Each step gets its own timeout and log file; a step failing (tunnel dying
mid-window) does not stop the later ones from being attempted. Run:

    python -m picotron_tpu.tools.chip_agenda [out_dir] [--only a,b,...]

``--only`` reruns a subset — tunnel_watch uses it so a second window only
repeats the steps the first window lost to a flap.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))

# The agenda, in priority order. tunnel_watch imports this so its step set
# and worst-case budget stay in lockstep with the agenda's.
STEP_TIMEOUTS = {
    "kernel_parity": 1500,
    "bench": 5700,
    "bench_7b": 5700,
    # the whole pair's budget: the chip run gets this MINUS the derived,
    # chip-free profile_analysis step's 300 (carved off in the step loop)
    # — so tunnel_watch's global cap (sum of pending step timeouts) stays
    # correct without knowing about derived steps
    "profile": 1800,
    "cond_gating": 1500,
    "offload_bw": 1500,
    # serving-side: continuous-batched KV-cache decode tokens/s (no tunnel
    # orchestrator of its own — the agenda timeout is its failure bound)
    "bench_decode": 1500,
}
PROFILE_ANALYSIS_TIMEOUT = 300


# Process group of the step currently executing, for the SIGTERM handler:
# each step runs in its OWN session (so a step timeout can kill the step's
# whole tree), which means anyone killing the *agenda* would orphan the
# in-flight step — and an orphan holds the TPU for the rest of the window.
# tunnel_watch SIGTERMs the agenda on its global cap; the handler forwards
# a SIGKILL to the live step's group before dying.
_current_pgid: int | None = None


def _install_term_handler() -> None:
    import signal

    def _handler(signum, frame):
        if _current_pgid is not None:
            try:
                os.killpg(_current_pgid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        sys.exit(128 + signum)

    signal.signal(signal.SIGTERM, _handler)


def run_step(name: str, cmd: list[str], out_dir: str, timeout: float,
             env: dict | None = None) -> dict:
    """Run one agenda step, streaming combined stdout+stderr STRAIGHT to the
    log file — in-memory capture would lose the whole window's output when a
    timeout fires (CPython discards captured output on TimeoutExpired). The
    child gets its own session so a timeout kills the entire process GROUP:
    the benches spawn their own children, and an orphan would keep holding
    the TPU for every later step."""
    import signal

    global _current_pgid
    log = os.path.join(out_dir, f"{name}.log")
    print(f"== {name}: {' '.join(cmd)} (timeout {timeout:.0f}s)", flush=True)
    pgid_file = os.path.join(out_dir, "current_step.pgid")
    # PYTHONUNBUFFERED for EVERY step: stdout goes to a file (block-
    # buffered), and a wedged step gets SIGKILLed by its timeout — without
    # write-through the log would be 0 bytes with no clue what hung
    step_env = dict(env or os.environ, PYTHONUNBUFFERED="1")
    with open(log, "w") as f:
        p = subprocess.Popen(cmd, cwd=REPO, env=step_env,
                             stdout=f, stderr=subprocess.STDOUT,
                             start_new_session=True)
        try:
            _current_pgid = os.getpgid(p.pid)
        except ProcessLookupError:
            _current_pgid = None
        # last-resort breadcrumb: if BOTH the agenda and its SIGTERM
        # handler are killed outright, the watcher reads this file and
        # killpgs the step itself (the step's own session survives a kill
        # of the agenda's group)
        with open(pgid_file, "w") as pf:
            pf.write(str(_current_pgid or ""))
        try:
            rc = p.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                p.kill()
            p.wait()
            rc = -9
            f.write(f"\n[timed out after {timeout:.0f}s; process group "
                    f"killed]\n")
        finally:
            _current_pgid = None
            try:
                os.remove(pgid_file)
            except OSError:
                pass
    with open(log, "rb") as f:
        f.seek(max(0, os.path.getsize(log) - 400))
        # binary + replace: a byte-offset seek can land mid-UTF-8-char
        tail = f.read().decode("utf-8", errors="replace").replace("\n", " ")
    print(f"   -> rc={rc} log={log}\n   tail: {tail}", flush=True)
    return {"step": name, "rc": rc, "log": log}


def main(argv=None):
    _install_term_handler()
    ap = argparse.ArgumentParser()
    ap.add_argument("out_dir", nargs="?", default=None)
    ap.add_argument("--only", default=None,
                    help="comma-separated step names to run (default: all)")
    args = ap.parse_args(argv)

    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y%m%dT%H%M%SZ")
    out_dir = args.out_dir or os.path.join(REPO, "docs", "chip_runs", stamp)
    os.makedirs(out_dir, exist_ok=True)

    def profile_cfg_path():
        # profiler trace of the winning single-chip config: short real
        # training run with the profiler window over steps [4, 6)
        from picotron_tpu.config import SMOLLM_1_7B  # plain dict, no jax

        cfg = {
            "distributed": {"dp_size": 1, "pp_size": 1, "cp_size": 1,
                            "tp_size": 1},
            "model": dict(SMOLLM_1_7B),
            "training": {"seq_length": 2048, "micro_batch_size": 2,
                         "gradient_accumulation_steps": 1,
                         "remat": "save_attn", "learning_rate": 3e-4,
                         "total_train_steps": 6, "steps_per_call": 1},
            "dataset": {"name": "synthetic"},
            "logging": {"profile_start": 4, "profile_stop": 6,
                        "profile_dir": os.path.join(out_dir, "profile")},
        }
        path = os.path.join(out_dir, "profile_cfg.json")
        with open(path, "w") as f:
            json.dump(cfg, f, indent=2)
        return path

    # name -> cmd-thunk; thunks so profile_cfg.json is only written when
    # its step is selected. The benches carry their own orchestrator
    # (probe/retry/null-artifact). cond_gating measures the on-hardware
    # cost of lax.cond stage gating (round-3 VERDICT weak #3).
    # -v: the log must show which test is in flight — a wedged remote
    # compile otherwise leaves no way to tell WHAT hung (the
    # 20260731T0103 window died exactly like that)
    tpu_env = dict(os.environ, PICOTRON_TEST_TPU="1")
    step_cmds = {
        "kernel_parity": lambda: (
            [sys.executable, "-m", "pytest", "-v",
             "tests/test_tpu_kernels.py"], tpu_env),
        "bench": lambda: ([sys.executable, "bench.py"], None),
        "bench_7b": lambda: ([sys.executable, "bench_7b.py"], None),
        "profile": lambda: (
            [sys.executable, "train.py", "--config", profile_cfg_path()],
            None),
        "cond_gating": lambda: (
            [sys.executable, "-m", "picotron_tpu.tools.measure_cond_gating"],
            None),
        "offload_bw": lambda: (
            [sys.executable, "-m", "picotron_tpu.tools.measure_offload_bw"],
            None),
        "bench_decode": lambda: ([sys.executable, "bench_decode.py"], None),
    }
    assert set(step_cmds) == set(STEP_TIMEOUTS)
    known = set(STEP_TIMEOUTS)
    only = set(args.only.split(",")) if args.only else known
    if only - known:
        ap.error(f"unknown step(s) {sorted(only - known)}; "
                 f"known: {sorted(known)}")

    results = []
    summary_path = os.path.join(out_dir, "summary.json")

    def flush_summary():
        # after EVERY step, not just at the end: a SIGTERM mid-window must
        # not cost the watcher the record of steps that already passed
        with open(summary_path, "w") as f:
            json.dump(results, f, indent=2)

    for name, timeout in STEP_TIMEOUTS.items():
        if name not in only:
            continue
        if name == "profile":  # leave room for the derived analysis step
            timeout -= PROFILE_ANALYSIS_TIMEOUT
        cmd, env = step_cmds[name]()
        results.append(run_step(name, cmd, out_dir, timeout, env=env))
        flush_summary()
        if name == "profile" and results[-1]["rc"] == 0:
            # Derived step, chip-free (pure xplane.pb parsing): the
            # window's trace leaves WITH its cost breakdown, so the
            # profiler-driven MFU pass needs no follow-up session. Its
            # budget is carved out of the profile slot (see
            # STEP_TIMEOUTS). If it ever fails, the trace is still on
            # disk — rerun by hand, no chip needed:
            #   python -m picotron_tpu.tools.analyze_trace <out_dir>/profile
            results.append(run_step(
                "profile_analysis",
                [sys.executable, "-m", "picotron_tpu.tools.analyze_trace",
                 os.path.join(out_dir, "profile")],
                out_dir, PROFILE_ANALYSIS_TIMEOUT))
            flush_summary()
    print(json.dumps(results))
    return 0 if all(r["rc"] == 0 for r in results) else 1


if __name__ == "__main__":
    raise SystemExit(main())
