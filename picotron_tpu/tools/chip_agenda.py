"""One-shot on-chip work agenda for a flaky-tunnel site.

The TPU behind this rig's tunnel dies for hours at a time (rounds 3-4);
when it comes back there may be only a short window. This tool runs the
whole chip-blocked agenda unattended, in priority order, saving every
artifact under ``docs/chip_runs/<utc-stamp>/`` so one live window converts
into committed evidence:

1. kernel parity  — PICOTRON_TEST_TPU=1 pytest tests/test_tpu_kernels.py
2. bench          — python bench.py          (includes the bshd A/B)
3. bench_7b       — python bench_7b.py       (includes the bshd A/B)
4. profile        — a jax.profiler trace of the winning SmolLM config
                    (via train.py's profiler window on a short run)
5. cond_gating    — measure_cond_gating: the on-hardware cost of
                    lax.cond stage gating vs compute-both masking

Each step gets its own timeout and log file; a step failing (tunnel dying
mid-window) does not stop the later ones from being attempted. Run:

    python -m picotron_tpu.tools.chip_agenda [out_dir]
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))


def run_step(name: str, cmd: list[str], out_dir: str, timeout: float,
             env: dict | None = None) -> dict:
    """Run one agenda step, streaming combined stdout+stderr STRAIGHT to the
    log file — in-memory capture would lose the whole window's output when a
    timeout fires (CPython discards captured output on TimeoutExpired). The
    child gets its own session so a timeout kills the entire process GROUP:
    the benches spawn their own children, and an orphan would keep holding
    the TPU for every later step."""
    import signal

    log = os.path.join(out_dir, f"{name}.log")
    print(f"== {name}: {' '.join(cmd)} (timeout {timeout:.0f}s)", flush=True)
    with open(log, "w") as f:
        p = subprocess.Popen(cmd, cwd=REPO, env=env or dict(os.environ),
                             stdout=f, stderr=subprocess.STDOUT,
                             start_new_session=True)
        try:
            rc = p.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                p.kill()
            p.wait()
            rc = -9
            f.write(f"\n[timed out after {timeout:.0f}s; process group "
                    f"killed]\n")
    with open(log, "rb") as f:
        f.seek(max(0, os.path.getsize(log) - 400))
        # binary + replace: a byte-offset seek can land mid-UTF-8-char
        tail = f.read().decode("utf-8", errors="replace").replace("\n", " ")
    print(f"   -> rc={rc} log={log}\n   tail: {tail}", flush=True)
    return {"step": name, "rc": rc, "log": log}


def main():
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y%m%dT%H%M%SZ")
    out_dir = (sys.argv[1] if len(sys.argv) > 1
               else os.path.join(REPO, "docs", "chip_runs", stamp))
    os.makedirs(out_dir, exist_ok=True)
    results = []

    env = dict(os.environ, PICOTRON_TEST_TPU="1")
    results.append(run_step(
        "kernel_parity",
        [sys.executable, "-m", "pytest", "-q", "tests/test_tpu_kernels.py"],
        out_dir, timeout=1500, env=env))

    # the benches carry their own orchestrator (probe/retry/null-artifact)
    results.append(run_step(
        "bench", [sys.executable, "bench.py"], out_dir, timeout=5700))
    results.append(run_step(
        "bench_7b", [sys.executable, "bench_7b.py"], out_dir, timeout=5700))

    # profiler trace of the winning single-chip config: short real training
    # run with the profiler window over steps [4, 6)
    prof_dir = os.path.join(out_dir, "profile")
    from picotron_tpu.config import SMOLLM_1_7B  # plain dict, no jax import

    cfg = {
        "distributed": {"dp_size": 1, "pp_size": 1, "cp_size": 1,
                        "tp_size": 1},
        "model": dict(SMOLLM_1_7B),
        "training": {"seq_length": 2048, "micro_batch_size": 2,
                     "gradient_accumulation_steps": 1, "remat": "save_attn",
                     "learning_rate": 3e-4, "total_train_steps": 6,
                     "steps_per_call": 1},
        "dataset": {"name": "synthetic"},
        "logging": {"profile_start": 4, "profile_stop": 6,
                    "profile_dir": prof_dir},
    }
    cfg_path = os.path.join(out_dir, "profile_cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f, indent=2)
    results.append(run_step(
        "profile", [sys.executable, "train.py", "--config", cfg_path],
        out_dir, timeout=1800))

    # cond-gating cost on hardware (round-3 VERDICT weak #3): is the
    # masked stage's embed/loss really ~free under lax.cond?
    results.append(run_step(
        "cond_gating",
        [sys.executable, "-m", "picotron_tpu.tools.measure_cond_gating"],
        out_dir, timeout=1500))

    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump(results, f, indent=2)
    print(json.dumps(results))
    return 0 if all(r["rc"] == 0 for r in results) else 1


if __name__ == "__main__":
    raise SystemExit(main())
