"""Experiment sweep launcher with status triage.

Re-build of the reference's ``submit_slurm_jobs.py`` (:8-220): the same
Status lifecycle (INIT -> PENDING -> RUNNING -> {FAIL, OOM, TIMEOUT} ->
COMPLETED, :8-16), per-job ``status.txt`` persistence (:18-53), a Scheduler
that walks experiment directories for ``config.json`` files, submits each,
supports resubmission filtered by status class (``--only fail|oom|timeout|
pending|running``, :157-171), and tabulates status (:116-147).

Two backends replace the reference's sbatch-only path:

- ``local``: run ``python -m picotron_tpu.train`` as a subprocess on this
  host — the natural launcher for a single-controller TPU VM (one process
  drives all chips; there is no torchrun-style per-rank spawn to reproduce).
  Post-mortem log classification (the reference does this inside
  base_job.slurm:82-94 by grepping the log for OOM/timeout markers) happens
  here in Python with TPU-appropriate patterns (RESOURCE_EXHAUSTED etc.).
- ``slurm``: render ``template/base_job.slurm`` with jinja2 (reference
  :74-80) and sbatch it, with optional chained ``--dependency=afterany``
  arrays (:104-113,:175-199) for time-sliced TPU reservations.
"""

from __future__ import annotations

import argparse
import enum
import os
import re
import subprocess
import sys
import time
from typing import Optional


class Status(enum.Enum):
    # Lifecycle mirrors reference submit_slurm_jobs.py:8-16.
    INIT = "init"
    PENDING = "pending"
    RUNNING = "running"
    FAIL = "fail"
    OOM = "oom"
    TIMEOUT = "timeout"
    COMPLETED = "completed"


# Log patterns -> terminal status (TPU re-expression of the grep table in
# reference base_job.slurm:82-94). Only patterns that are definitive on a
# *failed* run belong here — benign allocator/retry lines ("Attempting to
# reserve", "Timed out waiting ... retrying") appear on healthy runs too.
OOM_PATTERNS = (
    "RESOURCE_EXHAUSTED",
    "Out of memory",
    "out of memory",
    "OOM when allocating",
)
TIMEOUT_PATTERNS = (
    "DEADLINE_EXCEEDED",
    "DUE TO TIME LIMIT",
    "collective operation timed out",
)


def classify_log(log_text: str, exit_code: Optional[int]) -> Status:
    # Exit code wins: warning substrings on a successful run are benign.
    if exit_code == 0:
        return Status.COMPLETED
    # exit_code None = the launcher killed the job at its wall-clock limit;
    # that is a timeout regardless of what the log accumulated.
    if exit_code is None:
        return Status.TIMEOUT
    for pat in OOM_PATTERNS:
        if pat in log_text:
            return Status.OOM
    for pat in TIMEOUT_PATTERNS:
        if pat in log_text:
            return Status.TIMEOUT
    return Status.FAIL


class Job:
    """One experiment directory: a config.json + status.txt + log file
    (reference Job, submit_slurm_jobs.py:18-53)."""

    def __init__(self, root: str):
        self.root = root
        self.config_path = os.path.join(root, "config.json")
        self.status_path = os.path.join(root, "status.txt")
        self.log_path = os.path.join(root, "log.out")
        self.name = os.path.basename(os.path.normpath(root))

    @property
    def status(self) -> Status:
        try:
            with open(self.status_path) as f:
                return Status(f.read().strip())
        except (FileNotFoundError, ValueError):
            return Status.INIT

    def set_status(self, status: Status) -> None:
        with open(self.status_path, "w") as f:
            f.write(status.value)

    def classify_from_log(self, exit_code: Optional[int]) -> Status:
        try:
            with open(self.log_path, errors="replace") as f:
                text = f.read()
        except FileNotFoundError:
            text = ""
        status = classify_log(text, exit_code)
        self.set_status(status)
        return status


class Scheduler:
    """Walk an input dir of experiment subdirectories and run/submit each
    (reference Scheduler, submit_slurm_jobs.py:55-199)."""

    def __init__(self, inp_dir: str, backend: str = "local",
                 template_path: Optional[str] = None, qos: str = "normal"):
        self.inp_dir = inp_dir
        self.backend = backend
        self.qos = qos
        self.template_path = template_path or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "templates", "base_job.slurm")
        self.jobs = self._discover()

    def _discover(self) -> list[Job]:
        jobs = []
        for root, _dirs, files in sorted(os.walk(self.inp_dir)):
            if "config.json" in files and "/profiler" not in root:
                jobs.append(Job(root))
        return jobs

    def select(self, only: Optional[str]) -> list[Job]:
        """Filter by status class for resubmission (reference :157-171)."""
        if not only:
            return [j for j in self.jobs if j.status is Status.INIT]
        wanted = {Status(s.strip()) for s in only.split(",")}
        return [j for j in self.jobs if j.status in wanted]

    # ---- local backend ----

    def run_local(self, job: Job, timeout_s: Optional[float] = None,
                  extra_args: Optional[list[str]] = None) -> Status:
        job.set_status(Status.RUNNING)
        cmd = [sys.executable, "-m", "picotron_tpu.train",
               "--config", job.config_path] + (extra_args or [])
        with open(job.log_path, "w") as log:
            try:
                proc = subprocess.run(
                    cmd, stdout=log, stderr=subprocess.STDOUT,
                    timeout=timeout_s, cwd=job.root,
                    env={**os.environ, "PYTHONPATH": os.pathsep.join(
                        filter(None, [os.getcwd(),
                                      os.environ.get("PYTHONPATH", "")]))})
                exit_code: Optional[int] = proc.returncode
            except subprocess.TimeoutExpired:
                log.write("\nsubmit_jobs: killed: DUE TO TIME LIMIT\n")
                exit_code = None
        return job.classify_from_log(exit_code)

    # ---- slurm backend ----

    def render_slurm(self, job: Job) -> str:
        """Render the job script (reference :74-80 renders base_job.slurm,
        computing nodes from world size; TPU hosts drive multiple chips so
        nodes = ceil(world / chips_per_host))."""
        import jinja2

        from picotron_tpu.config import Config

        cfg = Config.from_json(job.config_path)
        chips_per_host = int(os.environ.get("PICOTRON_CHIPS_PER_HOST", "4"))
        nodes = max(1, -(-cfg.world_size // chips_per_host))
        with open(self.template_path) as f:
            template = jinja2.Template(f.read())
        rendered = template.render(
            exp_name=job.name, nodes=nodes, world_size=cfg.world_size,
            config_path=os.path.abspath(job.config_path),
            root=os.path.abspath(job.root), qos=self.qos,
            # single source of truth for failure classification patterns
            oom_greps=" ".join(f"-e {p!r}" for p in OOM_PATTERNS),
            timeout_greps=" ".join(f"-e {p!r}" for p in TIMEOUT_PATTERNS))
        script_path = os.path.join(job.root, "job.slurm")
        with open(script_path, "w") as f:
            f.write(rendered)
        return script_path

    def submit_slurm(self, job: Job, dependency: Optional[str] = None) -> str:
        script = self.render_slurm(job)
        cmd = ["sbatch"]
        if dependency:
            cmd.append(f"--dependency=afterany:{dependency}")
        cmd.append(script)
        # PENDING before sbatch: the job script writes "running" at startup,
        # and writing after submission could overwrite that on a fast start.
        job.set_status(Status.PENDING)
        try:
            out = subprocess.run(cmd, capture_output=True, text=True, check=True)
        except subprocess.SubprocessError:
            job.set_status(Status.INIT)
            raise
        job_id = out.stdout.strip().split()[-1]
        return job_id

    # ---- top-level ops ----

    def submit(self, only: Optional[str] = None, chain: bool = False,
               timeout_s: Optional[float] = None) -> None:
        selected = self.select(only)
        if not selected:
            print("no jobs to submit")
            return
        last_id: Optional[str] = None
        for job in selected:
            if self.backend == "local":
                t0 = time.perf_counter()
                status = self.run_local(job, timeout_s=timeout_s)
                print(f"{job.name}: {status.value} "
                      f"({time.perf_counter() - t0:.1f}s) -> {job.log_path}")
            else:
                dep = last_id if chain else None
                last_id = self.submit_slurm(job, dependency=dep)
                print(f"{job.name}: submitted as {last_id}"
                      + (f" (after {dep})" if dep else ""))

    def check_status(self) -> dict[str, int]:
        """Tabulate job statuses (reference check_status :116-147)."""
        counts: dict[str, int] = {}
        width = max((len(j.name) for j in self.jobs), default=4)
        for job in self.jobs:
            s = job.status.value
            counts[s] = counts.get(s, 0) + 1
            print(f"{job.name:<{width}}  {s}")
        print("-" * (width + 12))
        for s, n in sorted(counts.items()):
            print(f"{s:<{width}}  {n}")
        print(f"{'total':<{width}}  {len(self.jobs)}")
        return counts


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="Submit/triage experiment sweeps")
    p.add_argument("--inp_dir", required=True,
                   help="directory containing experiment subdirs with config.json")
    p.add_argument("--backend", choices=("local", "slurm"), default="local")
    p.add_argument("--only", default=None,
                   help="resubmit filter: comma list of fail,oom,timeout,"
                        "pending,running,init,completed")
    p.add_argument("--chain", action="store_true",
                   help="slurm: chain jobs with --dependency=afterany")
    p.add_argument("--timeout", type=float, default=None,
                   help="local: per-job wall-clock limit in seconds")
    p.add_argument("--check_status", action="store_true")
    p.add_argument("--template", default=None, help="slurm template path")
    args = p.parse_args(argv)

    sched = Scheduler(args.inp_dir, backend=args.backend,
                      template_path=args.template)
    if args.check_status:
        sched.check_status()
    else:
        sched.submit(only=args.only, chain=args.chain, timeout_s=args.timeout)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
