"""Dump, validate, and query Chrome-trace JSON from the span tracer.

    # validate a dumped trace (train's obs.trace_path, or a saved /tracez)
    python -m picotron_tpu.tools.trace_dump trace.json

    # fetch from a live server and save
    python -m picotron_tpu.tools.trace_dump --url http://127.0.0.1:8000/tracez \
        --out trace.json

    # additionally require at least one COMPLETE request chain
    # (queue/prefill -> >=1 dispatch -> delivery, all parented) — the
    # `make obs-smoke` gate
    python -m picotron_tpu.tools.trace_dump trace.json --require-request-chain

The file format is the Chrome trace-event "traceEvents" array
(chrome://tracing, https://ui.perfetto.dev both load it directly);
``picotron_tpu.obs.tracing.SpanTracer.chrome_trace`` emits it with
``args.id``/``args.parent`` carrying the span links. ``validate`` checks
structure (every event named, timestamped, complete events carry ``dur``);
``dangling_parents`` reports unresolved parent links as WARNINGS only — a
live ``/tracez`` snapshot legitimately has them (an in-flight request's
root span isn't in the ring until it ends, and ring eviction drops old
roots); ``request_chains`` reassembles each request's tree. Exit 1 on any
validation error (or a missing required chain), so the smoke targets can
gate on it.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

# span names the batcher/front end record under a request root
_CHAIN_DISPATCH = ("decode", "verify")


def load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def fetch(url: str) -> dict:
    """GET a /tracez endpoint (stdlib only)."""
    from urllib.request import urlopen

    with urlopen(url, timeout=60) as resp:
        return json.loads(resp.read())


def validate(trace: dict) -> list:
    """Structural errors in a Chrome-trace dict ([] = valid)."""
    errors = []
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["top-level 'traceEvents' must be a list"]
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            errors.append(f"event {i}: not an object")
            continue
        for field in ("name", "ph", "ts", "pid", "tid"):
            if field not in ev:
                errors.append(f"event {i}: missing {field!r}")
        if not isinstance(ev.get("ts", 0), (int, float)):
            errors.append(f"event {i}: non-numeric ts")
        if ev.get("ph") == "X":
            if not isinstance(ev.get("dur"), (int, float)) or ev["dur"] < 0:
                errors.append(f"event {i}: complete event without a "
                              f"non-negative dur")
    return errors


def dangling_parents(trace: dict) -> list:
    """Parent references that resolve to no event id in the trace.
    Reported as WARNINGS, not errors: a live ``/tracez`` snapshot
    legitimately contains them — a request's root span only lands in the
    ring when it ENDS, so an in-flight request's queue_wait/prefill/
    dispatch children reference a root that isn't exported yet, and ring
    eviction on a busy server drops old roots before their children."""
    events = [e for e in trace.get("traceEvents", ())
              if isinstance(e, dict)]
    ids = {(e.get("args") or {}).get("id") for e in events}
    out = []
    for i, ev in enumerate(events):
        parent = (ev.get("args") or {}).get("parent")
        if parent is not None and parent not in ids:
            out.append(
                f"event {i} ({ev.get('name')!r}): parent {parent} does "
                f"not resolve to any event id in the trace (in-flight "
                f"request or evicted root?)")
    return out


def request_chains(trace: dict) -> dict:
    """Reassemble per-request span trees: {uid: {"queue_wait", "prefill",
    "dispatches", "delivery", "complete"}}. A chain is COMPLETE when the
    request saw a prefill, at least one decode/verify dispatch child, and
    a delivery — all parented (directly) to the request root."""
    events = [e for e in trace.get("traceEvents", ())
              if isinstance(e, dict)]
    roots = {}  # span id -> uid
    for ev in events:
        args = ev.get("args") or {}
        if ev.get("name") == "request" and "uid" in args:
            roots[args.get("id")] = args["uid"]
    chains = {uid: {"queue_wait": False, "prefill": False,
                    "dispatches": 0, "delivery": False}
              for uid in roots.values()}
    for ev in events:
        args = ev.get("args") or {}
        uid = roots.get(args.get("parent"))
        if uid is None:
            continue
        c = chains[uid]
        name = ev.get("name")
        if name == "queue_wait":
            c["queue_wait"] = True
        elif name == "prefill":
            c["prefill"] = True
        elif name in _CHAIN_DISPATCH:
            c["dispatches"] += 1
        elif name == "delivery":
            c["delivery"] = True
    for c in chains.values():
        c["complete"] = bool(c["prefill"] and c["dispatches"]
                             and c["delivery"])
    return chains


def overlap_chain(trace: dict) -> dict:
    """Validate the overlapped-scheduling span chain (inference.overlap;
    docs/INFERENCE.md "Overlapped scheduling"): every ``overlap`` event
    must parent to a ``dispatch/*`` span and sit inside its parent's
    window — the witness that round N's sync/deliver stage ran while
    round N+1 executed on device. Returns {"overlaps", "linked",
    "errors"}; the obs-smoke overlap leg requires >= 1 linked and no
    errors (``--require-overlap-chain``)."""
    events = [e for e in trace.get("traceEvents", ())
              if isinstance(e, dict)]
    by_id = {}
    for e in events:
        sid = (e.get("args") or {}).get("id")
        if sid is not None:
            by_id[sid] = e
    out = {"overlaps": 0, "linked": 0, "errors": []}
    for i, ev in enumerate(events):
        if ev.get("name") != "overlap":
            continue
        out["overlaps"] += 1
        parent = by_id.get((ev.get("args") or {}).get("parent"))
        if parent is None:
            out["errors"].append(
                f"event {i}: overlap span has no resolvable parent")
            continue
        if not str(parent.get("name", "")).startswith("dispatch/"):
            out["errors"].append(
                f"event {i}: overlap parent is {parent.get('name')!r}, "
                f"expected a dispatch/* span")
            continue
        p0 = parent.get("ts", 0)
        p1 = p0 + parent.get("dur", 0)
        t0 = ev.get("ts", 0)
        t1 = t0 + ev.get("dur", 0)
        if t0 < p0 - 2 or t1 > p1 + 2:  # 2us slack: ts quantization
            out["errors"].append(
                f"event {i}: overlap window [{t0}, {t1}] escapes its "
                f"dispatch parent's [{p0}, {p1}]")
            continue
        out["linked"] += 1
    return out


def lane_chain(trace: dict) -> dict:
    """Validate the mixed-dispatch prefill-lane span chain
    (inference.mixed_dispatch; docs/INFERENCE.md "Mixed prefill–decode
    dispatch"): every ``lane`` event (one confirmed lane chunk) must
    parent to a ``request`` root, and per request the chunks must tile
    the prompt — chunk numbers 1..n with each chunk starting where the
    previous ended, the last one landing at the lane prefill span's
    ``prompt_tokens``. Returns {"lanes", "linked", "errors"}; the
    mixed obs gate requires >= 1 linked and no errors
    (``--require-lane-chain``)."""
    events = [e for e in trace.get("traceEvents", ())
              if isinstance(e, dict)]
    by_id = {}
    for e in events:
        sid = (e.get("args") or {}).get("id")
        if sid is not None:
            by_id[sid] = e
    # prompt length per request root, from the lane=True prefill span
    prompt_of = {}
    for e in events:
        args = e.get("args") or {}
        if (e.get("name") == "prefill" and args.get("lane")
                and "prompt_tokens" in args):
            prompt_of[args.get("parent")] = args["prompt_tokens"]
    out = {"lanes": 0, "linked": 0, "errors": []}
    per_root: dict = {}
    for i, ev in enumerate(events):
        if ev.get("name") != "lane":
            continue
        out["lanes"] += 1
        args = ev.get("args") or {}
        parent = by_id.get(args.get("parent"))
        if parent is None:
            out["errors"].append(
                f"event {i}: lane span has no resolvable parent")
            continue
        if parent.get("name") != "request":
            out["errors"].append(
                f"event {i}: lane parent is {parent.get('name')!r}, "
                f"expected a request root")
            continue
        if not all(k in args for k in ("chunk", "start", "end")):
            out["errors"].append(
                f"event {i}: lane span missing chunk/start/end args")
            continue
        if args["end"] <= args["start"]:
            out["errors"].append(
                f"event {i}: empty lane chunk window "
                f"[{args['start']}, {args['end']}]")
            continue
        per_root.setdefault(args.get("parent"), []).append((i, args))
        out["linked"] += 1
    for root, chunks in per_root.items():
        chunks.sort(key=lambda c: c[1]["chunk"])
        if [c[1]["chunk"] for c in chunks] != list(
                range(1, len(chunks) + 1)):
            out["errors"].append(
                f"request {root}: lane chunk numbers "
                f"{[c[1]['chunk'] for c in chunks]} are not 1..n")
            continue
        for (i, a), (_, b) in zip(chunks, chunks[1:]):
            if b["start"] != a["end"]:
                out["errors"].append(
                    f"request {root}: lane chunk {b['chunk']} starts at "
                    f"{b['start']}, previous ended at {a['end']}")
        want = prompt_of.get(root)
        if want is not None and chunks[-1][1]["end"] != want:
            out["errors"].append(
                f"request {root}: lane chunks end at "
                f"{chunks[-1][1]['end']}, prompt has {want} tokens")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate/query Chrome-trace JSON from the span "
                    "tracer (obs.tracing; docs/OBSERVABILITY.md)")
    ap.add_argument("path", nargs="?", help="trace JSON file")
    ap.add_argument("--url", help="fetch from a live /tracez endpoint "
                                  "instead of a file")
    ap.add_argument("--out", help="write the (fetched or loaded) trace "
                                  "back out — save a live /tracez")
    ap.add_argument("--require-request-chain", nargs="?", const="any",
                    default=None, metavar="UID",
                    help="fail unless a COMPLETE request chain exists "
                         "(prefill -> >=1 dispatch -> delivery); pass a "
                         "UID to require that specific request's")
    ap.add_argument("--require-overlap-chain", action="store_true",
                    help="fail unless >= 1 'overlap' span links to a "
                         "dispatch/* parent within its window (the "
                         "inference.overlap pipeline's obs-smoke gate)")
    ap.add_argument("--require-lane-chain", action="store_true",
                    help="fail unless >= 1 'lane' span links to a request "
                         "root with chunks tiling the prompt (the "
                         "inference.mixed_dispatch obs gate)")
    args = ap.parse_args(argv)
    if not args.path and not args.url:
        ap.error("pass a trace file path or --url")

    trace = fetch(args.url) if args.url else load(args.path)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(trace, f)
    errors = validate(trace)
    for e in errors:
        print(f"INVALID: {e}", file=sys.stderr)
    for w in dangling_parents(trace):
        print(f"WARN: {w}", file=sys.stderr)
    n = len(trace.get("traceEvents", ()))
    chains = request_chains(trace)
    complete = sorted(u for u, c in chains.items() if c["complete"])
    print(f"{n} events, {len(chains)} request chains "
          f"({len(complete)} complete)")
    for uid, c in sorted(chains.items()):
        print(f"  {uid}: queue_wait={c['queue_wait']} "
              f"prefill={c['prefill']} dispatches={c['dispatches']} "
              f"delivery={c['delivery']} "
              f"{'COMPLETE' if c['complete'] else 'partial'}")
    if errors:
        return 1
    want = args.require_request_chain
    if want is not None:
        ok = bool(complete) if want == "any" \
            else chains.get(want, {}).get("complete", False)
        if not ok:
            print(f"FAILED: no complete request chain"
                  f"{'' if want == 'any' else f' for uid {want!r}'}",
                  file=sys.stderr)
            return 1
    if args.require_overlap_chain:
        ov = overlap_chain(trace)
        print(f"overlap chain: {ov['overlaps']} spans, "
              f"{ov['linked']} linked")
        for e in ov["errors"]:
            print(f"FAILED: {e}", file=sys.stderr)
        if ov["errors"] or not ov["linked"]:
            if not ov["overlaps"]:
                print("FAILED: no overlap spans in trace "
                      "(was the server run with --overlap?)",
                      file=sys.stderr)
            return 1
    if args.require_lane_chain:
        la = lane_chain(trace)
        print(f"lane chain: {la['lanes']} spans, {la['linked']} linked")
        for e in la["errors"]:
            print(f"FAILED: {e}", file=sys.stderr)
        if la["errors"] or not la["linked"]:
            if not la["lanes"]:
                print("FAILED: no lane spans in trace "
                      "(was the server run with mixed_dispatch?)",
                      file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
