"""Serving front end: a resilient stdlib-only HTTP server over the batcher.

    python -m picotron_tpu.tools.serve --config exp.json \
        --load-path checkpoints --port 8000

The missing layer between ``ContinuousBatcher`` (a host-side scheduling
loop) and "serves heavy traffic": admission control, load shedding, health
surfaces, graceful drain, and a stall watchdog — the things that decide
whether one bad request or one sick dispatch takes down every other
request in flight (docs/SERVING.md). Stdlib only (``http.server``,
``threading``, ``json``): the front end must not be the component with the
exotic dependency.

API (all bodies JSON):

- ``POST /generate`` — ``{"prompt": [ids], "max_new_tokens", "temperature",
  "top_k", "top_p", "eos_id", "timeout_s", "stream", "uid",
  "request_id"}`` (all but ``prompt`` optional). Non-streaming: one JSON
  document with ``tokens`` and ``finish_reason``
  (``eos|length|timeout|shed|error``); HTTP status 200 for served
  outcomes, 503 + ``Retry-After`` when shed, 500 on ``error``.
  ``"stream": true``: NDJSON events ``{"event":"token",...}`` per
  generated token, then one ``{"event":"done", ...}`` carrying the full
  result. A client-supplied ``request_id`` (the router's correlation
  key) is echoed on every token row, the done row, and the non-streaming
  document, falling back to the server ``uid``.
- ``GET /healthz`` — liveness: 200 while the dispatch loop is making
  progress, 503 once the watchdog sees a stall (supervisors restart on
  this, exactly like ``tools/supervise.py``'s heartbeat rule).
- ``GET /readyz`` — readiness: 200 only when accepting work; the 503
  body carries ``"state": "draining" | "stalled" | "dead"`` so a poller
  (the multi-replica router, tools/router.py) can tell a GRACEFUL drain
  (stop placing, no breaker action) from a sick replica.
- ``GET /statz`` — the batcher's ``stats()`` (terminal-state counters,
  queue-wait / time-to-first-token percentiles) plus the server's
  admission-rejection counters and drain/stall state.
- ``GET /metrics`` — Prometheus text exposition of the engine/batcher/
  front-end registry plus the process-wide resilience counters
  (picotron_tpu/obs, docs/OBSERVABILITY.md). The counters are the SAME
  instruments ``/statz`` reads, so the two surfaces cannot disagree.
  Speculative engines additionally export ``picotron_spec_accept_rate``
  and ``picotron_spec_len`` gauges, refreshed on render exactly like the
  queue-depth gauges (batcher.refresh_gauges) — the fabric's router can
  see each replica's live speculation health off the scrape, and
  ``/statz`` mirrors them as ``accept_rate`` / ``spec_len_effective``
  (plus the controller's decision counts when
  ``inference.spec_controller`` is on).
- ``GET /tracez`` — the process span ring as Chrome-trace JSON: each
  request's queue-wait -> prefill -> per-dispatch -> delivery chain,
  parented. Validate/query with ``tools/trace_dump.py``.
- ``POST /profilez`` — start one timed ``jax.profiler`` capture
  (``{"seconds", "dir"}`` optional; defaults from ``obs.profile_dir`` /
  ``obs.profile_seconds``); 409 while one is running. The CLI wires
  SIGUSR2 to the same capture.
- ``GET /tenants`` / ``POST /tenants`` / ``DELETE /tenants/<name>`` —
  the multi-tenant admin plane (inference/tenancy.py, docs/SERVING.md
  "Multi-tenant serving"): list registered tenants + adapter-pack
  occupancy, hot-add one tenant (its LoRA weights land in a free pack
  slot — no recompile), hot-remove (the slot zeroes back to null).
  ``/generate``'s optional ``"tenant"`` field selects the serving
  identity; unknown names are a 400, never a silent base fallback.
  Per-tenant quotas 429 with ``"budget": "tenant_tokens" |
  "tenant_pages"`` in the body; global budget 429s carry ``"tokens"`` /
  ``"pages"`` — a client backoff can tell its own quota from fleet
  pressure. Only present when a registry is configured
  (``inference.tenancy`` or ``--tenant-manifest``).
- ``POST /kv/export`` / ``GET|POST /kv/pages`` / ``POST /kv/import`` —
  the prefill/decode disaggregation plane (``inference.role``,
  inference/page_transport.py, docs/SERVING.md "Disaggregated
  prefill/decode"): a prefill worker runs admission + prefill and hands
  the finished KV pool pages off as a byte-exact payload (+ the first
  sampled token); ``/kv/pages`` looks up the longest radix-cached
  prefix; ``/kv/import`` lands a payload in the local radix cache; and
  ``/generate``'s ``"kv"`` field seats a full-prompt payload with zero
  prefill dispatches. Paged layout only.

Admission control (checked atomically at POST time):

- **bounded wait queue** — more than ``--max-queue`` waiting requests is a
  503 (the queue is where latency hides; past the bound, waiting is worse
  for the client than retrying another replica);
- **token budget** — the worst-case token commitment (prompt +
  window-capped ``max_new_tokens``) of every live request is capped by
  ``--token-budget`` (default: ``slots * max_seq_len``, the cache's real
  capacity); past it new work is a 429. Both carry ``Retry-After``.
- **page budget** (``inference.kv_layout: "paged"`` only) — requests are
  additionally priced in KV POOL PAGES (``ceil(commitment / page_len)``,
  not a contiguous worst-case strip) against the pool size; past it, 429
  with a ``Retry-After`` scaled to the page deficit. ``/statz`` then also
  carries the pool occupancy and prefix-cache hit stats
  (``kv_pages_*``, ``prefix_hit_rate``, ``cow_copies`` — from
  ``batcher.stats()``; docs/SERVING.md).

Graceful drain (the ``resilience.preemption.PreemptionGuard`` pattern):
SIGTERM/SIGINT flips readiness, sheds the queued-but-unstarted requests
(``finish_reason "shed"``), lets in-flight slots run to completion, then
exits 0. A second signal aborts immediately (the operator means it).

``--smoke`` is the ``make serve-smoke`` target: tiny CPU model, ephemeral
port, one scripted client (health checks, a POST, a streamed POST, SIGTERM
drain with accounting) — exits nonzero on any malfunction.
"""

from __future__ import annotations

import argparse
import json
import queue
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class AdmissionError(Exception):
    """A request rejected at the door (shed before submission).
    ``extra`` rides into the JSON error body — budget rejections use it
    to name WHICH budget tripped (``"budget": "tokens" | "pages" |
    "tenant_tokens" | "tenant_pages"``) so a router or client backoff
    can tell global pressure from its own quota."""

    def __init__(self, status: int, reason: str, retry_after: int = 1,
                 **extra):
        super().__init__(reason)
        self.status = status
        self.reason = reason
        self.retry_after = retry_after
        self.extra = extra


class _Waiter:
    """Per-request rendezvous between the dispatch loop and its HTTP
    handler thread: token events stream through the queue, the final
    GenerationResult ends it."""

    def __init__(self):
        self.events: queue.Queue = queue.Queue()

    def put_token(self, tok: int) -> None:
        self.events.put(("token", tok))

    def put_done(self, result) -> None:
        self.events.put(("done", result))


class FrontEnd:
    """Owns the batcher, the dispatch loop thread, and the watchdog.

    All batcher access is serialized by ``_mu`` (the batcher is not
    thread-safe); HTTP handler threads only touch it for the short
    admission check + submit, the dispatch loop for step()/result
    draining. ``guard`` is a ``PreemptionGuard`` (not installed here —
    the CLI installs it on the main thread; tests drive ``begin_drain``
    directly)."""

    def __init__(self, engine, params, *, seed: int = 0,
                 max_queue: int = 64, token_budget: Optional[int] = None,
                 default_timeout_s: Optional[float] = None,
                 stall_timeout_s: float = 60.0,
                 watchdog_poll_s: float = 0.25,
                 tenants=None, log=print):
        from picotron_tpu.inference import ContinuousBatcher
        from picotron_tpu.obs import ProfileCapture
        from picotron_tpu.resilience.preemption import PreemptionGuard

        self.engine = engine
        self.obs = engine.obs  # one registry across engine/batcher/front end
        ocfg = engine.cfg.obs
        self.profiler = ProfileCapture(
            ocfg.profile_dir, ocfg.profile_seconds,
            log=lambda m: self._event("profiler", note=m))
        self.max_queue = int(max_queue)
        self.token_budget = int(token_budget if token_budget is not None
                                else engine.slots * engine.max_seq_len)
        self.default_timeout_s = default_timeout_s
        self.stall_timeout_s = float(stall_timeout_s)
        self.watchdog_poll_s = float(watchdog_poll_s)
        self.guard = PreemptionGuard()
        self._log = log
        self._mu = threading.Lock()
        self._uid_mu = threading.Lock()  # uid counter only: never wait on
        # _mu before the bounded acquire below, or a wedged dispatch parks
        # every uid-less submission forever instead of shedding it after 10s
        self._wake = threading.Event()
        self._waiters: dict = {}
        self._batcher = ContinuousBatcher(engine, params, seed=seed,
                                          on_token=self._on_token)
        # model-memory gauge: the router's /metrics scrape (tools/
        # router.py) can see per-replica resident weight bytes — int8
        # values + per-channel scales included, so a quantized replica
        # reports ~half its bf16 twin (docs/INFERENCE.md "Quantized
        # weights"); set once: weights never change size mid-serve
        from picotron_tpu.models import llama

        self.weight_bytes = llama.param_bytes(params)
        self.obs.registry.gauge(
            "picotron_weight_bytes",
            "model weight bytes resident on this replica").set(
                float(self.weight_bytes))
        # disaggregated serving role (inference.role, docs/SERVING.md
        # "Disaggregated prefill/decode"): "both" serves exactly as
        # before; "prefill" runs admission + prefill only and hands KV
        # pages off via POST /kv/export (its /generate sheds); "decode"
        # seats imported pages and runs the decode/spec loop. The role
        # gauge lets a router scrape tell a prefill worker from an idle
        # decode target (it would otherwise score as one).
        self.role = engine.cfg.inference.role
        self.obs.registry.gauge(
            "picotron_serve_role",
            "serving role of this replica", role=self.role).set(1.0)
        # multi-tenant registry (inference/tenancy.py, docs/SERVING.md
        # "Multi-tenant serving"): None = single-tenant serving, every
        # request anonymous base traffic, exactly as before. When set,
        # requests may name a tenant ("tenant" field) — UNKNOWN names
        # are a 400, never a silent base fallback (a typo'd tenant must
        # not dodge its quota) — and admission becomes priority-aware:
        # under budget pressure queued lower classes shed before a
        # higher-class arrival 429s.
        self.tenants = tenants
        self.draining = False
        # leaf lock for the drain flag: POST /drain handler threads, the
        # dispatch loop's guard check, and drain_and_join all race on it;
        # drain_begins counts WINNING initiations (the regression surface
        # for a double-run of the drain machinery — it must stay 1 when
        # SIGTERM lands during an HTTP-initiated drain)
        self._drain_mu = threading.Lock()
        self.drain_begins = 0
        self.stopped = threading.Event()  # dispatch loop has exited
        self.dead = False  # loop died on an exception (vs clean drain)
        self.stalled = False
        self.stalls = 0  # stall episodes the watchdog flagged
        # a CounterDict: plain-dict reads (tests, /statz) with every
        # write mirrored into picotron_rejections_total{reason} — the
        # /metrics rendering of the same numbers
        self.rejections = self.obs.registry.counter_dict(
            "picotron_rejections_total",
            ("queue_full", "token_budget", "page_budget", "tenant_quota",
             "draining", "stalled", "dead", "role"),
            help="admission sheds by reason", label="reason")
        # leaf lock for the rejection counters: the "stalled" increment
        # happens precisely when _mu could NOT be acquired, so the
        # counters need their own guard (picolint PICO-C003 — concurrent
        # timed-out handlers would lose increments). Always taken last
        # (inside _mu where both are held), never while waiting on _mu.
        self._rej_mu = threading.Lock()
        self._uid_seq = 0
        self._start_t = time.monotonic()
        self._progress_t = time.monotonic()
        self._req_t: dict = {}  # uid -> wall submit time (request log)
        self._threads: list = []
        self._on_drained = None  # callback once drain completes (CLI: shutdown)

    # ---- lifecycle --------------------------------------------------------

    def start(self) -> None:
        for name, fn in (("serve-dispatch", self._loop),
                         ("serve-watchdog", self._watchdog)):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def begin_drain(self) -> bool:
        """Stop admitting, shed the unstarted queue, finish in-flight
        slots, then stop the dispatch loop (readiness goes 503 at once).
        Idempotent AND race-free: POST /drain (a fleet controller's
        scale-down) and the PreemptionGuard's SIGTERM path can land
        concurrently — exactly one caller wins the flag under the leaf
        lock, so the drain machinery (the event, the shed, the eventual
        exit) runs once no matter how many initiators fire. Returns
        whether THIS caller won the initiation."""
        with self._drain_mu:
            first = not self.draining
            self.draining = True
            if first:
                self.drain_begins += 1
        if first:
            self._event("drain_begin")
        self._wake.set()
        return first

    def drain(self) -> dict:
        """POST /drain: the fleet controller's scale-down surface —
        start a graceful drain over HTTP (readyz flips to "draining" at
        once, in-flight finishes, the process exits 0 exactly as a
        SIGTERM drain would). 409 when there is nothing to start: the
        loop already exited (dead OR drained — no second drain can run)
        or a drain already owns the flag (the first initiator holds the
        contract; a controller seeing 409 treats the drain as already
        under way)."""
        if self.dead or self.stopped.is_set():
            raise AdmissionError(
                409, "dispatch loop already exited", retry_after=0,
                state="dead" if self.dead else "stopped")
        if not self.begin_drain():
            raise AdmissionError(409, "already draining", retry_after=0,
                                 state="draining")
        return {"ok": True, "state": "draining"}

    def join(self, timeout: Optional[float] = None) -> None:
        self.stopped.wait(timeout)

    # ---- admission --------------------------------------------------------

    def submit(self, spec: dict, _internal: bool = False) -> tuple:
        """Admission-check + submit one request. Returns (uid, waiter) or
        raises AdmissionError (the caller turns it into 429/503).
        ``_internal`` marks the /kv/export path's own 1-token submission,
        which a role=prefill replica must accept even though its public
        /generate sheds."""
        from picotron_tpu.inference import Request

        if self.role == "prefill" and not _internal:
            # a prefill worker's dispatch rounds belong to prefills; a
            # decode stream here would be the interference the role split
            # exists to remove. 503 (not 400): the client did nothing
            # wrong, the router just mis-placed.
            self._reject("role")
            raise AdmissionError(
                503, "replica serves prefill only (inference.role: "
                     "prefill); use POST /kv/export", retry_after=5)
        prompt = spec.get("prompt")
        if not isinstance(prompt, list) or not prompt \
                or not all(isinstance(t, int) for t in prompt):
            raise AdmissionError(400, "prompt must be a non-empty list of "
                                      "token ids", retry_after=0)
        kv = spec.get("kv")
        if kv is not None:
            # the disaggregated handoff payload: validate its spec BEFORE
            # taking a slot. A payload this replica can never consume —
            # contiguous layout, mismatched page geometry/dtype — is
            # DROPPED, not rejected: the request is still perfectly
            # servable by self-prefilling, and a mixed or mid-upgrade
            # fleet must degrade to colocated behavior, never to client
            # 400s (the capability gap is logged + counted).
            from picotron_tpu.inference import page_transport

            if not isinstance(kv, dict):
                raise AdmissionError(400, "kv must be a transport payload "
                                          "object", retry_after=0)
            why = None
            if self.engine.paged is None:
                why = "contiguous kv_layout (no page pool)"
            else:
                try:
                    page_transport.check_spec(self.engine, kv)
                except page_transport.TransportError as e:
                    why = str(e)
            if why is not None:
                self.obs.registry.counter(
                    "picotron_handoff_dropped_total",
                    "kv payloads dropped as locally unusable").inc()
                self._event("kv_dropped", why=why[:200])
                kv = None
        tenant, slot = self._resolve_tenant(spec.get("tenant"))
        timeout_s = spec.get("timeout_s", self.default_timeout_s)
        try:
            req = Request(
                uid=str(spec.get("uid") or self._next_uid()),
                prompt=list(prompt),
                max_new_tokens=int(spec.get("max_new_tokens", 32)),
                temperature=float(spec.get("temperature", 0.0)),
                top_k=int(spec.get("top_k", 0)),
                top_p=float(spec.get("top_p", 1.0)),
                eos_id=spec.get("eos_id"),
                timeout_s=None if timeout_s is None else float(timeout_s),
                kv_import=kv,
                tenant=self._tenant_salt(tenant),
                priority=tenant.priority if tenant is not None else 1,
                adapter_slot=slot,
                ttft_slo_ms=(tenant.ttft_slo_ms if tenant is not None
                             else None),
                tpot_slo_ms=(tenant.tpot_slo_ms if tenant is not None
                             else None))
        except (TypeError, ValueError) as e:
            raise AdmissionError(400, f"bad request field: {e}",
                                 retry_after=0)
        if req.max_new_tokens < 1:
            # a zero-budget request would hold a slot forever (no token ever
            # completes it); a negative one corrupts the token-budget math
            raise AdmissionError(400, "max_new_tokens must be >= 1",
                                 retry_after=0)
        # window-capped commitment (the same pricing token_load() uses): a
        # budget beyond max_seq_len can never be generated, so counting it
        # raw would 429 a servable request forever. Reads only the engine's
        # window — safe before taking _mu.
        cost = self._batcher.commitment(req)
        # bounded wait for the batcher lock: during a wedged dispatch (the
        # stall the watchdog flags) admission SHEDS instead of parking
        # handler threads on the lock forever
        if not self._mu.acquire(timeout=10.0):
            self._reject("stalled")
            raise AdmissionError(
                503, "dispatch stalled (admission unavailable)",
                retry_after=10)
        try:
            if self.stopped.is_set():
                # the dispatch loop is gone (drain done, or it died on an
                # unexpected exception): nothing will ever serve this
                # request — shed it instead of stranding the handler on a
                # waiter no loop will complete
                self._reject("dead")
                raise AdmissionError(
                    503, "dispatch loop exited (restart required)",
                    retry_after=30)
            if self.draining:
                self._reject("draining")
                raise AdmissionError(
                    503, "draining (restart in progress)", retry_after=5)
            if self._batcher.queue_depth >= self.max_queue:
                # the wait queue is bounded: past it, queueing only grows
                # the client's latency — shed instead
                self._reject("queue_full")
                raise AdmissionError(
                    503, f"wait queue full ({self.max_queue})",
                    retry_after=max(1, self.max_queue // 8))
            # per-tenant quotas FIRST: a tenant over its own ceiling is
            # ITS problem — it never triggers lower-class shedding, and
            # the 429 body names the tenant budget so its backoff does
            # not read as global pressure (Retry-After scales to the
            # tenant's own deficit, the PR 7 page-deficit pattern).
            if tenant is not None and tenant.max_tokens is not None:
                tload = self._batcher.tenant_token_load(req.tenant)
                if tload + cost > tenant.max_tokens:
                    deficit = tload + cost - tenant.max_tokens
                    self._reject("tenant_quota")
                    raise AdmissionError(
                        429,
                        f"tenant {tenant.name!r} token quota exhausted "
                        f"({tenant.max_tokens})",
                        retry_after=min(30, 1 + deficit
                                        // max(1, tenant.max_tokens // 4)),
                        budget="tenant_tokens", tenant=tenant.name)
            if (tenant is not None and tenant.max_pages is not None
                    and self.engine.paged is not None):
                pneed = self._batcher.page_commitment(req)
                pload = self._batcher.tenant_page_load(req.tenant)
                if pload + pneed > tenant.max_pages:
                    deficit = pload + pneed - tenant.max_pages
                    self._reject("tenant_quota")
                    raise AdmissionError(
                        429,
                        f"tenant {tenant.name!r} page quota exhausted "
                        f"({tenant.max_pages})",
                        retry_after=min(30, 1 + deficit
                                        // max(1, tenant.max_pages // 4)),
                        budget="tenant_pages", tenant=tenant.name)
            if self._batcher.token_load() + cost > self.token_budget:
                # before 429ing, a positive-class arrival sheds QUEUED
                # strictly-lower-class work (lowest class first) until
                # its commitment fits — priority is meaningless if a
                # full budget holds classes equal
                deficit = (self._batcher.token_load() + cost
                           - self.token_budget)
                if req.priority > 0:
                    self._batcher.shed_lower_priority(req.priority,
                                                      tokens=deficit)
                if self._batcher.token_load() + cost > self.token_budget:
                    self._reject("token_budget")
                    raise AdmissionError(
                        429, f"token budget exhausted ({self.token_budget})",
                        retry_after=1, budget="tokens")
            if self.engine.paged is not None:
                # paged layout: price in POOL PAGES, not contiguous
                # strips — ceil(commitment / page_len) against the pool,
                # with Retry-After scaled to the page deficit (deeper
                # overload -> back off longer; capped at 30s)
                need = self._batcher.page_commitment(req)
                usable = self.engine.paged.usable_pages
                load = self._batcher.page_load()
                if load + need > usable and req.priority > 0:
                    self._batcher.shed_lower_priority(
                        req.priority, pages=load + need - usable)
                    load = self._batcher.page_load()
                if load + need > usable:
                    deficit = load + need - usable
                    self._reject("page_budget")
                    raise AdmissionError(
                        429,
                        f"kv page pool exhausted (need {need} of "
                        f"{usable - min(load, usable)} pages free)",
                        retry_after=min(30, 1 + deficit
                                        // max(1, usable // 4)),
                        budget="pages")
            if req.uid in self._waiters:
                raise AdmissionError(400, f"duplicate uid {req.uid!r}",
                                     retry_after=0)
            waiter = _Waiter()
            self._waiters[req.uid] = waiter
            self._req_t[req.uid] = time.monotonic()
            try:
                self._batcher.submit(req)  # validates prompt vs max_seq_len
            except ValueError as e:
                self._waiters.pop(req.uid, None)
                self._req_t.pop(req.uid, None)
                raise AdmissionError(400, str(e), retry_after=0)
        finally:
            self._mu.release()
        self._wake.set()
        return req.uid, waiter

    def _reject(self, key: str) -> None:
        """Count one shed under the counters' own leaf lock — reachable
        both with and without ``_mu`` held (the "stalled" path fires
        exactly because ``_mu`` was unavailable)."""
        with self._rej_mu:
            self.rejections[key] += 1

    def _next_uid(self) -> str:
        with self._uid_mu:
            self._uid_seq += 1
            return f"r{self._uid_seq}"

    # ---- multi-tenant serving (inference/tenancy.py) -----------------------

    def _resolve_tenant(self, name) -> tuple:
        """(Tenant, adapter slot) for a request's ``tenant`` field, or
        (None, 0) for anonymous traffic on a single-tenant server.
        Unknown names are a 400 — never a silent base fallback."""
        if name is not None and not isinstance(name, str):
            raise AdmissionError(400, "tenant must be a string",
                                 retry_after=0)
        if self.tenants is None:
            if name:
                raise AdmissionError(
                    400, f"no tenant registry configured (got tenant "
                         f"{name!r}; set inference.tenancy or "
                         f"--tenant-manifest)", retry_after=0)
            return None, 0
        try:
            return self.tenants.resolve(name)
        except KeyError:
            raise AdmissionError(
                400, f"unknown tenant {name!r} (register via POST "
                     f"/tenants)", retry_after=0)

    @staticmethod
    def _tenant_salt(tenant) -> str:
        """The cache-isolation key a tenant stamps on radix subtrees and
        transport chunks. The base identity salts as "" — anonymous
        traffic keeps sharing the pre-tenancy default domain."""
        from picotron_tpu.inference.tenancy import BASE_TENANT

        if tenant is None or tenant.name == BASE_TENANT:
            return ""
        return tenant.name

    def tenants_snapshot(self) -> dict:
        """GET /tenants: every registered tenant + pack occupancy."""
        if self.tenants is None:
            raise AdmissionError(400, "no tenant registry configured",
                                 retry_after=0)
        out = {"tenants": self.tenants.snapshot()}
        pack = self.tenants.pack
        if pack is not None:
            out["pack"] = {"slots": pack.slots, "rank": pack.rank,
                           "version": pack.version,
                           "adapter_bytes_per_token":
                               pack.bytes_per_token()}
        return out

    def tenants_add(self, spec: dict) -> dict:
        """POST /tenants: hot-register one tenant (adapter weights land
        in a free pack slot; the next dispatch re-places the pack — no
        recompile, shapes are capacity-static)."""
        from picotron_tpu.inference.tenancy import Tenant

        if self.tenants is None:
            raise AdmissionError(
                400, "no tenant registry configured (start with "
                     "inference.tenancy or --tenant-manifest)",
                retry_after=0)
        try:
            tenant = Tenant.from_dict(spec)
            slot = self.tenants.add(tenant)
        except (TypeError, ValueError) as e:
            # duplicate names and a full pack are conflicts with current
            # state (retryable after a remove), not malformed requests —
            # but Tenant.from_dict's shape errors are; 409 covers both
            # without parsing messages, and the body says which
            raise AdmissionError(409, str(e), retry_after=0)
        self._event("tenant_add", tenant=tenant.name, slot=slot,
                    priority=tenant.priority, rank=tenant.adapter_rank)
        return {"ok": True, "tenant": tenant.name, "adapter_slot": slot}

    def tenants_remove(self, name: str) -> dict:
        """DELETE /tenants/<name>: hot-deregister. The slot zeroes back
        to null, so in-flight rows degrade to base output — never to
        another tenant's adapter."""
        if self.tenants is None:
            raise AdmissionError(400, "no tenant registry configured",
                                 retry_after=0)
        try:
            self.tenants.remove(name)
        except KeyError:
            raise AdmissionError(404, f"no tenant {name!r}",
                                 retry_after=0)
        self._event("tenant_remove", tenant=name)
        return {"ok": True, "tenant": name}

    # ---- KV-page transport (prefill/decode disaggregation) ----------------

    def _require_paged(self) -> None:
        if self.engine.paged is None:
            raise AdmissionError(
                503, "kv transport requires inference.kv_layout: 'paged' "
                     "on this replica", retry_after=0)

    def kv_export(self, spec: dict) -> dict:
        """POST /kv/export: run ``spec``'s prompt through the normal
        admission + prefill path with a 1-token budget (the one sampled
        token IS the handoff's seat state), then serialize the prompt's
        radix-cached pages as a transport payload. A repeat of a cached
        prompt prefills only its final token — the radix cache makes the
        prefill worker the cluster's prefix bank. Raises AdmissionError
        on shed/failure (the router's fallback trigger)."""
        if self.role == "decode":
            raise AdmissionError(
                503, "replica serves decode only (inference.role: "
                     "decode); export from a prefill/both replica",
                retry_after=5)
        self._require_paged()
        prompt = spec.get("prompt")
        # the tenant salts the exported chunk keys exactly as it salts
        # the radix domain the prefill lands in (resolved/validated again
        # inside submit; this call only needs the canonical salt)
        salt = self._tenant_salt(self._resolve_tenant(
            spec.get("tenant"))[0])
        sub = dict(spec)
        sub["max_new_tokens"] = 1
        sub.pop("stream", None)
        sub.pop("kv", None)
        uid, waiter = self.submit(sub, _internal=True)
        while True:
            kind, val = waiter.events.get()
            if kind == "done":
                res = val
                break
        if res.finish_reason == "shed":
            raise AdmissionError(503, "prefill shed (draining)",
                                 retry_after=5)
        if res.finish_reason not in ("length", "eos") or not res.tokens:
            raise AdmissionError(
                500, f"prefill finished {res.finish_reason!r}",
                retry_after=1)
        first = int(res.tokens[0])
        if not self._mu.acquire(timeout=30.0):
            raise AdmissionError(503, "dispatch stalled (export "
                                      "unavailable)", retry_after=10)
        try:
            payload = self._batcher.export_prefix(prompt,
                                                  first_token=first,
                                                  tenant=salt)
        finally:
            self._mu.release()
        self._event("kv_export", uid=uid, tokens=len(payload["token_ids"]),
                    pages=len(payload["pages"]),
                    bytes=payload["bytes_total"],
                    ttft_s=_r(res.ttft_s))
        return {"uid": uid, "kv": payload,
                "queue_wait_s": _r(res.queue_wait_s),
                "ttft_s": _r(res.ttft_s)}

    def kv_import(self, payload: dict) -> dict:
        """POST /kv/import: land a transport payload in the local pool +
        radix cache (no slot — the cross-replica prefix-cache transfer).
        A subsequent /generate for a prompt extending it radix-hits
        locally, zero prefill dispatches for the covered prefix."""
        from picotron_tpu.inference import page_transport
        from picotron_tpu.inference.paged_kv import PagePoolExhausted

        self._require_paged()
        if not self._mu.acquire(timeout=10.0):
            raise AdmissionError(503, "dispatch stalled (import "
                                      "unavailable)", retry_after=10)
        try:
            if self.stopped.is_set() or self.draining:
                raise AdmissionError(503, "draining (restart in progress)",
                                     retry_after=5)
            try:
                info = self._batcher.import_prefix(payload)
            except page_transport.TransportError as e:
                raise AdmissionError(400, f"bad kv payload: {e}",
                                     retry_after=0)
            except PagePoolExhausted:
                raise AdmissionError(429, "kv page pool exhausted",
                                     retry_after=5)
        finally:
            self._mu.release()
        self._event("kv_import", **info)
        return info

    def kv_pages(self, ids, tenant=None) -> dict:
        """GET/POST /kv/pages: the cross-replica prefix LOOKUP — the
        longest radix-cached prefix of ``ids`` as a transport payload
        (no first token: a lookup vouches for pages, not logits).
        ``matched`` 0 = miss (an empty payload, nothing to import).
        ``tenant`` scopes the lookup to that tenant's radix domain — a
        lookup must never vouch pages across the isolation boundary."""
        self._require_paged()
        salt = self._tenant_salt(self._resolve_tenant(tenant)[0])
        if (not isinstance(ids, list) or not ids
                or not all(isinstance(t, int) for t in ids)):
            raise AdmissionError(400, "ids must be a non-empty list of "
                                      "token ids", retry_after=0)
        if not self._mu.acquire(timeout=10.0):
            raise AdmissionError(503, "dispatch stalled (lookup "
                                      "unavailable)", retry_after=10)
        try:
            payload = self._batcher.export_prefix(ids, tenant=salt)
        finally:
            self._mu.release()
        return {"matched": len(payload["token_ids"]), "kv": payload}

    def kv_prefixes(self, limit: int = 4) -> dict:
        """GET /kv/prefixes: enumerate this replica's hottest radix-cached
        prefixes (token ids + owning tenant), hottest first — the surface
        a fleet controller's drain-time cache handoff walks (each entry
        round-trips /kv/pages here -> /kv/import at a survivor, so a
        drained worker's cache is not lost to the cluster). Bounded lock
        acquire like every scrape-plane surface: a wedged dispatch makes
        this degrade to 503, never deadlock."""
        self._require_paged()
        if limit < 1:
            raise AdmissionError(400, f"limit must be >= 1, got {limit}",
                                 retry_after=0)
        if not self._mu.acquire(timeout=10.0):
            raise AdmissionError(503, "dispatch stalled (enumeration "
                                      "unavailable)", retry_after=10)
        try:
            entries = self.engine.paged.radix.cached_prefixes(limit)
        finally:
            self._mu.release()
        return {"prefixes": [{"ids": list(ids), "tenant": salt or None}
                             for salt, ids in entries]}

    # ---- dispatch loop ----------------------------------------------------

    def _on_token(self, uid: str, tok: int) -> None:
        # called from inside batcher.step() (under _mu)
        w = self._waiters.get(uid)
        if w is not None:
            w.put_token(tok)

    def _loop(self) -> None:
        try:
            while True:
                if self.guard.triggered and not self.draining:
                    self.begin_drain()
                with self._mu:
                    if self.draining:
                        self._batcher.shed_pending()
                    if self._batcher.busy:
                        self._batcher.step()
                    results = self._batcher.take_results()
                    busy = self._batcher.busy
                self._progress_t = time.monotonic()
                for uid, res in results.items():
                    self._deliver(uid, res)
                if self.draining and not busy:
                    self._event("drain_done")
                    return
                if not busy:
                    self._wake.wait(0.05)
                    self._wake.clear()
        except BaseException as e:  # noqa: BLE001 - loop death is fatal news
            self._event("dispatch_loop_died",
                        error=f"{type(e).__name__}: {e}")
            # a dedicated latch, not `stalled`: the watchdog CLEARS stalled
            # on its next tick (progress looked recent), which would flip
            # healthz back to 200 on a dead server forever
            self.dead = True  # healthz goes 503: supervisors restart us
            raise
        finally:
            # never strand a blocked handler: whatever the loop's fate,
            # every still-registered waiter gets a terminal "error" result.
            # Under _mu, stopped BEFORE the snapshot: submit() checks
            # stopped under the same lock, so every admission either saw
            # it (shed 503) or registered its waiter before the snapshot
            # (delivered here) — no in-between request is stranded
            from picotron_tpu.inference.batcher import GenerationResult

            with self._mu:
                self.stopped.set()
                stranded = list(self._waiters)
            for uid in stranded:
                self._deliver(uid, GenerationResult(uid, [], [], "error"))
            if self._on_drained is not None:
                self._on_drained()

    def _deliver(self, uid: str, res) -> None:
        # the pops happen under _mu: handler threads INSERT these entries
        # under the same lock in submit(), and the duplicate-uid check
        # reads _waiters there — an unlocked pop here races both (picolint
        # PICO-C003). The log line and the waiter hand-off (a Queue put)
        # stay outside: neither needs the lock, and the log is file I/O
        # that must not stall admission (PICO-C002).
        with self._mu:
            t0 = self._req_t.pop(uid, None)
            w = self._waiters.pop(uid, None)
        self._event(
            "request", uid=uid, finish_reason=res.finish_reason,
            prompt_tokens=len(res.prompt), new_tokens=len(res.tokens),
            queue_wait_s=_r(res.queue_wait_s), ttft_s=_r(res.ttft_s),
            total_s=_r(None if t0 is None else time.monotonic() - t0))
        td = time.monotonic()
        if w is not None:
            w.put_done(res)
        # the chain's last link: hand-off to the waiting handler thread,
        # parented onto the request's (already-ended) root span
        if getattr(res, "span_id", None):
            self.obs.tracer.record("delivery", td, time.monotonic(),
                                   parent=res.span_id, uid=uid,
                                   finish_reason=res.finish_reason)

    def _watchdog(self) -> None:
        """Dispatch-stall detector, the in-process mirror of
        tools/supervise.py: while work exists, the loop must keep
        finishing steps; a silent gap longer than the threshold flips
        ``stalled`` (healthz 503 — the supervisor's restart signal).
        Recovery (the next completed step) clears it."""
        if self.stall_timeout_s <= 0:
            return
        while not self.stopped.is_set():
            time.sleep(self.watchdog_poll_s)
            busy = self._batcher.busy  # racy read: a threshold, not a ledger
            age = time.monotonic() - self._progress_t
            if busy and age > self.stall_timeout_s:
                if not self.stalled:
                    self.stalled = True
                    self.stalls += 1
                    self._event("stall", age_s=_r(age),
                                threshold_s=self.stall_timeout_s)
            elif self.stalled:
                self.stalled = False
                self._event("stall_recovered")

    # ---- observability ----------------------------------------------------

    def _event(self, evt: str, **fields) -> None:
        """One structured (JSON) log line per server event."""
        self._log(json.dumps({"evt": evt, "t": round(time.time(), 3),
                              **fields}), flush=True)

    def metrics_text(self) -> str:
        """Prometheus text: the server's registry (engine + batcher +
        front end — the same instruments ``/statz`` reads) followed by
        the process-wide resilience counters (retries, emergency saves —
        obs.GLOBAL_REGISTRY). No lock is needed: every instrument
        snapshots under its own leaf lock, and the gauge refresh only
        reads the batcher's occupancy."""
        from picotron_tpu.obs import GLOBAL_REGISTRY

        # depth/occupancy gauges are point-in-time reads: refresh them so
        # a scraper that never touches /statz still sees current values
        self._batcher.refresh_gauges()
        # the prefill-queue depth a disaggregated router watches: on a
        # prefill worker every queued request IS a waiting prefill
        self.obs.registry.gauge(
            "picotron_prefill_queue_depth",
            "requests waiting for a prefill slot").set(
                self._batcher.queue_depth)
        return self.obs.registry.prometheus() + GLOBAL_REGISTRY.prometheus()

    def trace_json(self) -> dict:
        """The process span ring as Chrome-trace JSON."""
        return self.obs.tracer.chrome_trace()

    def healthy(self) -> bool:
        return not (self.stalled or self.dead)

    def ready(self) -> bool:
        return not (self.draining or self.stalled or self.dead)

    def stats(self) -> dict:
        # bounded wait: the stats an operator checks DURING a dispatch
        # stall must answer, degraded, rather than park on the lock the
        # stalled loop is holding
        if self._mu.acquire(timeout=1.0):
            try:
                d = self._batcher.stats()
            finally:
                self._mu.release()
        else:
            d = {"snapshot": "partial (dispatch in progress)"}
        with self._rej_mu:
            d["rejected"] = dict(self.rejections)
        d["weight_bytes"] = self.weight_bytes
        d["weight_dtype"] = self.engine.weight_dtype
        d["role"] = self.role
        if self.tenants is not None:
            d["tenant_names"] = self.tenants.names()
        d["draining"] = self.draining
        d["dead"] = self.dead
        d["stalled"] = self.stalled
        d["stalls"] = self.stalls
        d["uptime_s"] = round(time.monotonic() - self._start_t, 3)
        return d


def _r(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 6)


MAX_BODY_BYTES = 8 << 20  # request-body cap: reject before allocating


class _Handler(BaseHTTPRequestHandler):
    # close-delimited streaming: HTTP/1.0 responses end at connection close,
    # which lets the token stream flush incrementally with zero framing code
    protocol_version = "HTTP/1.0"

    @property
    def front(self) -> FrontEnd:
        return self.server.front

    def log_message(self, *a):  # the front end's JSON lines replace these
        pass

    def _json(self, status: int, payload: dict, headers=()) -> None:
        body = (json.dumps(payload) + "\n").encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:
        f = self.front
        if self.path == "/healthz":
            ok = f.healthy()
            self._json(200 if ok else 503,
                       {"ok": ok, "stalled": f.stalled, "dead": f.dead})
        elif self.path == "/readyz":
            # the body's "state" is the poller's contract: "draining" is
            # GRACEFUL (a router stops placing, breaker untouched) while
            # "stalled"/"dead" are failures — without it, drain and death
            # are indistinguishable 503s (docs/SERVING.md)
            ok = f.ready()
            state = ("dead" if f.dead else "stalled" if f.stalled
                     else "draining" if f.draining else "ready")
            # "role" rides the poller's contract: a router must know a
            # prefill worker from a decode target off the same probe
            self._json(200 if ok else 503,
                       {"ok": ok, "state": state, "role": f.role,
                        "draining": f.draining,
                        "stalled": f.stalled, "dead": f.dead})
        elif self.path == "/statz":
            self._json(200, f.stats())
        elif self.path == "/metrics":
            body = f.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/tracez":
            self._json(200, f.trace_json())
        elif self.path.startswith("/kv/pages"):
            # GET /kv/pages?ids=1,2,3[&tenant=name] — the lookup surface
            # for short prompts and manual inspection (POST takes a JSON
            # body for long ones)
            from urllib.parse import parse_qs, urlparse

            q = parse_qs(urlparse(self.path).query)
            try:
                ids = [int(t) for t in
                       (q.get("ids", [""])[0]).split(",") if t]
            except ValueError as e:
                self._json(400, {"error": f"bad ids: {e}"})
                return
            try:
                self._json(200, f.kv_pages(
                    ids, tenant=q.get("tenant", [None])[0]))
            except AdmissionError as e:
                self._json(e.status, {"error": e.reason, **e.extra})
        elif self.path.startswith("/kv/prefixes"):
            # GET /kv/prefixes?limit=N — the drain-time cache handoff's
            # enumeration surface (tools/fleet.py)
            from urllib.parse import parse_qs, urlparse

            q = parse_qs(urlparse(self.path).query)
            try:
                limit = int(q.get("limit", ["4"])[0])
            except ValueError as e:
                self._json(400, {"error": f"bad limit: {e}"})
                return
            try:
                self._json(200, f.kv_prefixes(limit))
            except AdmissionError as e:
                self._json(e.status, {"error": e.reason, **e.extra})
        elif self.path == "/tenants":
            try:
                self._json(200, f.tenants_snapshot())
            except AdmissionError as e:
                self._json(e.status, {"error": e.reason, **e.extra})
        else:
            self._json(404, {"error": f"unknown path {self.path}"})

    def do_DELETE(self) -> None:
        # DELETE /tenants/<name> — hot tenant removal (the admin half of
        # POST /tenants); in-flight rows degrade to base output
        if not self.path.startswith("/tenants/"):
            self._json(404, {"error": f"unknown path {self.path}"})
            return
        from urllib.parse import unquote

        name = unquote(self.path[len("/tenants/"):])
        try:
            self._json(200, self.front.tenants_remove(name))
        except AdmissionError as e:
            self._json(e.status, {"error": e.reason, **e.extra})

    def _profilez(self, spec: dict) -> None:
        f = self.front
        try:
            seconds = (float(spec["seconds"]) if "seconds" in spec
                       else None)
        except (TypeError, ValueError) as e:
            self._json(400, {"error": f"bad profilez field: {e}"})
            return
        if seconds is not None and seconds <= 0:
            # a malformed request is the CLIENT's bug: 400, never the
            # 409 that means "a capture is already running"
            self._json(400, {"ok": False,
                             "error": f"seconds must be > 0, got {seconds}"})
            return
        out = f.profiler.start(out_dir=spec.get("dir") or None,
                               seconds=seconds)
        self._json(200 if out["ok"] else 409, out)

    def do_POST(self) -> None:
        if self.path not in ("/generate", "/profilez", "/kv/export",
                             "/kv/import", "/kv/pages", "/tenants",
                             "/drain"):
            self._json(404, {"error": f"unknown path {self.path}"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
        except ValueError as e:
            self._json(400, {"error": f"bad Content-Length: {e}"})
            return
        if n < 0:
            self._json(400, {"error": f"bad Content-Length: {n}"})
            return
        if n > MAX_BODY_BYTES:
            # the declared length drives the read: cap it BEFORE allocating,
            # or one client buys arbitrary memory ahead of any admission check
            self._json(413, {"error": f"request body too large "
                                      f"({n} > {MAX_BODY_BYTES} bytes)"})
            return
        try:
            spec = json.loads(self.rfile.read(n) or b"{}")
        except (ValueError, json.JSONDecodeError) as e:
            self._json(400, {"error": f"bad request body: {e}"})
            return
        if not isinstance(spec, dict):
            # valid JSON that is not an object ('[]', 'null', '3') must be
            # a 400, not an AttributeError-dropped connection
            self._json(400, {"error": "request body must be a JSON object"})
            return
        if self.path == "/profilez":
            self._profilez(spec)
            return
        if self.path == "/drain":
            try:
                self._json(202, self.front.drain())
            except AdmissionError as e:
                self._json(e.status, {"error": e.reason, **e.extra})
            return
        if self.path in ("/kv/export", "/kv/import", "/kv/pages",
                         "/tenants"):
            try:
                if self.path == "/kv/export":
                    out = self.front.kv_export(spec)
                elif self.path == "/kv/import":
                    out = self.front.kv_import(spec.get("kv") or spec)
                elif self.path == "/tenants":
                    out = self.front.tenants_add(spec)
                else:
                    out = self.front.kv_pages(spec.get("ids"),
                                              tenant=spec.get("tenant"))
            except AdmissionError as e:
                headers = ([("Retry-After", str(e.retry_after))]
                           if e.retry_after else [])
                self._json(e.status, {"error": e.reason, **e.extra},
                           headers)
                return
            self._json(200, out)
            return
        try:
            uid, waiter = self.front.submit(spec)
        except AdmissionError as e:
            headers = ([("Retry-After", str(e.retry_after))]
                       if e.retry_after else [])
            self._json(e.status, {"error": e.reason, "shed": True,
                                  **e.extra}, headers)
            return
        # client-supplied correlation id, echoed on every response row
        # (falling back to the server uid): the observable a router's
        # replay dedup keys off end to end
        rid = str(spec.get("request_id") or uid)
        if spec.get("stream"):
            self._stream(uid, waiter, rid)
        else:
            res = self._await_result(waiter)
            payload = {"uid": uid, "request_id": rid,
                       "tokens": list(res.tokens),
                       "finish_reason": res.finish_reason,
                       "queue_wait_s": _r(res.queue_wait_s),
                       "ttft_s": _r(res.ttft_s)}
            if res.finish_reason == "shed":
                self._json(503, payload, [("Retry-After", "5")])
            elif res.finish_reason == "error":
                self._json(500, payload)
            else:
                self._json(200, payload)

    def _await_result(self, waiter: _Waiter):
        while True:
            kind, val = waiter.events.get()
            if kind == "done":
                return val

    def _stream(self, uid: str, waiter: _Waiter, request_id: str) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.end_headers()

        def emit(obj):
            self.wfile.write((json.dumps(obj) + "\n").encode())
            self.wfile.flush()

        while True:
            kind, val = waiter.events.get()
            try:
                if kind == "token":
                    emit({"event": "token", "uid": uid,
                          "request_id": request_id, "token": int(val)})
                    continue
                emit({"event": "done", "uid": uid,
                      "request_id": request_id,
                      "tokens": list(val.tokens),
                      "finish_reason": val.finish_reason,
                      "queue_wait_s": _r(val.queue_wait_s),
                      "ttft_s": _r(val.ttft_s)})
            except (BrokenPipeError, ConnectionResetError):
                # client went away: generation continues (the batcher owns
                # the request; its per-request timeout_s bounds the waste),
                # keep draining events so the waiter's queue empties
                if kind == "done":
                    return
                continue
            if kind == "done":
                return


class Server:
    """FrontEnd + ThreadingHTTPServer, both on background threads. The
    embedding entry point for the CLI, the smoke drive, and the tests."""

    def __init__(self, engine, params, *, host: str = "127.0.0.1",
                 port: int = 0, **front_kw):
        self.front = FrontEnd(engine, params, **front_kw)
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.front = self.front
        self.port = self.httpd.server_address[1]
        self._http_thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self.front.start()
        # once the dispatch loop finishes a drain, stop accepting sockets
        self.front._on_drained = lambda: threading.Thread(
            target=self.httpd.shutdown, daemon=True).start()
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever, name="serve-http", daemon=True)
        self._http_thread.start()

    def drain_and_join(self, timeout: Optional[float] = None) -> None:
        self.front.begin_drain()
        self.front.join(timeout)
        self.httpd.shutdown()
        if self._http_thread is not None:
            self._http_thread.join(timeout)
        self.httpd.server_close()


# --------------------------------------------------------------------------- #
# CLI
# --------------------------------------------------------------------------- #


def _build_engine_and_params(args):
    from picotron_tpu.config import Config
    from picotron_tpu.inference import InferenceEngine
    from picotron_tpu.tools.generate import SMOKE_CONFIG, _load_weights
    from picotron_tpu.train import _ensure_devices

    if args.smoke:
        cfg = Config.from_dict(SMOKE_CONFIG)
        args.random_init = True
    elif args.config:
        with open(args.config) as f:
            cfg = Config.from_dict(json.load(f))
    else:
        raise SystemExit("pass --config (or --smoke)")
    if not (args.load_path or args.hf_path or args.random_init):
        raise SystemExit("pass one of --load-path / --hf-path / "
                         "--random-init")
    if getattr(args, "kv_layout", None):
        cfg.inference.kv_layout = args.kv_layout
    if getattr(args, "role", None):
        cfg.inference.role = args.role
    if getattr(args, "overlap", False):
        # zero-bubble pipelined scheduling (docs/INFERENCE.md
        # "Overlapped scheduling"): forces the per-slot key schedule,
        # token streams stay bit-identical to the default
        cfg.inference.overlap = True
    if getattr(args, "kv_layout", None) or getattr(args, "role", None):
        # either override can break the role/layout invariant (e.g.
        # --kv-layout contiguous on a config whose role is prefill)
        cfg.validate()
    _ensure_devices(cfg)
    from picotron_tpu.resilience.chaos import ServingChaos

    chaos = ServingChaos(cfg.resilience)
    hooks = chaos if chaos.active else None
    adapters, registry = _build_tenancy(cfg, args)
    engine = InferenceEngine(cfg, slots=args.slots,
                             max_seq_len=args.max_seq_len, hooks=hooks,
                             adapters=adapters)
    params = _load_weights(args, cfg, engine)
    return cfg, engine, params, registry


def _build_tenancy(cfg, args):
    """(AdapterPack, TenantRegistry) from inference.tenancy + the
    --tenant-manifest override, or (None, None) when no tenancy is
    configured (the bit-pinned single-tenant default: no pack, so the
    compiled programs are byte-identical to the pre-tenancy engine).
    The pack is built whenever a registry is — even all-rank-0 tenants
    may hot-add an adapter tenant later, and capacity must exist from
    the start (add/remove never recompiles)."""
    tcfg = cfg.inference.tenancy
    manifest = getattr(args, "tenant_manifest", "") or tcfg.manifest
    if not manifest and not tcfg.tenants:
        return None, None
    from picotron_tpu.inference import tenancy

    pack = tenancy.AdapterPack(cfg.model, slots=tcfg.adapter_slots,
                               rank=tcfg.adapter_rank)
    if manifest:
        registry = tenancy.TenantRegistry.from_manifest(manifest, pack)
    else:
        registry = tenancy.TenantRegistry(pack)
    for entry in tcfg.tenants:  # config extends (or replaces) a manifest
        registry.add(tenancy.Tenant.from_dict(entry))
    return pack, registry


def _post(port: int, spec: dict, stream: bool = False):
    """Minimal stdlib client for the smoke drive: returns (status,
    parsed-JSON body) or, streaming, (status, [parsed NDJSON events])."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
    conn.request("POST", "/generate", json.dumps(spec),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    if stream:
        lines = [json.loads(l) for l in resp.read().splitlines() if l]
        out = (resp.status, lines)
    else:
        out = (resp.status, json.loads(resp.read() or b"{}"))
    conn.close()
    return out


def _get(port: int, path: str):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", path)
    resp = conn.getresponse()
    out = (resp.status, json.loads(resp.read() or b"{}"))
    conn.close()
    return out


def _profilez_post(port: int, spec: dict):
    """POST /profilez (stdlib client): (status, parsed body)."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("POST", "/profilez", json.dumps(spec),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    out = (resp.status, json.loads(resp.read() or b"{}"))
    conn.close()
    return out


def _get_text(port: int, path: str):
    """GET a non-JSON surface (/metrics): (status, text)."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", path)
    resp = conn.getresponse()
    out = (resp.status, resp.read().decode("utf-8", errors="replace"))
    conn.close()
    return out


def _smoke(server: Server, obs_dump: str = "") -> int:
    """The `make serve-smoke` drive: health, one POST, one streamed POST,
    the observability surfaces (/metrics agreeing with /statz, a complete
    request chain in /tracez, one timed /profilez capture), then a
    SIGTERM drain with full accounting. ``obs_dump`` saves the trace and
    metrics page there for `make obs-smoke`'s trace_dump gate. Returns an
    exit code."""
    import os
    import signal

    port = server.port
    fail = []

    def check(name, ok):
        print(f"serve-smoke: {name}: {'ok' if ok else 'FAIL'}", flush=True)
        if not ok:
            fail.append(name)

    check("healthz", _get(port, "/healthz")[0] == 200)
    check("readyz", _get(port, "/readyz")[0] == 200)

    spec = {"prompt": [1, 2, 3, 4, 5], "max_new_tokens": 8}
    st, body = _post(port, spec)
    check("generate", st == 200 and len(body["tokens"]) == 8
          and body["finish_reason"] == "length")

    st, events = _post(port, {**spec, "stream": True}, stream=True)
    done = [e for e in events if e["event"] == "done"]
    toks = [e["token"] for e in events if e["event"] == "token"]
    check("stream", st == 200 and len(done) == 1
          and done[0]["tokens"] == toks
          and done[0]["tokens"] == body["tokens"])  # greedy: deterministic

    # ---- observability surfaces (docs/OBSERVABILITY.md) ----
    from picotron_tpu.obs.metrics import parse_prometheus
    from picotron_tpu.tools import trace_dump

    st, stats = _get(port, "/statz")
    mst, mtext = _get_text(port, "/metrics")
    prom = parse_prometheus(mtext)
    check("metrics_agrees_with_statz",
          mst == 200
          and prom.get('picotron_requests_total{state="completed"}')
          == stats.get("completed")
          and prom.get('picotron_generated_tokens_total')
          == stats.get("generated_tokens"))
    tst, trace = _get(port, "/tracez")
    chains = trace_dump.request_chains(trace)
    check("tracez_request_chain",
          tst == 200 and not trace_dump.validate(trace)
          and any(c["complete"] for c in chains.values()))
    if stats.get("overlap", {}).get("enabled"):
        # zero-bubble gates (--overlap): the issue-to-issue gap collapses
        # under a full pipeline — strictly below the per-round host sync
        # it used to serialize behind — and every overlap span links
        # round N's sync stage inside round N+1's dispatch window
        ov = stats["overlap"]
        gap = (ov.get("dispatch_gap_s") or {}).get("p50")
        check("overlap_gap_lt_host_sync",
              gap is not None
              and gap < max(stats.get("last_host_sync_s", 0.0), 1e-6))
        oc = trace_dump.overlap_chain(trace)
        check("overlap_span_chain",
              oc["linked"] >= 1 and not oc["errors"])
    if obs_dump:
        os.makedirs(obs_dump, exist_ok=True)
        with open(os.path.join(obs_dump, "trace.json"), "w") as f:
            json.dump(trace, f)
        with open(os.path.join(obs_dump, "metrics.txt"), "w") as f:
            f.write(mtext)
    prof_dir = os.path.join(obs_dump or "/tmp", "serve-smoke-profile")
    pst, pbody = _profilez_post(port, {"seconds": 0.2, "dir": prof_dir})
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and server.front.profiler.running:
        time.sleep(0.05)
    check("profilez",
          pst == 200 and pbody.get("ok")
          and server.front.profiler.captures >= 1
          and os.path.isdir(prof_dir) and os.listdir(prof_dir))

    # drain: one slow request in flight + SIGTERM -> it finishes, the
    # server stops admitting, and the exit is clean
    slow: dict = {}

    def bg():
        slow["resp"] = _post(port, {"prompt": [7, 8, 9],
                                    "max_new_tokens": 24})

    t = threading.Thread(target=bg)
    t.start()
    # wait until the slow request actually holds a slot (a fixed sleep is a
    # race on a loaded host: still-queued at SIGTERM means it gets shed and
    # the drain checks below fail spuriously); "completed" covers the other
    # race, where the tiny model finishes it before we observe the slot
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        s = _get(port, "/statz")[1]
        if s.get("active_slots", 0) > 0 or s.get("completed", 0) >= 3:
            break
        time.sleep(0.02)
    else:
        check("slow_request_admitted", False)
    os.kill(os.getpid(), signal.SIGTERM)
    server.front.join(timeout=120)
    check("drain_finished", server.front.stopped.is_set())
    t.join(timeout=120)
    st, body = slow.get("resp", (None, {}))
    check("inflight_served_through_drain",
          st == 200 and body.get("finish_reason") == "length")
    stats = server.front.stats()
    # every admitted request reached a terminal state and nothing leaked
    terminal = stats["completed"] + stats["expired"] + stats["errored"]
    check("accounting", terminal == stats["admitted"] == 3
          and stats["queued"] == 0 and stats["active_slots"] == 0)
    check("no_stalls", stats["stalls"] == 0)
    return 1 if fail else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="HTTP serving front end over the continuous batcher "
                    "(admission control, load shedding, graceful drain)")
    ap.add_argument("--config", help="training config.json (model shape, tp)")
    ap.add_argument("--load-path", default="", help="orbax checkpoint dir")
    ap.add_argument("--hf-path", default="", help="HF safetensors file/dir")
    ap.add_argument("--random-init", action="store_true",
                    help="seed-derived random weights (smoke)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000,
                    help="0 = ephemeral (printed at startup)")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--max-seq-len", type=int, default=None)
    ap.add_argument("--role", choices=("prefill", "decode", "both"),
                    default=None,
                    help="disaggregated serving role (overrides "
                         "inference.role; prefill/decode require "
                         "inference.kv_layout: paged)")
    ap.add_argument("--kv-layout", choices=("contiguous", "paged"),
                    default=None,
                    help="KV cache layout override (paged is required "
                         "for any role but 'both')")
    ap.add_argument("--overlap", action="store_true",
                    help="zero-bubble scheduling: issue dispatch N+1 "
                         "before syncing dispatch N (sets "
                         "inference.overlap; bit-identical streams)")
    ap.add_argument("--max-queue", type=int, default=64,
                    help="bounded wait queue: excess submissions get 503")
    ap.add_argument("--token-budget", type=int, default=None,
                    help="cap on live prompt+generation tokens (default: "
                         "slots * max_seq_len); excess gets 429")
    ap.add_argument("--default-timeout-s", type=float, default=None,
                    help="per-request wall-clock deadline when the request "
                         "does not set one (finish_reason 'timeout')")
    ap.add_argument("--stall-timeout", type=float, default=60.0,
                    help="dispatch-stall watchdog threshold (0 = off); a "
                         "stall flips /healthz to 503")
    ap.add_argument("--tenant-manifest", default="",
                    help="JSON tenant manifest ({\"tenants\": [...]}, "
                         "inference/tenancy.py) — overrides "
                         "inference.tenancy.manifest; enables the "
                         "multi-tenant plane (adapter pack, /tenants "
                         "admin endpoint, per-tenant quotas/SLOs)")
    ap.add_argument("--smoke", action="store_true",
                    help="built-in tiny CPU model + scripted client drive "
                         "(the `make serve-smoke` target)")
    ap.add_argument("--obs-dump", default="",
                    help="smoke only: save the drive's /tracez JSON and "
                         "/metrics page into this dir (the `make "
                         "obs-smoke` target validates them with "
                         "tools/trace_dump.py)")
    args = ap.parse_args(argv)

    cfg, engine, params, registry = _build_engine_and_params(args)

    server = Server(
        engine, params, host=args.host,
        port=0 if args.smoke else args.port, seed=args.seed,
        max_queue=args.max_queue, token_budget=args.token_budget,
        default_timeout_s=args.default_timeout_s,
        stall_timeout_s=args.stall_timeout, tenants=registry)
    # SIGTERM/SIGINT -> graceful drain (the PreemptionGuard pattern: first
    # signal is cooperative, second aborts). SIGUSR2 -> one timed
    # jax.profiler capture into obs.profile_dir (the POST /profilez
    # trigger without a client). Installed on the main thread.
    server.front.guard.install()
    from picotron_tpu.obs import install_sigusr2

    install_sigusr2(server.front.profiler)
    server.start()
    server.front._event(
        "serving", port=server.port, slots=engine.slots,
        max_seq_len=engine.max_seq_len, max_queue=args.max_queue,
        token_budget=server.front.token_budget,
        attend_impl=engine.attend_impl, role=server.front.role,
        kv=str(engine.cache_dtype), kv_layout=engine.kv_layout,
        tp=engine.topo.tp_size,
        tenants=(registry.names() if registry is not None else None))

    if args.smoke:
        rc = _smoke(server, obs_dump=args.obs_dump)
        print(f"serve-smoke: {'PASS' if rc == 0 else 'FAIL'}", flush=True)
        return rc

    # foreground: wait for the drain (SIGTERM) to complete. Exit 0 ONLY
    # for a clean drain — a dead dispatch loop must exit nonzero so a
    # supervisor (tools/supervise.py --serve) restarts the replica
    # instead of reading the death as an intentional shutdown.
    try:
        while not server.front.stopped.is_set():
            server.front.join(timeout=1.0)
    except KeyboardInterrupt:
        pass  # second signal: abort now
    return 1 if server.front.dead else 0


if __name__ == "__main__":
    sys.exit(main())
