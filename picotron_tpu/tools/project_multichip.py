"""Multi-chip MFU projection for the BASELINE config ladder.

Real multi-chip hardware is not reachable from this rig (one v5e behind a
tunnel), so the ladder configs 3-5 (BASELINE.md:24-26) are *projected* from
first principles, anchored on measured single-chip efficiency:

    MFU_proj = eff_1chip                      (measured compute efficiency)
             x t_compute / (t_compute + t_exposed_comm)
             x bubble_efficiency               (pipeline fill/drain)

with per-axis communication volumes computed analytically from the model
geometry (the same math the reference's NCCL schedule implies) and divided
by stated ICI bandwidth assumptions. Every assumption is a named constant
below; re-run `python tools/project_multichip.py` to regenerate
docs/PROJECTION.md's table.

Conservatism policy (each choice biases MFU_proj DOWN):
- TP/SP collectives are counted fully exposed (XLA can overlap the backward
  weight-grad matmuls with them; we take no credit).
- The DP gradient all-reduce is overlapped with the backward pass except
  for one final reduce the optimizer waits on; we charge 25% of it.
- CP ring K/V hops overlap with per-block attention compute; we charge only
  the amount by which the hop exceeds the block compute (0 in practice at
  these sizes, so the ring is charged its first hop only).
- PP p2p boundary activations are tiny but charged fully exposed.

Anchors (single-chip, measured on the v5e, docs/BENCH_7B.md; re-anchor when
any round's bench capture lands — still pending as of r05, see
docs/PROJECTION.md status note):
- SmolLM-1.7B @ seq 2048: 55.3% MFU
- Llama-2-7B-geometry proxy @ seq 4096: 66.5% MFU
"""

from __future__ import annotations

import dataclasses

# ---- TPU v5e assumptions (public numbers; jax-ml.github.io/scaling-book) ----
PEAK_FLOPS = 1.97e14        # dense bf16 FLOPs/s/chip
ICI_BW = 4.5e10             # bytes/s one-way per link per direction
# A v5e-16 slice is a 4x4 2D torus: each mesh axis mapped onto a torus ring
# has wraparound, so ring collectives run at 2 links x ICI_BW (both
# directions). We charge the standard ring-algorithm cost:
#   all_gather / reduce_scatter of S bytes over n chips: S*(n-1)/n / (2*ICI_BW)
#   all_reduce: 2x that.
RING_BW = 2 * ICI_BW
BYTES_ACT = 2               # bf16 activations
# Default grad bytes: sync and accumulation run in fp32 (the dp/cp pmean
# and the ZeRO-1 reduce-scatter see the accumulators; downcast happens
# after sync+clip, train_step.py). Rows with grad_accum='param' override
# this to 2 B in project() — bf16 accumulators are synced as bf16.
BYTES_GRAD = 4

# measured single-chip compute efficiency anchors (docs/BENCH_7B.md)
EFF_SMOLLM = 0.553
EFF_7B = 0.665


@dataclasses.dataclass
class Model:
    name: str
    L: int          # layers
    H: int          # hidden
    I: int          # intermediate (SwiGLU)
    heads: int
    kv_heads: int
    V: int          # vocab
    eff_1chip: float

    @property
    def head_dim(self):
        return self.H // self.heads

    def n_params(self) -> int:
        attn = self.H * (self.heads + 2 * self.kv_heads) * self.head_dim \
            + self.heads * self.head_dim * self.H
        mlp = 3 * self.H * self.I
        return self.L * (attn + mlp + 2 * self.H) + 2 * self.V * self.H + self.H

    def flops_per_token(self, seq: int) -> float:
        """Reference MFU numerator (utils.py:42-48): 6N + 12*L*H*S."""
        return 6 * self.n_params() + 12 * self.L * self.H * seq


SMOLLM = Model("SmolLM-1.7B", L=24, H=2048, I=8192, heads=32, kv_heads=32,
               V=49152, eff_1chip=EFF_SMOLLM)
LLAMA7B = Model("Llama-2-7B", L=32, H=4096, I=11008, heads=32, kv_heads=32,
                V=32000, eff_1chip=EFF_7B)


@dataclasses.dataclass
class Ladder:
    idx: int
    model: Model
    dp: int
    tp: int
    pp: int
    cp: int
    seq: int
    mbs: int = 1
    acc: int = 8   # microbatches per step (>= pp so 1F1B fills)
    zero1: bool = False  # dp-shard optimizer state (needed to FIT 7B on v5e)
    interleave: int = 1  # virtual pipeline stages (pp_interleave): bubble /= v
    # training.grad_accum_dtype: "float32" | "param" (bf16 accumulators)
    grad_accum: str = "float32"
    tag: str = ""  # annotation carried into the printed config column

    @property
    def chips(self):
        return self.dp * self.tp * self.pp * self.cp


def ring_ag_or_rs(bytes_full: float, n: int) -> float:
    """Seconds for a ring all-gather or reduce-scatter of a full-size
    ``bytes_full`` tensor over ``n`` chips."""
    if n == 1:
        return 0.0
    return bytes_full * (n - 1) / n / RING_BW


def ring_ar(bytes_full: float, n: int) -> float:
    return 2 * ring_ag_or_rs(bytes_full, n)


def project(lc: Ladder) -> dict:
    m, S = lc.model, lc.seq
    B = lc.mbs                       # per-microbatch batch per dp replica

    # ---- compute time per microbatch (fwd+bwd), per chip ----
    flops_mb = m.flops_per_token(S) * B * S / (lc.tp * lc.pp * lc.cp)
    t_compute = flops_mb / (PEAK_FLOPS * m.eff_1chip)

    # ---- TP/SP collectives per microbatch (Megatron, sequence-parallel) ----
    # Per layer, forward: all-gather into attn + into mlp, reduce-scatter out
    # of both; backward mirrors (the transpose collective). 4 AG + 4 RS per
    # layer per microbatch, each of the full [B, S/cp, H] activation.
    act_bytes = B * (S // lc.cp) * m.H * BYTES_ACT
    layers_here = m.L / lc.pp
    t_tp = layers_here * 8 * ring_ag_or_rs(act_bytes, lc.tp)
    # vocab-parallel CE gathers logits max/sum only (scalars per token) —
    # negligible; the fused-CE path never materializes gathered logits.

    # ---- CP ring per microbatch ----
    # K and V blocks hop cp-1 times (fwd) and kv+dkv hop cp-1 times (bwd).
    # Each hop overlaps with that block's attention compute; attention block
    # compute >> hop time at these sizes, so only the first hop is exposed.
    kv_bytes = 2 * B * (S // lc.cp) * m.kv_heads * m.head_dim * BYTES_ACT
    t_cp = (3 * kv_bytes / RING_BW) if lc.cp > 1 else 0.0  # 1 fwd + 2 bwd hops

    # ---- PP p2p per microbatch ----
    pp_bytes = B * (S // lc.cp) * m.H * BYTES_ACT / max(
        1, lc.tp)  # SP: boundary is seq-sharded over tp
    # interleaving multiplies boundary crossings by v (each microbatch
    # traverses v*pp chunks) — the cost side of the bubble credit
    t_pp = (2 * pp_bytes * lc.interleave / ICI_BW) if lc.pp > 1 else 0.0

    # ---- DP gradient sync per step (amortized over acc microbatches) ----
    bytes_grad = 2 if lc.grad_accum == "param" else BYTES_GRAD
    shard_params = m.n_params() / (lc.tp * lc.pp)
    if lc.zero1:
        # reduce-scatter the accumulator-dtype grads + all-gather the bf16
        # updated params: 6 B/param at fp32 accum vs the plain all-reduce's
        # 2 x 4 = 8 — ZeRO-1 is cheaper on the wire, not just on memory
        t_dp_full = (ring_ag_or_rs(shard_params * bytes_grad, lc.dp)
                     + ring_ag_or_rs(shard_params * 2, lc.dp))
    else:
        t_dp_full = ring_ar(shard_params * bytes_grad, lc.dp)
    t_dp = 0.25 * t_dp_full / lc.acc  # mostly overlapped with backward

    t_comm = t_tp + t_cp + t_pp + t_dp
    comm_eff = t_compute / (t_compute + t_comm)
    # interleaved 1F1B shrinks the fill/drain bubble by the virtual-stage
    # factor (parallel/pp.py::pipeline_1f1b_interleaved; equivalence-tested)
    bubble_eff = lc.acc / (lc.acc + (lc.pp - 1) / lc.interleave)

    mfu = m.eff_1chip * comm_eff * bubble_eff

    # ---- memory sanity (bytes/chip): params bf16 (2) + Adam m,v in param
    # dtype (optax zeros_like -> bf16, 4 total; NOT the fp32 8 a torch
    # fp32-state setup would need) + the grad accumulator (4 fp32 / 2
    # param). ZeRO-1 dp-shards the moments. Activations/temp buffers are
    # excluded (remat keeps them small; stated in docs/PROJECTION.md) ----
    opt_bytes = 4 / lc.dp if lc.zero1 else 4
    mem = shard_params * (2 + opt_bytes + bytes_grad)
    return dict(
        config=(f"{m.name} dp{lc.dp}/tp{lc.tp}/pp{lc.pp}/cp{lc.cp} seq{S}"
                + (" (ZeRO-1)" if lc.zero1 else "")
                + (f" [{lc.tag}]" if lc.tag else "")),
        grad_accum=lc.grad_accum,
        chips=lc.chips, mfu=100 * mfu, comm_eff=100 * comm_eff,
        bubble_eff=100 * bubble_eff,
        t_compute_ms=1e3 * t_compute, t_tp_ms=1e3 * t_tp, t_cp_ms=1e3 * t_cp,
        t_pp_ms=1e3 * t_pp, t_dp_ms=1e3 * t_dp,
        mem_gb=mem / 1e9,
    )


LADDER = [
    Ladder(3, SMOLLM, dp=2, tp=2, pp=2, cp=1, seq=2048),
    Ladder(3, SMOLLM, dp=2, tp=2, pp=2, cp=2, seq=2048),  # v5e-16 north star
    # 7B does NOT fit a 16 GB v5e at tp2/pp2 with dp-replicated grads+state
    # (1.68B params/chip x 10 B = 16.8 GB) — the GPU reference fits in 80 GB
    # H100s; on v5e the tp2/pp2 configs need our ZeRO-1 (13.5 GB), and
    # grad_accum_dtype='param' (bf16 accumulators, supported by all three
    # pipeline engines) buys another 3.4 GB of activation headroom at
    # seq 8192. The pp4/dp1 rows carry the same 16-chip 4D workload with
    # deeper model sharding instead.
    Ladder(4, LLAMA7B, dp=4, tp=2, pp=2, cp=1, seq=1024, zero1=True),
    Ladder(5, LLAMA7B, dp=2, tp=2, pp=2, cp=2, seq=8192, zero1=True,
           tag="canonical"),
    Ladder(5, LLAMA7B, dp=2, tp=2, pp=2, cp=2, seq=8192, zero1=True,
           grad_accum="param", tag="canonical + bf16 grad accum"),
    Ladder(5, LLAMA7B, dp=1, tp=2, pp=4, cp=2, seq=8192,
           tag="pp4 variant"),
    Ladder(5, LLAMA7B, dp=1, tp=2, pp=4, cp=2, seq=8192, interleave=2,
           tag="pp4 variant + pp_interleave 2"),
]


def main():
    rows = [project(lc) for lc in LADDER]
    print("| config | chips | proj MFU % | comm eff % | bubble eff % | "
          "t_comp ms | t_tp ms | t_cp ms | t_pp ms | t_dp ms | mem GB/chip |")
    print("|---|---|---|---|---|---|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['config']} | {r['chips']} | {r['mfu']:.1f} | "
              f"{r['comm_eff']:.1f} | {r['bubble_eff']:.1f} | "
              f"{r['t_compute_ms']:.2f} | {r['t_tp_ms']:.2f} | "
              f"{r['t_cp_ms']:.3f} | {r['t_pp_ms']:.3f} | "
              f"{r['t_dp_ms']:.3f} | {r['mem_gb']:.1f} |")


if __name__ == "__main__":
    main()
