"""Measure the training-step stall caused by a checkpoint save.

The round-3 VERDICT flagged synchronous orbax saves (weak item 6): at
7B-proxy scale every ``save_frequency`` boundary stalled training for the
full serialization. ``CheckpointManager`` now defaults to async saves —
``save()`` returns after the device-to-host copy and the disk write happens
in a background thread. This tool measures both modes on the same tree:

    python -m picotron_tpu.tools.measure_ckpt_stall [n_params_millions]

Prints one JSON line: {"n_params", "sync_save_s", "async_return_s",
"async_drain_s", "stall_reduction"} where *_return_s is the time train()
is blocked and drain is the background completion (paid only at exit).
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time


def measure(n_million: int = 200) -> dict:
    import jax
    import jax.numpy as jnp

    from picotron_tpu.utils import honor_cpu_env_pin

    honor_cpu_env_pin()

    from picotron_tpu.checkpoint import CheckpointManager

    n = n_million * 1_000_000
    # a handful of large leaves, like a real layer-stacked param tree
    leaf = n // 8
    params = {f"w{i}": jnp.arange(leaf, dtype=jnp.float32) / leaf
              for i in range(8)}
    opt_state = {f"m{i}": jnp.zeros(leaf // 4, jnp.float32) for i in range(8)}
    jax.block_until_ready(params)

    out = {"n_params": n}
    for mode in ("sync", "async"):
        d = tempfile.mkdtemp(prefix=f"ckpt_stall_{mode}_")
        try:
            mgr = CheckpointManager(d, async_save=(mode == "async"))
            t0 = time.perf_counter()
            mgr.save(1, params, opt_state, trained_tokens=0)
            t_return = time.perf_counter() - t0
            mgr.wait_until_finished()
            t_drain = time.perf_counter() - t0 - t_return
            mgr.close()
        finally:
            shutil.rmtree(d, ignore_errors=True)
        if mode == "sync":
            out["sync_save_s"] = round(t_return, 3)
        else:
            out["async_return_s"] = round(t_return, 3)
            out["async_drain_s"] = round(t_drain, 3)
    out["stall_reduction"] = round(
        out["sync_save_s"] / max(out["async_return_s"], 1e-9), 1)
    return out


if __name__ == "__main__":
    nm = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    print(json.dumps(measure(nm)))
