"""Session-long TPU-tunnel watcher: turn ANY live window into evidence.

Rounds 3-4 lost their official numbers to a tunnel that dies for hours
and revives without notice; chip_agenda converts one live window into
artifacts, but someone still has to be watching when the window opens.
This tool IS that someone: it probes the tunnel on an interval (with a
killable child — the axon client blocks forever inside backend init on a
dead tunnel) and, whenever the probe sees a TPU, runs the agenda steps
that have not yet succeeded (``chip_agenda --only <pending>``). Steps
that pass are never re-run; the watcher exits 0 the moment every step
has passed, or 1 when the time budget runs out.

    python -m picotron_tpu.tools.tunnel_watch [--interval 600]
        [--budget-hours 10] [--state docs/chip_runs/watch_state.json]

State (which steps have passed, where their artifacts live) persists to
a JSON file, so a restarted watcher — or a later round — resumes instead
of repeating captured evidence. Nothing in this process ever initializes
a jax backend (that, not the import, is what hangs on a dead tunnel):
probing and work both happen in killable children, so the watcher itself
can never hang on the tunnel.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))

from picotron_tpu.tools.chip_agenda import STEP_TIMEOUTS  # noqa: E402

ALL_STEPS = tuple(STEP_TIMEOUTS)


def probe_tunnel(timeout: float = 90.0) -> str:
    """'tpu' | 'cpu' | 'dead' — same contract as bench.probe_tunnel
    (bench.py:211), duplicated here so the watcher stays import-light."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices()[0]; "
             "print(d.platform, d.device_kind)"],
            capture_output=True, text=True, timeout=timeout, cwd=REPO)
        if r.returncode != 0:
            return "dead"
        return "tpu" if "tpu" in r.stdout.lower() else "cpu"
    except subprocess.TimeoutExpired:
        return "dead"


# backend-init-free, like everything else this module pulls in (the
# package import chain does load the jax MODULE; only backend init — which
# this process never does — can hang on a dead tunnel)
from picotron_tpu.bench_record import (  # noqa: E402
    BENCH_METRICS as BENCH_STEP_METRICS,
    iter_metric_records,
)


def step_captured(step: str, rc: int, log_path: str) -> bool:
    """Whether a finished agenda step actually produced its evidence.

    rc==0 alone is NOT that for the bench steps: their orchestrator exits
    0 even when it publishes a null artifact or republishes an earlier
    stale capture (the never-empty contract, bench.py:orchestrate). Were
    rc the test, a diagnosed-failure bench would be marked passed and
    never retried in a later window — the 20260731T0316 window's bench
    ended exactly that way. A bench step counts only when its own log
    (``log_path``, from the agenda's summary record) carries a real,
    non-stale record of the step's on-TPU metric."""
    if rc != 0:
        return False
    metric = BENCH_STEP_METRICS.get(step)
    if metric is None:
        return True
    return any(rec.get("metric") == metric
               and rec.get("value") is not None
               and "stale_from" not in rec
               for rec in iter_metric_records(log_path))


def null_artifact_blames_code(log_path: str) -> bool:
    """Whether a bench step's rc==0 null artifact diagnoses a CODE failure.

    orchestrate stamps ``"code_failure": true`` into the null artifact
    when an inner run exited artifact-less WITHOUT an infra signature
    (bench.py:orchestrate) — deterministic, worth a strike, or the
    watcher would re-run a broken bench every live window for the whole
    budget. Infra verdicts (hangs, EX_INFRA bail-outs, tunnel-death
    crash tails, dead probes) carry no such stamp and stay retryable."""
    return any(rec.get("value") is None and rec.get("code_failure")
               for rec in iter_metric_records(log_path))


def load_state(path: str) -> dict:
    try:
        with open(path) as f:
            state = json.load(f)
    except (OSError, ValueError):
        state = {}
    if not isinstance(state, dict) or not isinstance(
            state.get("passed"), dict):
        state = {"passed": {}}
    # Paths persist REPO-relative (a checkout on another machine must not
    # inherit /root/repo-absolute evidence pointers); absolute entries from
    # older state files are accepted as-is. In memory they are absolute.
    for step, out_dir in list(state["passed"].items()):
        if not os.path.isabs(out_dir):
            state["passed"][step] = os.path.join(REPO, out_dir)
    # Revalidate resumed bench entries against their actual evidence: a
    # state file written by an older watcher (whose pass criterion was
    # rc==0 alone) can claim a bench passed when its artifact was null.
    # The agenda's summary.json in the recorded out_dir carries each
    # step's rc and log path; anything unverifiable is retried.
    for step in [s for s in state["passed"] if s in BENCH_STEP_METRICS]:
        out_dir = state["passed"][step]
        ok = False
        try:
            with open(os.path.join(out_dir, "summary.json")) as f:
                for r in json.load(f):
                    if r.get("step") == step and step_captured(
                            step, r.get("rc", 1), r.get("log", "")):
                        ok = True
        except (OSError, ValueError):
            pass
        if not ok:
            log(f"resumed state claimed {step} passed but {out_dir} has "
                f"no real capture — retrying it")
            del state["passed"][step]
    # Non-bench entries carry no summary to revalidate; at least demand the
    # evidence directory exists, or a state file copied between machines
    # silently inherits a pass pointing at nothing.
    for step in [s for s in state["passed"]
                 if s not in BENCH_STEP_METRICS]:
        if not os.path.isdir(state["passed"][step]):
            log(f"resumed state claimed {step} passed but its evidence dir "
                f"{state['passed'][step]} does not exist — retrying it")
            del state["passed"][step]
    return state


def save_state(path: str, state: dict) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    to_disk = dict(state)
    to_disk["passed"] = {
        step: (os.path.relpath(out_dir, REPO)
               if out_dir.startswith(REPO + os.sep) else out_dir)
        for step, out_dir in state["passed"].items()}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(to_disk, f, indent=2)
    os.replace(tmp, path)


def log(msg: str) -> None:
    now = datetime.datetime.now(datetime.timezone.utc).strftime("%H:%M:%S")
    print(f"[{now}] {msg}", flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=600,
                    help="seconds between probes while the tunnel is dead")
    ap.add_argument("--budget-hours", type=float, default=10)
    ap.add_argument("--state", default=os.path.join(
        REPO, "docs", "chip_runs", "watch_state.json"))
    ap.add_argument("--steps", default=",".join(ALL_STEPS),
                    help="comma-separated steps this watcher is after")
    ap.add_argument("--max-step-failures", type=int, default=3,
                    help="consecutive live-tunnel failures before a step "
                         "is given up on")
    args = ap.parse_args(argv)

    deadline = time.monotonic() + args.budget_hours * 3600
    wanted = [s for s in args.steps.split(",") if s]
    unknown = set(wanted) - set(ALL_STEPS)
    if unknown:
        ap.error(f"unknown step(s) {sorted(unknown)}; "
                 f"known: {list(ALL_STEPS)}")
    state = load_state(args.state)
    # consecutive ON-TPU failures per step: a step that fails
    # deterministically on a live tunnel (a real test failure, not a flap)
    # must not be retried in a tight loop for the whole budget
    fails: dict[str, int] = {}

    while True:
        pending = [s for s in wanted
                   if s not in state["passed"]
                   and fails.get(s, 0) < args.max_step_failures]
        given_up = [s for s in wanted if s not in state["passed"]
                    and s not in pending]
        if not pending:
            log(f"done: passed={json.dumps(state['passed'])} "
                f"given_up={given_up}")
            return 0 if not given_up else 1
        if time.monotonic() > deadline:
            log(f"budget exhausted; still pending: {pending}")
            return 1

        status = probe_tunnel()
        if status == "tpu":
            stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
                "%Y%m%dT%H%M%SZ")
            out_dir = os.path.join(REPO, "docs", "chip_runs", stamp)
            log(f"tunnel ALIVE; running agenda steps {pending} -> {out_dir}")
            # the agenda enforces per-step timeouts and process-group
            # kills; cap the whole run anyway (with headroom for per-step
            # startup overhead) so one wedged step cannot outlive the
            # watcher's budget — and kill the agenda's whole process GROUP
            # on expiry, or the in-flight step would survive as an orphan
            # holding the TPU for the rest of the window
            cap = sum(STEP_TIMEOUTS[s] for s in pending) + 600
            p = subprocess.Popen(
                [sys.executable, "-m", "picotron_tpu.tools.chip_agenda",
                 out_dir, "--only", ",".join(pending)],
                cwd=REPO, start_new_session=True)
            try:
                p.wait(timeout=cap)
            except subprocess.TimeoutExpired:
                # SIGTERM first: the agenda's handler forwards a SIGKILL to
                # its in-flight step's process group (each step runs in its
                # own session, so killing only the agenda would orphan the
                # step — and an orphan holds the TPU for the whole window)
                import signal
                p.terminate()
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    try:
                        os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):
                        p.kill()
                    p.wait()
                    # hard kill bypassed the agenda's handler: reap its
                    # in-flight step via the pgid breadcrumb run_step keeps
                    try:
                        with open(os.path.join(
                                out_dir, "current_step.pgid")) as pf:
                            pgid = int(pf.read().strip())
                        os.killpg(pgid, signal.SIGKILL)
                        log(f"orphaned step group {pgid} killed")
                    except (OSError, ValueError, ProcessLookupError,
                            PermissionError):
                        pass
                log("agenda run exceeded its global cap; terminated")
            progressed = False
            failed_steps = []
            derived_failed = []
            try:
                with open(os.path.join(out_dir, "summary.json")) as f:
                    for r in json.load(f):
                        if r["step"] not in ALL_STEPS:
                            # derived steps (profile_analysis) are not in
                            # the agenda's step set: they must neither be
                            # marked passed (a name that can never be
                            # pending) nor accrue strikes — but a failed
                            # one is worth a chip-free retry below
                            if r["rc"] != 0:
                                derived_failed.append(r["step"])
                            continue
                        if step_captured(r["step"], r["rc"],
                                         r.get("log", "")):
                            state["passed"][r["step"]] = out_dir
                            fails.pop(r["step"], None)
                            progressed = True
                        else:
                            failed_steps.append(
                                (r["step"], r["rc"], r.get("log", "")))
            except (OSError, ValueError) as e:
                log(f"no readable summary from {out_dir}: {e}")
            if "profile_analysis" in derived_failed:
                # the trace is already on disk and the analysis is pure
                # xplane.pb parsing — recover it here instead of leaving
                # the artifact to a documented manual rerun; profile's own
                # passed status is unaffected either way (the trace IS the
                # chip evidence)
                log("profile_analysis failed in-agenda; retrying chip-free")
                try:
                    with open(os.path.join(
                            out_dir, "profile_analysis_retry.log"),
                            "w") as lf:
                        r2 = subprocess.run(
                            [sys.executable, "-m",
                             "picotron_tpu.tools.analyze_trace",
                             os.path.join(out_dir, "profile")],
                            cwd=REPO, stdout=lf, stderr=subprocess.STDOUT,
                            timeout=300)
                    log(f"chip-free profile_analysis rc={r2.returncode}")
                except (OSError, subprocess.TimeoutExpired) as e:
                    log(f"chip-free profile_analysis retry failed: {e}")
            if failed_steps:
                # Strikes are for DETERMINISTIC failures: a step that
                # exited rc!=0, or a bench whose rc==0 null artifact
                # blames the inner code (crash, not hang). A step that
                # died to a flap, or a bench that diagnosed its own infra
                # problem (hangs, EX_INFRA bail-outs, dead probes), stays
                # pending strike-free — the whole point is retrying those
                # in a later, healthier window. Strikes only count when
                # the tunnel is still alive right after the run: a
                # deterministic failure keeps failing on a live tunnel,
                # a flap shows up as probe=dead here.
                # Two strikeable classes: rc!=0 steps, and benches whose
                # rc==0 null artifact was stamped code_failure by their
                # orchestrator. BOTH stay probe-gated: orchestrate's
                # infra-signature blocklist is necessarily incomplete
                # (an unlisted transport error from a mid-run tunnel
                # death still stamps code_failure), and a wrong strike
                # permanently gives the step up while a delayed one only
                # costs a retry window. Soft failures (diagnosed infra)
                # never strike — retrying them in a healthier window is
                # the watcher's whole point.
                hard = [s for s, rc, lp in failed_steps
                        if rc != 0 or null_artifact_blames_code(lp)]
                soft = [s for s, rc, lp in failed_steps if s not in hard]
                if hard and probe_tunnel() == "tpu":
                    for s in hard:
                        fails[s] = fails.get(s, 0) + 1
                    log(f"deterministic failures on a live tunnel: "
                        f"{ {s: fails[s] for s in hard} }")
                elif hard:
                    log(f"steps {hard} failed but tunnel is down — "
                        f"counting as a flap, no strike")
                if soft:
                    log(f"steps {soft} produced no evidence (flap/infra) "
                        f"— no strike, still pending")
            save_state(args.state, state)
            if progressed:
                continue  # re-probe immediately: momentum, use the window
            # no step passed: tunnel flapped mid-run or the steps are
            # failing for real — wait a beat instead of hammering
        else:
            log(f"tunnel {status} (pending: {pending})")
        log(f"sleeping {args.interval:.0f}s")
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
