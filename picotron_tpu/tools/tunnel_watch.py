"""Session-long TPU-tunnel watcher: turn ANY live window into evidence.

Rounds 3-4 lost their official numbers to a tunnel that dies for hours
and revives without notice; chip_agenda converts one live window into
artifacts, but someone still has to be watching when the window opens.
This tool IS that someone: it probes the tunnel on an interval (with a
killable child — the axon client blocks forever inside backend init on a
dead tunnel) and, whenever the probe sees a TPU, runs the agenda steps
that have not yet succeeded (``chip_agenda --only <pending>``). Steps
that pass are never re-run; the watcher exits 0 the moment every step
has passed, or 1 when the time budget runs out.

    python -m picotron_tpu.tools.tunnel_watch [--interval 600]
        [--budget-hours 10] [--state docs/chip_runs/watch_state.json]

State (which steps have passed, where their artifacts live) persists to
a JSON file, so a restarted watcher — or a later round — resumes instead
of repeating captured evidence. Nothing in this process ever imports
jax: probing and work both happen in killable children, so the watcher
itself can never hang on the tunnel.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))

from picotron_tpu.tools.chip_agenda import STEP_TIMEOUTS  # noqa: E402

ALL_STEPS = tuple(STEP_TIMEOUTS)


def probe_tunnel(timeout: float = 90.0) -> str:
    """'tpu' | 'cpu' | 'dead' — same contract as bench.probe_tunnel
    (bench.py:211), duplicated here so the watcher stays import-light."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices()[0]; "
             "print(d.platform, d.device_kind)"],
            capture_output=True, text=True, timeout=timeout, cwd=REPO)
        if r.returncode != 0:
            return "dead"
        return "tpu" if "tpu" in r.stdout.lower() else "cpu"
    except subprocess.TimeoutExpired:
        return "dead"


def load_state(path: str) -> dict:
    try:
        with open(path) as f:
            state = json.load(f)
    except (OSError, ValueError):
        state = {}
    if not isinstance(state, dict) or not isinstance(
            state.get("passed"), dict):
        state = {"passed": {}}
    return state


def save_state(path: str, state: dict) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=2)
    os.replace(tmp, path)


def log(msg: str) -> None:
    now = datetime.datetime.now(datetime.timezone.utc).strftime("%H:%M:%S")
    print(f"[{now}] {msg}", flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=600,
                    help="seconds between probes while the tunnel is dead")
    ap.add_argument("--budget-hours", type=float, default=10)
    ap.add_argument("--state", default=os.path.join(
        REPO, "docs", "chip_runs", "watch_state.json"))
    ap.add_argument("--steps", default=",".join(ALL_STEPS),
                    help="comma-separated steps this watcher is after")
    ap.add_argument("--max-step-failures", type=int, default=3,
                    help="consecutive live-tunnel failures before a step "
                         "is given up on")
    args = ap.parse_args(argv)

    deadline = time.monotonic() + args.budget_hours * 3600
    wanted = [s for s in args.steps.split(",") if s]
    unknown = set(wanted) - set(ALL_STEPS)
    if unknown:
        ap.error(f"unknown step(s) {sorted(unknown)}; "
                 f"known: {list(ALL_STEPS)}")
    state = load_state(args.state)
    # consecutive ON-TPU failures per step: a step that fails
    # deterministically on a live tunnel (a real test failure, not a flap)
    # must not be retried in a tight loop for the whole budget
    fails: dict[str, int] = {}

    while True:
        pending = [s for s in wanted
                   if s not in state["passed"]
                   and fails.get(s, 0) < args.max_step_failures]
        given_up = [s for s in wanted if s not in state["passed"]
                    and s not in pending]
        if not pending:
            log(f"done: passed={json.dumps(state['passed'])} "
                f"given_up={given_up}")
            return 0 if not given_up else 1
        if time.monotonic() > deadline:
            log(f"budget exhausted; still pending: {pending}")
            return 1

        status = probe_tunnel()
        if status == "tpu":
            stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
                "%Y%m%dT%H%M%SZ")
            out_dir = os.path.join(REPO, "docs", "chip_runs", stamp)
            log(f"tunnel ALIVE; running agenda steps {pending} -> {out_dir}")
            # the agenda enforces per-step timeouts and process-group
            # kills; cap the whole run anyway (with headroom for per-step
            # startup overhead) so one wedged step cannot outlive the
            # watcher's budget — and kill the agenda's whole process GROUP
            # on expiry, or the in-flight step would survive as an orphan
            # holding the TPU for the rest of the window
            cap = sum(STEP_TIMEOUTS[s] for s in pending) + 600
            p = subprocess.Popen(
                [sys.executable, "-m", "picotron_tpu.tools.chip_agenda",
                 out_dir, "--only", ",".join(pending)],
                cwd=REPO, start_new_session=True)
            try:
                p.wait(timeout=cap)
            except subprocess.TimeoutExpired:
                # SIGTERM first: the agenda's handler forwards a SIGKILL to
                # its in-flight step's process group (each step runs in its
                # own session, so killing only the agenda would orphan the
                # step — and an orphan holds the TPU for the whole window)
                import signal
                p.terminate()
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    try:
                        os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):
                        p.kill()
                    p.wait()
                    # hard kill bypassed the agenda's handler: reap its
                    # in-flight step via the pgid breadcrumb run_step keeps
                    try:
                        with open(os.path.join(
                                out_dir, "current_step.pgid")) as pf:
                            pgid = int(pf.read().strip())
                        os.killpg(pgid, signal.SIGKILL)
                        log(f"orphaned step group {pgid} killed")
                    except (OSError, ValueError, ProcessLookupError,
                            PermissionError):
                        pass
                log("agenda run exceeded its global cap; terminated")
            progressed = False
            failed_steps = []
            try:
                with open(os.path.join(out_dir, "summary.json")) as f:
                    for r in json.load(f):
                        if r["rc"] == 0:
                            state["passed"][r["step"]] = out_dir
                            fails.pop(r["step"], None)
                            progressed = True
                        else:
                            failed_steps.append(r["step"])
            except (OSError, ValueError) as e:
                log(f"no readable summary from {out_dir}: {e}")
            if failed_steps:
                # a step that died because the tunnel flapped mid-run is
                # NOT a real failure — only count strikes when the tunnel
                # is still alive right after the run (a deterministic
                # on-TPU failure keeps failing on a live tunnel; a flap
                # shows up as probe=dead here and costs no strike)
                if probe_tunnel() == "tpu":
                    for s in failed_steps:
                        fails[s] = fails.get(s, 0) + 1
                    log(f"failed on live tunnel: "
                        f"{ {s: fails[s] for s in failed_steps} }")
                else:
                    log(f"steps {failed_steps} failed but tunnel is down "
                        f"— counting as a flap, no strike")
            save_state(args.state, state)
            if progressed:
                continue  # re-probe immediately: momentum, use the window
            # no step passed: tunnel flapped mid-run or the steps are
            # failing for real — wait a beat instead of hammering
        else:
            log(f"tunnel {status} (pending: {pending})")
        log(f"sleeping {args.interval:.0f}s")
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
