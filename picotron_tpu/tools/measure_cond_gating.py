"""Measure what ``lax.cond`` stage gating actually costs on this chip.

The pipeline engines gate embedding and LM-head/loss to their owning
stage with ``lax.cond`` (models/llama.py:_stage_input/_stage_loss); the
CPU test path masks with compute-both ``jnp.where`` instead, and
docs/PP_COST.md's interleaved FLOP guardrail therefore carries the caveat
that "cond gating makes the masked work free on TPU" had never been
measured on hardware (round-3 VERDICT, weak #3). This tool measures it:
for the real SmolLM-geometry loss and embedding computations it times

  - ``cond(True)``  — the owning stage's cost,
  - ``cond(False)`` — what every OTHER stage pays under gating,
  - ``where``       — what every other stage would pay compute-both,

on a 1-device ('dp','pp','cp','tp') mesh so the exact production code
path (tp_copy / fused linear+CE / vocab-parallel embed) runs unmodified.
The predicate is a device scalar, so XLA compiles a true runtime
conditional — nothing constant-folds.

Usage:
    python -m picotron_tpu.tools.measure_cond_gating [--small]

Prints a table plus one JSON line for the round record.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time

import jax
import jax.numpy as jnp
from jax import lax

from picotron_tpu.config import Config, ModelConfig
from picotron_tpu.models import llama
from picotron_tpu.topology import build_topology
from picotron_tpu.utils import honor_cpu_env_pin
from picotron_tpu.utils import shard_map as shard_map_compat

P = jax.sharding.PartitionSpec


def _time(fn, *args, warmup=3, iters=20):
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append((time.perf_counter() - t0) * 1e3)
    return statistics.median(ts)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="tiny shapes (CPU smoke / CI)")
    args = ap.parse_args(argv)
    honor_cpu_env_pin()  # JAX_PLATFORMS=cpu must beat the axon site pin

    if args.small:
        m = ModelConfig(hidden_size=64, num_attention_heads=4,
                        num_key_value_heads=4, intermediate_size=128,
                        num_hidden_layers=2, vocab_size=256,
                        max_position_embeddings=128, dtype="float32")
        b, s = 2, 64
    else:
        # SmolLM-1.7B loss/embed geometry at the bench's microbatch
        m = ModelConfig(hidden_size=2048, num_attention_heads=32,
                        num_key_value_heads=32, intermediate_size=8192,
                        num_hidden_layers=2, vocab_size=49152,
                        max_position_embeddings=2048, dtype="bfloat16")
        b, s = 4, 2048
    cfg = Config(model=m)
    cfg.training.seq_length = s
    dt = jnp.dtype(m.dtype)

    topo = build_topology(1, 1, 1, 1)
    key = jax.random.PRNGKey(0)
    kh, ke, kn, kl, kt = jax.random.split(key, 5)
    h = jax.random.normal(kh, (b, s, m.hidden_size), dt)
    params = {
        "embed": jax.random.normal(ke, (m.vocab_size, m.hidden_size), dt)
        * 0.02,
        "final_norm": jnp.ones((m.hidden_size,), dt),
        "lm_head": jax.random.normal(kl, (m.hidden_size, m.vocab_size), dt)
        * 0.02,
    }
    tokens = jax.random.randint(kt, (b, s), 0, m.vocab_size)
    targets = jax.random.randint(kn, (b, s), 0, m.vocab_size)

    def loss_cond(pred, params, h, targets):
        return lax.cond(
            pred,
            lambda: llama.loss_from_hidden(params, h, targets, cfg),
            lambda: jnp.zeros((), jnp.float32))

    def loss_where(pred, params, h, targets):
        return jnp.where(pred,
                         llama.loss_from_hidden(params, h, targets, cfg),
                         0.0)

    def embed_cond(pred, params, tokens, h_recv):
        return lax.cond(
            pred,
            lambda: llama.embed_lookup(params["embed"], tokens).astype(dt),
            lambda: h_recv)

    def embed_where(pred, params, tokens, h_recv):
        emb = llama.embed_lookup(params["embed"], tokens).astype(dt)
        return jnp.where(pred, emb, h_recv)

    def shard(fn):
        return jax.jit(shard_map_compat(
            fn, mesh=topo.mesh,
            in_specs=(P(), P(), P(), P()), out_specs=P(),
            check_vma=False))

    t = jnp.array(True)
    f = jnp.array(False)
    rows = {}
    for name, fn, extra in [
        ("loss", shard(loss_cond), (params, h, targets)),
        ("loss_where", shard(loss_where), (params, h, targets)),
        ("embed", shard(embed_cond), (params, tokens, h)),
        ("embed_where", shard(embed_where), (params, tokens, h)),
    ]:
        rows[name + "_true"] = _time(fn, t, *extra)
        rows[name + "_false"] = _time(fn, f, *extra)

    plat = jax.devices()[0].platform
    print(f"# cond-gating cost, platform={plat} b={b} s={s} "
          f"hidden={m.hidden_size} vocab={m.vocab_size} dtype={m.dtype}")
    print(f"{'path':<24}{'pred=True ms':>14}{'pred=False ms':>15}")
    for k in ("loss", "loss_where", "embed", "embed_where"):
        print(f"{k:<24}{rows[k + '_true']:>14.3f}{rows[k + '_false']:>15.3f}")
    # The claim under test: cond(False) << where(False) (the compute-both
    # cost every non-owning stage would pay without gating).
    summary = {
        "platform": plat,
        "loss_owner_ms": round(rows["loss_true"], 3),
        "loss_gated_other_ms": round(rows["loss_false"], 3),
        "loss_maskedboth_other_ms": round(rows["loss_where_false"], 3),
        "embed_owner_ms": round(rows["embed_true"], 3),
        "embed_gated_other_ms": round(rows["embed_false"], 3),
        "embed_maskedboth_other_ms": round(rows["embed_where_false"], 3),
    }
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
