"""Device topology: one named mesh instead of torch process groups.

The reference builds a 4D rank grid ``torch.arange(world).view(dp, pp, cp, tp)``
and six process subgroups from it, held in a module-global singleton
(reference picotron/process_group_manager.py:5-68). On TPU the whole object
collapses into a single ``jax.sharding.Mesh`` with axes ``('dp','pp','cp','tp')``
— tp fastest-varying so tensor-parallel neighbors sit on adjacent devices
(innermost ICI), dp outermost (DCN), mirroring process_group_manager.py:13.
Subgroups need no construction: a collective over axis name 'tp' *is* the tp
group; the fused cp×dp group (process_group_manager.py:20) is just
``('cp','dp')``. Ring neighbors (cp_send_rank/pp_next_rank, :43-53) become
``lax.ppermute`` permutations, and the is_first/is_last-stage flags become
``lax.axis_index('pp') == 0 / pp-1`` inside ``shard_map``.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MESH_AXES = ("dp", "pp", "cp", "tp")


@dataclass(frozen=True)
class Topology:
    """Static topology facts + the mesh. The queryable surface of the
    reference's ProcessGroupManager, minus anything that needs communication."""

    mesh: Mesh
    dp_size: int
    pp_size: int
    cp_size: int
    tp_size: int

    @property
    def world_size(self) -> int:
        return self.dp_size * self.pp_size * self.cp_size * self.tp_size

    # Collective "groups" are just axis-name tuples.
    GRAD_SYNC_AXES = ("dp", "cp")  # the fused cp_dp group of data_parallel.py:47,83
    LOSS_AXES = ("dp", "cp")  # loss averaging group (utils.py:93-98)


def build_topology(dp: int, pp: int, cp: int, tp: int, devices=None) -> Topology:
    """Create the named mesh over the first dp*pp*cp*tp devices.

    Row-major reshape puts tp on the fastest axis — same device adjacency as
    the reference grid (process_group_manager.py:13).
    """
    world = dp * pp * cp * tp
    if devices is None:
        devices = jax.devices()
    if len(devices) < world:
        raise ValueError(
            f"topology dp={dp} pp={pp} cp={cp} tp={tp} needs {world} devices, "
            f"have {len(devices)}"
        )
    grid = np.asarray(devices[:world]).reshape(dp, pp, cp, tp)
    mesh = Mesh(grid, MESH_AXES)
    return Topology(mesh=mesh, dp_size=dp, pp_size=pp, cp_size=cp, tp_size=tp)


def topology_from_config(cfg, devices=None) -> Topology:
    d = cfg.distributed
    return build_topology(d.dp_size, d.pp_size, d.cp_size, d.tp_size, devices=devices)


def named_shardings(topo: Topology, pspecs):
    """Map a PartitionSpec pytree to NamedShardings on this topology's mesh."""
    return jax.tree.map(
        lambda s: NamedSharding(topo.mesh, s), pspecs,
        is_leaf=lambda x: isinstance(x, P),
    )


def batch_pspec() -> P:
    """Batch arrays are (microbatch, batch, seq): batch sharded over dp,
    sequence over cp — the contiguous CP chunking the reference dataloader
    does per-rank in collate (data.py:102-116) becomes a sharding."""
    return P(None, "dp", "cp")
