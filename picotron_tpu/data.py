"""Data pipeline: tokenize, pack, shard.

Re-design of the reference's MicroBatchDataLoader (picotron/data.py): HF
dataset load + tokenizer, pack token stream into seq_length+1 chunks
(data.py:57-100), dp-sharded sampling with interleaved assignment
(DistributedSampler semantics, shuffle=False, data.py:40-45), infinite
iterator bumping the epoch on wrap (data.py:118-137). Differences that fall
out of single-controller JAX:

- the loader yields the *global* batch [grad_acc, mbs*dp, seq]; the dp split
  and the per-rank contiguous CP sequence slice (reference collate,
  data.py:102-116) happen by sharding the array (None,'dp','cp') rather than
  by per-process slicing — same math, zero data movement code;
- no tokenizer broadcast (data.py:23-32): there is one process;
- a built-in "synthetic" source (deterministic affine-chain token stream)
  because TPU test environments are often offline; any HF dataset path works
  when the hub is reachable.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from picotron_tpu import native
from picotron_tpu.config import Config


def synthetic_corpus(vocab_size: int, length: int, seed: int) -> np.ndarray:
    """Deterministic, learnable token stream: a noisy affine bigram chain
    (next = a*t + b mod V, with occasional random jumps) so loss curves fall
    measurably below ln(V) once the model learns the transitions.

    All random draws come from numpy's PCG64; only the loop-carried
    recurrence runs in the native kernel when available, so the native and
    Python paths are bitwise identical."""
    rng = np.random.default_rng(seed)
    a = int(rng.integers(1, vocab_size))
    b = int(rng.integers(0, vocab_size))
    toks = np.empty(length, dtype=np.int32)
    toks[0] = rng.integers(0, vocab_size)
    jumps = rng.random(length) < 0.05
    # NOTE: int64 draw (numpy's default) — Generator.integers consumes a
    # different stream per dtype, and the corpus for a given seed is part of
    # the resume/baseline contract.
    jump_vals = rng.integers(0, vocab_size, length)
    if native.available():
        native.affine_chain(toks, jumps.view(np.uint8), jump_vals,
                            a, b, vocab_size)
    else:
        for i in range(1, length):
            toks[i] = (jump_vals[i] if jumps[i]
                       else (a * int(toks[i - 1]) + b) % vocab_size)
    return toks


def _pack(stream: np.ndarray, chunk: int) -> np.ndarray:
    n = len(stream) // chunk
    return stream[: n * chunk].reshape(n, chunk)


class _ArrowSamples:
    """Packed rows backed by the datasets arrow cache (disk-mapped): a
    corpus above dataset.max_in_memory_tokens never materializes in host
    RAM — __next__ gathers only the current batch's rows. The reference
    also keeps its grouped dataset arrow-backed (through the torch
    DataLoader, picotron/data.py:57-100) — the parity is the storage
    strategy only; the packing stride itself deviates (see
    ``_load_hf_samples``'s group comment)."""

    def __init__(self, ds):
        self._ds = ds.with_format("numpy", columns=["ids"])
        # the packed "ids" column as one arrow ChunkedArray of fixed-width
        # list rows — gather() runs a single `take` over it instead of a
        # per-row python fetch (bitwise-pinned against the per-row path by
        # tests/test_hf_data.py)
        self._ids = self._ds.data.column("ids")

    def __len__(self) -> int:
        return len(self._ds)

    def gather(self, idx: np.ndarray) -> np.ndarray:
        import pyarrow as pa

        idx = np.ascontiguousarray(np.asarray(idx, dtype=np.int64))
        rows = self._ids.take(pa.array(idx)).combine_chunks()
        flat = rows.flatten().to_numpy(zero_copy_only=False)
        return np.asarray(flat, dtype=np.int32).reshape(len(idx), -1)

    def _gather_per_row(self, idx: np.ndarray) -> np.ndarray:
        """Reference per-row fetch; kept as the equality oracle for the
        batched arrow `take` above."""
        rows = self._ds[[int(i) for i in idx]]["ids"]
        return np.asarray(rows, dtype=np.int32)


class MicroBatchDataLoader:
    """Yields {'input_ids','target_ids'}: int32 [grad_acc, mbs*dp, seq_length]."""

    def __init__(self, cfg: Config, tokenizer=None):
        t, d = cfg.training, cfg.distributed
        self.seq_length = t.seq_length
        self.micro_batch_size = t.micro_batch_size
        self.grad_acc = t.gradient_accumulation_steps
        self.dp_size = d.dp_size
        self.global_batch_size = cfg.global_batch_size  # mbs*acc*dp (data.py:17)
        self.rows_per_step = t.micro_batch_size * d.dp_size
        self.tokenizer = tokenizer

        # samples: [n, seq_length+1] rows so input/target are shifted views
        # (reference data.py:88-96) — a host numpy array, or an arrow-backed
        # _ArrowSamples for corpora above dataset.max_in_memory_tokens
        if cfg.dataset.name == "synthetic":
            stream = synthetic_corpus(
                cfg.model.vocab_size,
                max(2_000_000, 64 * self.rows_per_step * (t.seq_length + 1)),
                cfg.training.seed,
            )
            self.samples = _pack(stream, self.seq_length + 1)
            if t.num_samples:
                # the reference subsets raw documents pre-tokenization
                # (data.py:34-35); the synthetic stream has no documents, so
                # the "first N examples" contract applies to packed samples
                self.samples = self.samples[: t.num_samples]
        else:
            self.samples = self._load_hf_samples(
                cfg, tokenizer, self.seq_length + 1)
        if len(self.samples) < self.rows_per_step:
            raise ValueError("dataset too small for one global batch")
        self._epoch = 0
        self._cursor = 0
        # DistributedSampler(shuffle=False) hands sample i to dp rank i % dp
        # (reference data.py:40-45); row-major [dp, mbs] layout after this
        # permutation puts each rank's rows contiguous for the 'dp' sharding.
        perm = (np.arange(self.rows_per_step)
                .reshape(self.micro_batch_size, self.dp_size).T.reshape(-1))
        self._batch_offsets = (
            np.arange(self.grad_acc, dtype=np.int64)[:, None] * self.rows_per_step
            + perm[None, :]).reshape(-1)
        # Zigzag CP: permute the sequence axis so that the contiguous 'cp'
        # shard of the permuted sequence owns original chunks (r, 2n-1-r)
        # (parallel/cp.py::zigzag_perm). Loss is a token mean, so the
        # permutation is training-invariant.
        self._seq_perm = None
        if d.cp_zigzag and d.cp_size > 1:
            from picotron_tpu.parallel.cp import zigzag_perm

            self._seq_perm = zigzag_perm(t.seq_length, d.cp_size)

    @staticmethod
    def _load_hf_samples(cfg: Config, tokenizer, chunk: int):
        """Tokenize and pack an HF dataset into [n, chunk] rows WITHOUT ever
        holding the whole corpus in host RAM: both the tokenize and the
        group step run as batched ``datasets.map`` passes, which stream
        batch-by-batch through the arrow cache on disk. Small corpora
        (<= dataset.max_in_memory_tokens) materialize to one numpy array at
        the end (fastest gathers); larger ones stay arrow-backed."""
        import datasets  # deferred: offline environments use "synthetic"

        if tokenizer is None:
            from transformers import AutoTokenizer

            tokenizer = AutoTokenizer.from_pretrained(cfg.model.name)
        name = cfg.dataset.name
        if name.endswith((".json", ".jsonl", ".txt", ".csv")):
            # local files work air-gapped: dataset.name is a path (or glob)
            fmt = {"jsonl": "json", "txt": "text"}.get(
                name.rsplit(".", 1)[-1], name.rsplit(".", 1)[-1])
            ds = datasets.load_dataset(fmt, data_files=name,
                                       split=cfg.dataset.split)
        else:
            ds = datasets.load_dataset(
                name, cfg.dataset.subset_name, split=cfg.dataset.split)
        if cfg.training.num_samples:
            # first-N raw documents, pre-tokenization (reference
            # data.py:34-35: select(range(min(N, len))))
            ds = ds.select(range(min(cfg.training.num_samples, len(ds))))
        col = cfg.dataset.text_column

        def tok(batch):
            return {"ids": tokenizer(batch[col])["input_ids"]}

        ds = ds.map(tok, batched=True, num_proc=max(cfg.dataset.num_proc, 1),
                    remove_columns=ds.column_names)

        # Group into fixed-length rows INSIDE the arrow cache: each map
        # batch concatenates its documents and emits len//chunk rows,
        # dropping the per-batch remainder. NOT the reference's grouping
        # contract: this packs NON-OVERLAPPING seq_length+1 chunks, while
        # the reference's tokenizer_group_text packs OVERLAPPING windows
        # (stride seq_length over seq_length+1-token rows, adjacent rows
        # sharing one boundary token, reference data.py:70-75). Row
        # counts, token alignment, and per-epoch sample identity therefore
        # ALL differ from upstream for the same corpus/num_samples — a
        # deliberate deviation (no token is trained on twice per epoch),
        # not a parity claim (ADVICE.md round 5).
        def group(batch):
            parts = [np.asarray(x, np.int32) for x in batch["ids"]]
            ids = (np.concatenate(parts) if parts
                   else np.zeros(0, np.int32))
            n = len(ids) // chunk
            return {"ids": ids[: n * chunk].reshape(n, chunk)}

        ds = ds.map(group, batched=True, batch_size=1000,
                    num_proc=max(cfg.dataset.num_proc, 1),
                    remove_columns=ds.column_names)
        if len(ds) * chunk <= cfg.dataset.max_in_memory_tokens:
            return np.asarray(ds.with_format("numpy")["ids"], np.int32)
        return _ArrowSamples(ds)

    def skip_steps(self, n_steps: int) -> None:
        """Advance the cursor past n_steps global batches (resume support: the
        reference replays the dataset from the top after resume since only
        step/tokens are checkpointed, train.py:214-215; skipping is strictly
        better and costs an index update)."""
        total = n_steps * self.grad_acc * self.rows_per_step
        wraps, self._cursor = divmod(self._cursor + total, len(self.samples))
        self._epoch += wraps

    def seek_steps(self, n_steps: int) -> None:
        """Position the cursor ABSOLUTELY at the start of global batch
        ``n_steps`` (rollback support: a resumed-from-checkpoint run must
        replay the exact batches the rolled-back steps consumed)."""
        self._cursor = 0
        self._epoch = 0
        self.skip_steps(n_steps)

    def state_meta(self, step: int) -> dict:
        """Position + geometry for checkpoint metadata: ``consumed_rows`` is
        the absolute sample-row position after ``step`` global batches, and
        the geometry fields are what that position was computed FROM — a
        resume whose config changed the batch geometry cannot silently
        continue on wrong data (see ``verify_resume``)."""
        return {
            "consumed_steps": int(step),
            "consumed_rows": int(step) * self.grad_acc * self.rows_per_step,
            "grad_acc": self.grad_acc,
            "rows_per_step": self.rows_per_step,
            "seq_length": self.seq_length,
            "num_samples": len(self.samples),
        }

    def verify_resume(self, saved: Optional[dict], step: int) -> None:
        """Assert the saved loader position against what ``skip_steps(step)``
        will reproduce under THIS config. Checkpoints predating the data
        metadata (saved is None) pass — geometry drift was undetectable for
        them anyway. Fails loudly on any mismatch: a changed micro-batch
        size, grad-accum, dp width, seq_length, or corpus size means the
        resumed run would train on different tokens than the original."""
        if not saved:
            return
        cur = self.state_meta(step)
        bad = {k: (saved[k], cur[k])
               for k in sorted(set(saved) & set(cur)) if saved[k] != cur[k]}
        if bad:
            detail = ", ".join(
                f"{k}: saved={s} now={n}" for k, (s, n) in bad.items())
            raise ValueError(
                f"checkpoint data-loader position does not match this "
                f"config ({detail}); the batch geometry changed between "
                f"save and resume — resuming would silently train on "
                f"different data. Restore under the saving run's geometry "
                f"or start a fresh run.")

    def __iter__(self) -> Iterator[dict]:
        return self

    def __next__(self) -> dict:
        """One global batch of consecutive samples, wrapping epochs
        (reference data.py:118-137), assembled by the native gather kernel
        when available (numpy fallback is bitwise identical)."""
        M, R = self.grad_acc, self.rows_per_step
        n = len(self.samples)
        abs_idx = (self._cursor + self._batch_offsets) % n
        wraps, self._cursor = divmod(self._cursor + M * R, n)
        self._epoch += wraps
        if isinstance(self.samples, np.ndarray) and native.available():
            inp, tgt = native.gather_batch(self.samples, abs_idx)
        else:
            rows = (self.samples[abs_idx]
                    if isinstance(self.samples, np.ndarray)
                    else self.samples.gather(abs_idx))
            inp = np.ascontiguousarray(rows[:, :-1])
            tgt = np.ascontiguousarray(rows[:, 1:])
        shape = (M, R, self.seq_length)
        inp, tgt = inp.reshape(shape), tgt.reshape(shape)
        if self._seq_perm is not None:
            inp = np.ascontiguousarray(inp[:, :, self._seq_perm])
            tgt = np.ascontiguousarray(tgt[:, :, self._seq_perm])
        return {"input_ids": inp, "target_ids": tgt}
