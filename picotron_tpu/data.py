"""Data pipeline: tokenize, pack, shard.

Re-design of the reference's MicroBatchDataLoader (picotron/data.py): HF
dataset load + tokenizer, pack token stream into seq_length+1 chunks
(data.py:57-100), dp-sharded sampling with interleaved assignment
(DistributedSampler semantics, shuffle=False, data.py:40-45), infinite
iterator bumping the epoch on wrap (data.py:118-137). Differences that fall
out of single-controller JAX:

- the loader yields the *global* batch [grad_acc, mbs*dp, seq]; the dp split
  and the per-rank contiguous CP sequence slice (reference collate,
  data.py:102-116) happen by sharding the array (None,'dp','cp') rather than
  by per-process slicing — same math, zero data movement code;
- no tokenizer broadcast (data.py:23-32): there is one process;
- a built-in "synthetic" source (deterministic affine-chain token stream)
  because TPU test environments are often offline; any HF dataset path works
  when the hub is reachable.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from picotron_tpu.config import Config


def synthetic_corpus(vocab_size: int, length: int, seed: int) -> np.ndarray:
    """Deterministic, learnable token stream: a noisy affine bigram chain
    (next = a*t + b mod V, with occasional random jumps) so loss curves fall
    measurably below ln(V) once the model learns the transitions."""
    rng = np.random.default_rng(seed)
    a = int(rng.integers(1, vocab_size))
    b = int(rng.integers(0, vocab_size))
    toks = np.empty(length, dtype=np.int32)
    toks[0] = rng.integers(0, vocab_size)
    jumps = rng.random(length) < 0.05
    jump_vals = rng.integers(0, vocab_size, length)
    for i in range(1, length):
        toks[i] = jump_vals[i] if jumps[i] else (a * int(toks[i - 1]) + b) % vocab_size
    return toks


def _pack(stream: np.ndarray, chunk: int) -> np.ndarray:
    n = len(stream) // chunk
    return stream[: n * chunk].reshape(n, chunk)


class MicroBatchDataLoader:
    """Yields {'input_ids','target_ids'}: int32 [grad_acc, mbs*dp, seq_length]."""

    def __init__(self, cfg: Config, tokenizer=None):
        t, d = cfg.training, cfg.distributed
        self.seq_length = t.seq_length
        self.micro_batch_size = t.micro_batch_size
        self.grad_acc = t.gradient_accumulation_steps
        self.dp_size = d.dp_size
        self.global_batch_size = cfg.global_batch_size  # mbs*acc*dp (data.py:17)
        self.rows_per_step = t.micro_batch_size * d.dp_size
        self.tokenizer = tokenizer

        if cfg.dataset.name == "synthetic":
            stream = synthetic_corpus(
                cfg.model.vocab_size,
                max(2_000_000, 64 * self.rows_per_step * (t.seq_length + 1)),
                cfg.training.seed,
            )
        else:
            stream = self._load_hf_stream(cfg, tokenizer)
        # pack into seq_length+1 so input/target are shifted views
        # (reference data.py:88-96)
        self.samples = _pack(stream, self.seq_length + 1)
        if len(self.samples) < self.rows_per_step:
            raise ValueError("dataset too small for one global batch")
        self._epoch = 0
        self._cursor = 0

    @staticmethod
    def _load_hf_stream(cfg: Config, tokenizer) -> np.ndarray:
        import datasets  # deferred: offline environments use "synthetic"

        if tokenizer is None:
            from transformers import AutoTokenizer

            tokenizer = AutoTokenizer.from_pretrained(cfg.model.name)
        ds = datasets.load_dataset(
            cfg.dataset.name, cfg.dataset.subset_name, split=cfg.dataset.split
        )
        col = cfg.dataset.text_column

        def tok(batch):
            return {"ids": tokenizer(batch[col])["input_ids"]}

        ds = ds.map(tok, batched=True, num_proc=max(cfg.dataset.num_proc, 1),
                    remove_columns=ds.column_names)
        return np.concatenate([np.asarray(x, np.int32) for x in ds["ids"]])

    def skip_steps(self, n_steps: int) -> None:
        """Advance the cursor past n_steps global batches (resume support: the
        reference replays the dataset from the top after resume since only
        step/tokens are checkpointed, train.py:214-215; skipping is strictly
        better and costs an index update)."""
        total = n_steps * self.grad_acc * self.rows_per_step
        wraps, self._cursor = divmod(self._cursor + total, len(self.samples))
        self._epoch += wraps

    def __iter__(self) -> Iterator[dict]:
        return self

    def _next_rows(self, n: int) -> np.ndarray:
        """n consecutive global samples, wrapping epochs (data.py:118-137)."""
        out = []
        while n > 0:
            take = min(n, len(self.samples) - self._cursor)
            out.append(self.samples[self._cursor : self._cursor + take])
            self._cursor += take
            n -= take
            if self._cursor == len(self.samples):
                self._cursor = 0
                self._epoch += 1
        return np.concatenate(out, 0)

    def __next__(self) -> dict:
        M, R = self.grad_acc, self.rows_per_step
        rows = self._next_rows(M * R)
        # DistributedSampler(shuffle=False) hands sample i to dp rank i % dp
        # (data.py:40-45); row-major [dp, mbs] layout after this gather puts
        # each rank's rows contiguous for the 'dp' sharding.
        rows = rows.reshape(M, R, self.seq_length + 1)
        idx = np.arange(R).reshape(self.micro_batch_size, self.dp_size).T.reshape(-1)
        rows = rows[:, idx]
        return {
            "input_ids": np.ascontiguousarray(rows[:, :, :-1]),
            "target_ids": np.ascontiguousarray(rows[:, :, 1:]),
        }
