"""Experiment configuration.

One JSON file per experiment, schema mirroring the reference's
``template/base_config.json:1-52`` (sections: distributed / model / training /
dataset / checkpoint / logging). The reference's second, implicit config
channel — environment variables like FLASH_ATTEN / CONTEXT_PARALLEL / DTYPE
(reference train.py:65-68, model.py:147) — is deliberately replaced by explicit
fields here (``model.attention_impl``, ``model.dtype``); SURVEY.md §5.6 calls
that channel an implementation wart, not a capability.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Optional


def parse_rank_at_step(name: str, spec: str) -> tuple[int, int]:
    """Parse a ``"RANK:STEP"`` pod-chaos spec (resilience
    ``chaos_*_rank_at_step`` fields) into ``(rank, step)``; "" (off) ->
    ``(-1, 0)``. Lives here rather than resilience/chaos.py so validate()
    stays importable without pulling in jax."""
    if not spec:
        return -1, 0
    rank_s, sep, step_s = spec.partition(":")
    try:
        if not sep:
            raise ValueError
        rank, step = int(rank_s), int(step_s)
        if rank < 0 or step < 1:
            raise ValueError
    except ValueError:
        raise ValueError(
            f'{name} must be "RANK:STEP" with RANK >= 0 and STEP >= 1 '
            f'(got {spec!r})') from None
    return rank, step


@dataclass
class DistributedConfig:
    """4D topology sizes. Grid ordering is (dp, pp, cp, tp), tp fastest-varying,
    mirroring the reference rank grid (process_group_manager.py:13) so that tp
    neighbors sit on the innermost ICI dimension and dp on the outermost."""

    tp_size: int = 1
    cp_size: int = 1
    pp_size: int = 1
    dp_size: int = 1
    pp_engine: str = "1f1b"  # "afab" | "1f1b"   (reference train.py:223-229)
    # Interleaved 1F1B (virtual pipeline stages, beyond the reference —
    # SURVEY §2.3 notes "no interleaved/virtual stages"): each device holds
    # pp_interleave non-contiguous model chunks and the schedule cycles
    # through them, shrinking the pipeline bubble by the interleave factor.
    # Requires pp_engine="1f1b", num_hidden_layers % (pp*v) == 0, and
    # gradient_accumulation_steps % pp == 0.
    pp_interleave: int = 1
    use_cpu: bool = False  # run on host CPU devices (reference gloo path, train.py:83)
    # Zigzag context-parallel layout: each cp rank owns sequence chunks
    # (r, 2n-1-r), balancing causal ring-attention work across ranks. False =
    # contiguous chunks, faithful to the reference (its zigzag TODO:
    # tests/test_dataloader.py:136).
    cp_zigzag: bool = False
    # Context-parallel algorithm: "ring" = ppermute K/V ring attention (the
    # reference's mode); "ulysses" = DeepSpeed-style all-to-all sequence
    # parallelism (beyond the reference, SURVEY §2.3): one all-to-all swaps
    # seq-sharding for head-sharding, a single full-sequence (flash)
    # attention runs per rank, one all-to-all swaps back. Needs local heads
    # (num_attention_heads / tp) divisible by cp; incompatible with
    # cp_zigzag (it is load-balanced by construction).
    cp_impl: str = "ring"
    # Megatron-style sequence parallelism: between TP blocks the activation
    # sequence axis is sharded over 'tp' (all-gather entering column-parallel
    # matmuls, reduce-scatter leaving row-parallel ones). Same wire bytes as
    # plain TP, residual stream / norms / saved boundaries shrink by 1/tp.
    # The reference only TODOs this (utils.py:66); beyond-parity feature.
    tp_sequence_parallel: bool = False
    # ZeRO stage 1: shard optimizer state (and the update compute) over 'dp'.
    # Gradients reduce-scatter over dp instead of all-reducing, each rank
    # updates its 1/dp chunk of the (flattened) params, updated params
    # all-gather back. Cuts AdamW state memory by dp at identical numerics.
    # Out of the reference's scope (SURVEY.md §2.3 ZeRO row); beyond-parity.
    zero1: bool = False
    # FSDP / ZeRO stage 3 for the decoder-layer stack: layer params rest
    # dp-sharded on their hidden-size axis (models/llama.py:param_pspecs),
    # are all-gathered just in time inside each layer's forward
    # (decoder_layer), and the gather's AD transpose reduce-scatters the
    # grads back — params, grads, and optimizer state for the stack all
    # shrink by dp. Embedding/LM-head/final-norm stay replicated (they are
    # pp-owned and small relative to the stack at depth). Requires
    # hidden_size % dp == 0; mutually exclusive with zero1 (redundant —
    # FSDP already shards the stack's state). Beyond-parity feature.
    fsdp: bool = False
    # Build the training step under shard_map's varying-manual-axes checker
    # (jax check_vma): every replicated-vs-varying typing error — the class
    # of bug the equivalence suite can only catch dynamically — becomes a
    # static trace-time error. DIAGNOSTIC mode, not the production default:
    # the checker auto-inserts pvary casts whose AD transposes are real
    # psums, which resequences reductions (loss trajectories drift at the
    # 1e-4..1e-2 level on zero1/fsdp) and deadlocks inside lax.cond-gated
    # stage branches. Incompatible with pp_engine='afab' (jax's scan
    # transpose does not yet type vma — upstream limitation) and with
    # cond stage gating (collectives inside single-stage branches) — on a
    # CPU-only box set use_cpu=true so the default stage_gating='auto'
    # resolves to where-masking and the checker can run (validate()'s
    # rejection error names the same fix).
    check_vma: bool = False
    # How per-stage embed/loss work is gated to its owning pipeline stage
    # (models/llama.py::_stage_gating): "cond" = lax.cond, the branch only
    # runs on the owning stage (what production TPU pipelines execute);
    # "where" = compute-both masking (collective-rendezvous-safe on the XLA
    # CPU runtime, pre-gating FLOP cost); "auto" = cond on TPU, where on
    # CPU. "cond" on a CPU mesh is supported for configs whose gated
    # branches carry no collectives (tp=1 pipelines) — the equivalence
    # suite uses it so the exact program a TPU pod runs is validated
    # off-chip.
    stage_gating: str = "auto"


@dataclass
class ModelConfig:
    name: str = "HuggingFaceTB/SmolLM-1.7B"
    num_hidden_layers: int = 24
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    hidden_size: int = 2048
    intermediate_size: int = 8192
    vocab_size: int = 49152
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    max_position_embeddings: int = 2048
    dtype: str = "bfloat16"  # compute/param dtype (reference train.py:76-77)
    # "auto": pallas flash attention on TPU, XLA sdpa elsewhere.
    # Replaces the reference's FLASH_ATTEN env switch (model.py:147-157).
    attention_impl: str = "auto"  # "auto" | "sdpa" | "flash"
    # Pallas flash-attention tile sizes; None = kernel defaults (512x512,
    # measured optimal on v5e at seq 2048/D64 and 4096/D128 — see
    # ops/pallas/flash_attention.py). Tuning knobs for other chips/shapes.
    flash_block_q: Optional[int] = None
    flash_block_k: Optional[int] = None
    # Kernel data layout: "folded" reshapes [B,S,H,D] -> [B*H,S,D] around
    # every kernel call (battle-tested default); "bshd" runs the kernels on
    # the model layout directly, skipping the host-side transpose copies
    # (opt-in until A/B'd on hardware; interpret-mode-verified identical).
    flash_layout: str = "folded"
    use_pallas_rmsnorm: Optional[bool] = None  # None = auto (TPU only)
    # gather logits over tp before the loss (reference tensor_parallel.py:48-50
    # gather_output=True); False = vocab-parallel cross-entropy (faster).
    # Only consulted by eval-time forward_logits; the training loss path is
    # picked by loss_impl.
    gather_logits: bool = True
    # training loss: "auto" (= fused), "fused" (row-chunked linear+CE, never
    # materializes fp32 logits), "gathered" (reference-parity
    # all-gather + plain CE), "vocab_parallel" (local logits, psum'd stats).
    loss_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


@dataclass
class TrainingConfig:
    seed: int = 42
    learning_rate: float = 3e-4
    # LR schedule (beyond the reference, which trains at constant lr,
    # train.py:209): "constant" | "cosine" | "linear", with optional linear
    # warmup from 0 over lr_warmup_steps. Decay runs to
    # learning_rate * lr_min_ratio over lr_decay_steps (default:
    # total_train_steps). The default (constant, no warmup) keeps the
    # optimizer state structurally identical to a plain float lr.
    lr_schedule: str = "constant"
    lr_warmup_steps: int = 0
    lr_min_ratio: float = 0.0
    lr_decay_steps: Optional[int] = None
    # torch AdamW defaults — the reference passes only lr (train.py:209)
    weight_decay: float = 0.01
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_eps: float = 1e-8
    grad_clip: float = 0.0  # 0 = off
    total_train_steps: int = 100
    seq_length: int = 1024
    micro_batch_size: int = 1
    gradient_accumulation_steps: int = 1
    max_tokens: Optional[int] = None
    # Train on only the first N raw dataset examples (reference
    # data.py:34-35, template/base_config.json:27: select(range(min(N,
    # len)))) — applied before tokenization on the HF path; the synthetic
    # stream has no documents, so there the cap applies to packed samples.
    num_samples: Optional[int] = None
    # Optimizer steps fused into one device dispatch (lax.scan over stacked
    # batches). >1 removes per-step host latency; losses are still reported
    # per step. Checkpoint/log boundaries snap to multiples of this.
    steps_per_call: int = 1
    # "full": remat every decoder layer (jax.checkpoint); "none": store all;
    # "save_attn": remat layers but keep flash-attention out+LSE (the
    # backward never re-runs the attention forward kernel).
    # Applies to the AD engines (afab, pp=1); the 1f1b engine checkpoints at
    # layer boundaries by construction — equivalent to "full" — and ignores
    # this knob (models/llama.py::stage_fwd_save, docs/PP_COST.md).
    remat: str = "full"
    # dtype gradients accumulate in across microbatches: "float32" (the
    # reference's main_grad policy, data_parallel.py:66,81) or "param"
    # (param dtype; halves grad memory, useful single-chip). Only consulted
    # when pp_size == 1 — both pipeline engines always accumulate fp32
    # (validate() rejects "param" with pp_size > 1).
    grad_accum_dtype: str = "float32"


@dataclass
class DatasetConfig:
    name: str = "synthetic"  # "synthetic" or an HF dataset path
    split: str = "train"
    text_column: str = "text"
    num_workers: int = 0
    num_proc: int = 1
    subset_name: Optional[str] = None
    # Packed corpora at or under this many tokens materialize as one host
    # numpy array (fastest gathers); anything larger stays in the datasets
    # arrow cache (disk-mapped, RAM stays bounded by the batch) — the
    # reference keeps its grouped dataset arrow-backed the same way
    # (picotron/data.py:57-100). Default 50M tokens = 200 MB of int32.
    max_in_memory_tokens: int = 50_000_000


@dataclass
class CheckpointConfig:
    save_dir: str = "checkpoints"
    save_frequency: int = 0  # 0 = disabled
    load_path: str = ""  # orbax checkpoint dir to resume from
    # HF-format safetensors file/dir to initialize weights from before training
    # (the reference's bootstrap path, checkpoint.py:50-102)
    hf_bootstrap_path: str = ""
    # Reference semantics: the reference loads the HF file, then deliberately
    # re-randomizes — the files act as shape/name templates for pre-training
    # (reference checkpoint.py:99-100). True = validate the file against the
    # model (names, shapes) but keep the seed-derived random init; False
    # (our default) = actually load the weights.
    hf_bootstrap_reinit: bool = False


@dataclass
class ResilienceConfig:
    """Fault-tolerance knobs (picotron_tpu/resilience/, docs/RESILIENCE.md).
    Defaults are production-safe: signals are caught, exits flush a
    checkpoint, re-running the same command resumes, and a NaN step applies
    no update. The chaos_* fields are a test/debug surface — deterministic
    fault injection at a given 1-indexed step (0 = off)."""

    # -- preemption safety --
    handle_signals: bool = True  # SIGTERM/SIGINT -> finish dispatch, save, exit 75
    save_on_exit: bool = True  # try/finally emergency save (needs save_frequency > 0)
    # Empty load_path + an existing checkpoint under save_dir resumes from it
    # (load_path "auto" asks for the same thing explicitly); re-running one
    # command continues one run. False restores start-from-scratch semantics.
    auto_resume: bool = True
    # -- loss-anomaly guard --
    # jit-side gate: a non-finite loss OR gradient applies no param/opt
    # update (jnp.where select inside the train step — numerically identity
    # on finite steps).
    nonfinite_guard: bool = True
    anomaly_policy: str = "skip"  # "skip" | "rollback" | "abort"
    anomaly_ema_beta: float = 0.95
    anomaly_zscore: float = 6.0  # spike = deviation > zscore * EMA-std
    anomaly_warmup_steps: int = 20  # steps before spike detection arms
    rollback_after: int = 3  # consecutive anomalies before a rollback
    max_rollbacks: int = 2  # then abort (a livelocked run must not loop)
    # -- retrying I/O (checkpoint saves/restores, safetensors reads) --
    io_attempts: int = 3
    io_backoff: float = 0.5  # seconds; doubles per attempt
    io_jitter: float = 0.25  # uniform [1, 1+jitter] delay scale
    # -- checkpoint replication --
    # After each primary save commits, the step directory is copied here
    # (retried, committed by atomic rename); restores fall back to the
    # mirror when every primary step is corrupt/unreadable. Point it at a
    # SECOND storage tier (different mount/bucket) or the replica is
    # decorative. "" = off.
    ckpt_mirror_dir: str = ""
    # -- emergency saves (preemption path) --
    # The preemption flush runs on a background thread (the signal path
    # stays fast) and the exit joins it with this deadline: a save wedged
    # on a dead mount delays the exit by at most this many seconds instead
    # of eating the whole preemption grace window. 0 = wait forever.
    emergency_save_timeout_s: float = 600.0
    # -- serving dispatch retry (inference/batcher.py) --
    # Each jitted serving dispatch (prefill, decode block, verify) is
    # retried this many times with exponential backoff before the batcher
    # isolates the failure to the implicated slots (finish_reason "error")
    # and keeps serving the rest.
    dispatch_attempts: int = 2
    dispatch_backoff: float = 0.05  # seconds; doubles per attempt
    # -- supervisor heartbeat (tools/supervise.py); also via $PICOTRON_HEARTBEAT --
    heartbeat_path: str = ""
    # -- cluster fault tolerance (resilience/cluster.py; docs/MULTIHOST.md) --
    # Steps between preemption-consensus rounds: a tiny jitted all-reduce of
    # every host's PreemptionGuard flag, so ANY host's SIGTERM becomes the
    # SAME coordinated emergency save + exit 75 on every host. Only active
    # with >1 JAX process (single-host behavior is byte-identical); raising
    # it trades per-boundary overhead for signal latency inside the
    # preemption grace window. 0 = off (legacy local-only check — a
    # preempted host may wedge its peers' collective save).
    consensus_interval: int = 1
    # A peer process silent (no lease renewal) this long is a dead host:
    # the ClusterMonitor exits THIS process with EXIT_CLUSTER_FAILED (77)
    # instead of wedging forever inside the next collective. 0 = off
    # (default: needs a shared cluster_dir to mean anything).
    peer_timeout_s: float = 0.0
    lease_interval_s: float = 2.0  # how often the monitor renews this host's lease
    # Shared directory for lease/done files — must be visible to every host
    # (a checkpoint-tier mount works). "" = <checkpoint.save_dir>/_cluster.
    cluster_dir: str = ""
    # -- chaos injection (resilience/chaos.py; each fires once per process) --
    chaos_raise_step: int = 0
    chaos_nan_step: int = 0
    chaos_sigterm_step: int = 0
    chaos_truncate_step: int = 0
    # -- serving chaos (resilience.chaos.ServingChaos, engine dispatch hooks;
    #    rounds are 1-indexed decode/verify dispatch invocations; 0 = off) --
    chaos_dispatch_raise_round: int = 0  # transient: raise once on round N
    # persistent: EVERY dispatch with this slot active raises — the
    # batcher's isolation path must fail exactly this slot (-1 = off)
    chaos_dispatch_fail_slot: int = -1
    chaos_latency_round: int = 0  # sleep chaos_latency_s before round N
    chaos_latency_s: float = 0.25
    chaos_poison_logits_round: int = 0  # round N's logits come back NaN
    # -- pod chaos ("RANK:STEP" strings, "" = off; fires on the process
    #    whose jax.process_index() == RANK after step STEP; a fired marker
    #    under save_dir keeps pod restarts from re-tripping the fault) --
    chaos_preempt_rank_at_step: str = ""  # SIGTERM one host: consensus drill
    chaos_kill_rank_at_step: str = ""  # SIGKILL one host: dead-peer drill
    chaos_stall_rank_at_step: str = ""  # one host sleeps: straggler drill
    chaos_stall_rank_s: float = 30.0  # how long the stalled rank sleeps


@dataclass
class SpecControllerConfig:
    """Closed-loop speculation tuning (inference/speculative.py::
    SpecController, docs/INFERENCE.md "Self-tuning speculation"). The
    first consumer of the obs registry as a CONTROL surface: the batcher
    mirrors per-slot draft-proposed/accepted counts and per-kind dispatch
    latencies into the registry, and the controller reads those live
    instruments to set ``spec_len`` per slot each round — ramping up where
    acceptance pays, ramping to 0 (speculation off; the batcher falls back
    to blocked decode when every slot is off) where it does not, and
    switching drafters per slot — with hysteresis so adversarial traffic
    cannot make it oscillate."""

    # Master switch. Inert unless inference.spec_len > 0 (there is no
    # speculation to tune); the batcher builds the controller only on
    # speculative engines.
    enabled: bool = False
    # Windowed accept rate at or above which a slot ramps its spec_len UP
    # (doubling toward inference.spec_len).
    target: float = 0.5
    # Windowed accept rate below which a slot ramps DOWN (halving toward
    # 0). The [low, target) band holds steady — the hysteresis band that
    # keeps borderline traffic from dithering.
    low: float = 0.25
    # Proposed-draft tokens per slot per evaluation window: the controller
    # re-decides only after a slot has proposed this many tokens since its
    # last decision, so one unlucky round cannot flip the policy.
    window: int = 32
    # Consecutive same-direction evaluations required before a ramp is
    # applied. With flip-flopping accept rates the direction alternates,
    # the streak never completes, and spec_len holds — test-pinned.
    hysteresis: int = 2
    # Rounds a slot sits at spec_len 0 before the controller re-probes
    # with a length-1 draft (traffic changes; a slot turned off on hard
    # traffic must be able to rediscover easy traffic).
    cooloff: int = 64
    # Minimum per-kind dispatch-latency samples (picotron_dispatch_seconds
    # histograms) before the measured verify-vs-decode cost ratio joins
    # the decision; below it the accept-rate thresholds decide alone.
    latency_min_samples: int = 16


@dataclass
class TenancyConfig:
    """Multi-tenant serving (inference/tenancy.py, docs/SERVING.md
    "Multi-tenant serving"): one replica serves many named tenants —
    each an optional LoRA adapter over the shared (possibly int8) base,
    a priority class, in-flight quotas, and TTFT/TPOT SLO targets. The
    default (no tenants, no manifest) builds no adapter pack and leaves
    every compiled program and every smoke byte-identical to the
    single-tenant engine."""

    # Inline tenant definitions (list of tenancy.Tenant dicts — see the
    # manifest schema in inference/tenancy.py). Applied after the
    # manifest, so a config can extend a shared fleet manifest.
    tenants: list = field(default_factory=list)
    # Path to a JSON tenant manifest: {"tenants": [{...}, ...]}. The
    # serve CLI's --tenant-manifest flag overrides this.
    manifest: str = ""
    # Adapter pack capacity: total adapter slots (slot 0 is the reserved
    # null adapter — base-only rows point at it and bypass exactly) and
    # the maximum adapter rank. Capacity-static: hot tenant add/remove
    # via POST /tenants writes pack slots, never recompiles a program.
    adapter_slots: int = 8
    adapter_rank: int = 16


@dataclass
class InferenceConfig:
    """Serving knobs (picotron_tpu/inference/, docs/INFERENCE.md). These
    only affect the InferenceEngine / ContinuousBatcher path; training
    ignores them."""

    # Autoregressive steps fused into one jitted decode dispatch
    # (engine.decode_block): per-slot EOS/budget stop state lives on device,
    # so the host syncs once per block instead of once per token. 1 = the
    # classic per-token loop (one dispatch per token). Also bounds admission
    # latency: the batcher admits/retires only at block boundaries.
    decode_block_len: int = 8
    # Data-parallel shards of one logical engine (docs/INFERENCE.md
    # "dp-sharded batching"): the slot axis — tokens, sampling state,
    # lengths, KV cache / paged pool — shards over a ('dp', 'tp') mesh
    # while params stay replicated across dp, so ONE jitted dispatch
    # advances dp x slots_per_shard slots with zero cross-shard traffic
    # on the decode/verify hot path. 1 (default) = today's tp-only mesh,
    # every existing smoke byte-identical. Requires slots % dp_size == 0
    # and (paged) kv_num_pages % dp_size == 0.
    dp_size: int = 1
    # Weight storage format for serving: "bf16" (the model's param dtype,
    # the bit-pinned default — every existing smoke is unchanged) or
    # "int8" = per-output-channel absmax quantization of every matmul
    # weight (wq/wk/wv/wo, w_gate/w_up/w_down, lm_head; embeddings and
    # norms stay full precision), applied at load
    # (checkpoint.load_params / load_hf_safetensors) so a 7B-class
    # checkpoint's weights land on device at ~half the bf16 bytes.
    # Matmuls consume the int8 storage directly through the fused
    # dequant kernel (ops/pallas/quant_matmul.py) — no dequantized
    # weight copy ever exists; scales shard over 'tp' with their output
    # channels. Generations are allclose to bf16 (and pinned exactly
    # against the fake-quant reference — tests/test_quant_weights.py).
    weight_dtype: str = "bf16"
    # KV cache storage dtype: "auto" = the model's param dtype; "int8" =
    # per-row per-kv-head absmax-quantized storage with fp32 scales
    # (kv_cache.quantize_kv) — ~2x the slots or context at the same HBM
    # budget, dequantized inside decode attention.
    kv_cache_dtype: str = "auto"
    # KV cache memory layout: "contiguous" = every slot owns a
    # max_seq_len strip (the bit-pinned default); "paged" = block-table
    # indirection over a global pool of fixed-size KV pages
    # (inference/paged_kv.py) with refcounted prefix sharing and
    # copy-on-write — HBM tracks LIVE tokens instead of slots x window,
    # and identical prompt prefixes are stored (and prefilled) once.
    # Generations are pinned identical to contiguous
    # (tests/test_paged_kv.py); contiguous stays the default until the
    # paged path is A/B'd on hardware.
    kv_layout: str = "contiguous"
    # Rows per KV page (paged layout only). Small pages waste less
    # capacity per sequence and fork prefixes at finer grain; large pages
    # make each kernel DMA deeper. Power of two >= 8 (the flash kernel's
    # sublane quantum).
    kv_page_len: int = 16
    # Pool size in pages (paged layout only). 0 = auto: one reserved
    # NULL page + slots * ceil(max_seq_len / kv_page_len) — capacity
    # parity with the contiguous layout; raise it to oversubscribe slots
    # against short typical sequences, shrink it to cap HBM.
    kv_num_pages: int = 0
    # Radix prefix cache (paged layout only): prompt pages are kept in a
    # token-keyed trie after prefill and new requests reuse (refcount,
    # skip prefilling) their longest cached prefix, copy-on-write at the
    # fork point. False = pure paging, no sharing.
    prefix_cache: bool = True
    # Per-page storage policy (paged layout only): "uniform" = every page
    # stores kv_cache_dtype (the pinned default); "hot_bf16" = pages with
    # more than one holder — radix-shared prefixes, forked slots — are
    # READ at full precision while exclusively-held pages (cold unique
    # tails, the bulk of a long generation) are read as int8 + per-row
    # scales, so the shared prefix keeps full fidelity and the tail moves
    # ~half the bytes per attend walk. Requires kv_layout: "paged" and is
    # mutually exclusive with kv_cache_dtype: "int8" (the policy manages
    # its own quantized representation). Handled by both the dense gather
    # and the flash DMA read paths (inference/paged_kv.py).
    kv_page_policy: str = "uniform"
    # Disaggregated serving role (tools/serve.py, docs/SERVING.md
    # "Disaggregated prefill/decode"): "both" (default — one replica runs
    # admission, prefill, and decode exactly as before; every existing
    # smoke is unchanged); "prefill" — the replica runs admission +
    # chunked/paged prefill only and hands finished KV pages off through
    # POST /kv/export (its /generate sheds with 503); "decode" — the
    # replica seats imported pages (POST /kv/import, /generate's "kv"
    # field) and runs the decode/spec loop, so a long prompt's prefill
    # never steals one of its dispatch rounds (it still self-prefills
    # plain requests as the failover fallback). Any role but "both"
    # requires kv_layout: "paged" — the page pool IS the handoff unit.
    role: str = "both"
    # Prompts longer than this prefill as a sequence of fixed-width chunk
    # dispatches writing K/V straight into the target slot
    # (engine.prefill_chunked): O(1) compiled shapes in prompt length and
    # flat peak activation memory. Prompts at or under it keep the
    # pow-2-bucketed one-shot prefill.
    prefill_chunk: int = 512
    # Which kernel serves KV-cache attention on the decode/verify/chunked-
    # prefill hot path: "dense" = the masked einsum+softmax over the whole
    # cache window (kv_cache.decode_attention — the bit-pinned reference,
    # always the default); "flash" = the Pallas flash-decode kernel
    # (ops/pallas/decode_attention.py) — online softmax over KV blocks
    # bounded by each slot's LIVE length, int8 K/V dequantized inside the
    # kernel (no whole-cache fp32 materialization), GQA-native. On CPU the
    # flash kernel runs in Pallas interpret mode (slow — a parity/test
    # surface, not a serving one); allclose-pinned against dense in
    # tests/test_decode_kernel.py.
    attend_impl: str = "dense"
    # Fused on-device sampling epilogue: the prefill / chunked-prefill /
    # decode_step dispatches sample their next token INSIDE the jitted
    # program (temperature -> top-k -> top-p -> categorical, the same
    # fused filter sampling.sample runs, sanitize_logits applied first),
    # so only sampled token ids [B] cross to the host instead of full
    # [B, vocab] fp32 logits. Seeded-identical to the host sampler: the
    # batcher passes the exact PRNG key the host path would have drawn.
    # False (default) keeps the host-side sampling path — the bit-pinned
    # staging default until the epilogue is A/B'd on a chip, like
    # attend_impl/kv_layout before it. (decode_block and verify always
    # sampled on device; this key completes the story for the remaining
    # logits round-trips.)
    sample_on_device: bool = False
    # Speculative decoding (inference/speculative.py, engine.verify): number
    # of tokens the drafter proposes per slot per dispatch. One jitted
    # verify pass scores all spec_len+1 positions, accepts the matching
    # draft prefix (exact match for greedy, distribution-preserving
    # rejection sampling otherwise) and emits 1..spec_len+1 tokens per
    # dispatch. 0 (default) = off: the batcher drives decode_block instead.
    spec_len: int = 0
    # Longest suffix n-gram the built-in prompt-lookup drafter matches
    # against the slot's own token history (tried spec_ngram down to 1) to
    # propose continuations. Only consulted when spec_len > 0.
    spec_ngram: int = 3
    # Which draft model proposes speculative continuations (spec_len > 0):
    # "ngram" = the model-free prompt-lookup drafter (host-side, free);
    # "learned" = the EAGLE-style learned drafter
    # (inference/speculative.py::LearnedDrafter) — a tiny head over the
    # target's own last hidden state that shares the target's embedding
    # and lm_head weights (no separate checkpoint; optional tiny-head
    # params ride a params tree), drafting spec_len tokens in one small
    # jitted dispatch. "learned" makes the engine plumb the last hidden
    # state out of every decode/verify dispatch (the return_hidden hook).
    drafter: str = "ngram"
    # Token window the n-gram drafter's suffix match scans (most recent N
    # history tokens). 0 = unbounded. The drafter's index is incremental
    # (append-only) either way; the window caps how far back a match may
    # land, keeping long-running slots' lookups O(1) per round.
    spec_history_window: int = 0
    # Closed-loop per-slot spec_len tuning — see SpecControllerConfig.
    spec_controller: SpecControllerConfig = field(
        default_factory=SpecControllerConfig)
    # Multi-tenant serving — see TenancyConfig.
    tenancy: TenancyConfig = field(default_factory=TenancyConfig)
    # Zero-bubble overlapped scheduling (docs/INFERENCE.md "Overlapped
    # scheduling"): the batcher issues dispatch N+1 BEFORE syncing
    # dispatch N, so token delivery / drafting / admission run while the
    # device executes the next round. Requires the per-slot key schedule
    # (key_schedule resolves to "slot" under "auto") so sampled streams
    # stay bit-identical to overlap-off. False (default) keeps the
    # issue-then-sync loop byte-identical to today's smokes.
    overlap: bool = False
    # PRNG key schedule for sampled decode/verify tokens:
    # "round" — one fresh key per dispatch round (the historical
    #   schedule; streams depend on round structure, so it cannot
    #   overlap);
    # "slot"  — one base key per ADMITTED request, token at position p
    #   keyed fold_in(base, p-1): streams depend only on (base key,
    #   prompt, logits), independent of round boundaries, draft
    #   contents, and controller decisions;
    # "auto" (default) — "slot" when overlap or mixed_dispatch is on,
    #   else "round".
    key_schedule: str = "auto"
    # Stall-free mixed prefill–decode dispatch (docs/INFERENCE.md "Mixed
    # prefill–decode dispatch"): every decode/verify dispatch also
    # carries one fixed-width prefill LANE (prefill_chunk tokens, padded
    # and masked when idle so the compiled shape never changes), so
    # admissions stream in without stalling active decode slots on solo
    # prefill dispatches. Requires the per-slot key schedule
    # (key_schedule resolves to "slot" under "auto") so sampled streams
    # stay bit-identical to mixed-off. False (default) keeps the serial
    # prefill path byte-identical to today's scheduler.
    mixed_dispatch: bool = False

    def __post_init__(self):
        # from_dict hands nested blocks through as plain dicts; coerce so
        # cfg.inference.spec_controller.target always works (unknown keys
        # ignored, matching Config.from_dict's build())
        if isinstance(self.spec_controller, dict):
            known = {f.name for f in
                     dataclasses.fields(SpecControllerConfig)}
            self.spec_controller = SpecControllerConfig(
                **{k: v for k, v in self.spec_controller.items()
                   if k in known})
        if isinstance(self.tenancy, dict):
            known = {f.name for f in dataclasses.fields(TenancyConfig)}
            self.tenancy = TenancyConfig(
                **{k: v for k, v in self.tenancy.items() if k in known})
    # Graceful degradation for the flash attend path: when a
    # attend_impl="flash" dispatch fails, log once, rebuild the engine's
    # compiled programs on "dense", and keep serving — for the REST OF THE
    # PROCESS (new engines start dense too; a kernel that broke once is
    # not re-trusted mid-serve). False = the failure propagates.
    attend_fallback: bool = True


@dataclass
class ObsConfig:
    """Observability knobs (picotron_tpu/obs/, docs/OBSERVABILITY.md).
    The default is ON: recording counters/spans costs nanoseconds per
    event and never touches stdout, so smoke output is unchanged either
    way; ``enabled: false`` swaps in null instruments for a zero-
    bookkeeping hot path. Scope: the switch governs the engine/batcher/
    serve/train instruments built from THIS config; ``comm_trace``'s
    per-collective instant spans are debug output gated by
    ``PICOTRON_VERBOSE>=1`` alone (off by default, and already paying a
    stderr line per collective when on)."""

    enabled: bool = True
    # Finished spans the process trace ring retains (oldest dropped).
    span_ring: int = 4096
    # Raw samples each histogram keeps for exact /statz percentiles.
    sample_window: int = 4096
    # Per-step training metrics JSONL path ("" = off). The supervisor/
    # scheduler export $PICOTRON_METRICS_JSONL next to the run log, which
    # wins over this field — same precedence as the heartbeat path.
    # Controller process only; extract_metrics.py prefers this file over
    # regex-scraping the log.
    metrics_jsonl: str = ""
    # Chrome-trace JSON dumped from the span ring when train() exits
    # ("" = off). Validate/inspect with tools/trace_dump.py.
    trace_path: str = ""
    # On-demand profiler captures (SIGUSR2 on the CLIs, POST /profilez on
    # the serving front end): jax.profiler traces land here, each capture
    # timed at profile_seconds.
    profile_dir: str = "profiles"
    profile_seconds: float = 5.0


@dataclass
class RouterConfig:
    """Multi-replica serving fabric knobs (``tools/router.py``,
    docs/SERVING.md "Multi-replica fabric"). Deliberately NOT a section of
    ``Config``: the router fronts a FLEET of serve.py replicas (each with
    its own experiment config) and is configured per deployment — one JSON
    object loaded with ``RouterConfig.from_dict`` (unknown keys ignored,
    same policy as ``Config``) or plain CLI flags."""

    # -- health probing (per-replica prober thread) --
    probe_interval_s: float = 1.0  # closed-state probe cadence
    probe_timeout_s: float = 2.0  # per-HTTP-call probe deadline
    # -- circuit breaker --
    breaker_failures: int = 3  # consecutive hard failures -> open
    # open-state reprobe ladder (resilience.retry): first delay, doubling
    # per failed reprobe, capped; a successful reprobe -> half-open, one
    # trial request decides closed vs open again.
    breaker_backoff_s: float = 1.0
    breaker_backoff_max_s: float = 30.0
    breaker_probe_attempts: int = 6  # reprobes per retry() ladder cycle
    # -- load scraping / scoring --
    # a replica whose last good /metrics scrape is older than this falls
    # out of the candidate set (stale = unknown load = unplaceable)
    scrape_stale_s: float = 10.0
    load_queue_weight: float = 1.0  # per queued request (+ router inflight)
    load_slot_weight: float = 0.5  # per active slot
    load_pool_weight: float = 4.0  # per unit of KV pool utilization [0,1]
    load_ttft_weight: float = 2.0  # per second of TTFT p95
    # -- prefix affinity --
    # prompt prefixes are hashed at this page alignment (match the fleet's
    # inference.kv_page_len so the hash key is exactly the radix-shareable
    # page run); the affinity (rendezvous) pick wins while its load score
    # is within affinity_load_slack of the least-loaded candidate.
    affinity_page_len: int = 16
    affinity_load_slack: float = 4.0
    # -- prefill/decode disaggregation (docs/SERVING.md) --
    # When the fleet holds role=prefill replicas, route each prompt's
    # prefill to its affinity prefill worker (POST /kv/export), stream
    # the finished KV pages to the decode placement, and splice the token
    # stream — a failed/severed export falls back to self-prefill at the
    # decode placement (the replay bookkeeping's path). False = ignore
    # prefill workers for orchestration (they still probe/scrape).
    disagg: bool = True
    # On a placement that escaped its affinity owner, ask the owner for
    # the longest cached page-aligned prefix (GET /kv/pages) and import
    # it at the chosen replica (POST /kv/import) before generating —
    # shared system prompts prefill once per CLUSTER. Soft: any failure
    # just skips the fetch.
    prefix_fetch: bool = True
    # Deadline for one /kv/export round trip (the prefill itself runs
    # inside it, so this is a prefill budget, not a probe timeout).
    handoff_timeout_s: float = 120.0
    # -- per-request bounds --
    place_attempts: int = 3  # placements that never streamed (shed/refused)
    replay_budget: int = 2  # mid-stream failovers (replays) per request
    connect_timeout_s: float = 5.0
    # no token for this long mid-stream reads as a wedged replica (the
    # failover trigger for stalls the replica's own watchdog missed)
    stream_idle_timeout_s: float = 60.0
    retry_after_s: int = 2  # Retry-After when no replica is eligible

    def validate(self) -> None:
        for name in ("probe_interval_s", "probe_timeout_s",
                     "breaker_backoff_s", "breaker_backoff_max_s",
                     "scrape_stale_s", "connect_timeout_s",
                     "stream_idle_timeout_s", "handoff_timeout_s"):
            if getattr(self, name) <= 0:
                raise ValueError(f"router.{name} must be > 0")
        for name in ("breaker_failures", "breaker_probe_attempts",
                     "place_attempts", "retry_after_s"):
            if getattr(self, name) < 1:
                raise ValueError(f"router.{name} must be >= 1")
        if self.replay_budget < 0:
            raise ValueError("router.replay_budget must be >= 0 (0 = a "
                             "mid-stream death fails the request)")
        if self.breaker_backoff_max_s < self.breaker_backoff_s:
            raise ValueError(
                f"router.breaker_backoff_max_s "
                f"({self.breaker_backoff_max_s}) must be >= "
                f"breaker_backoff_s ({self.breaker_backoff_s})")
        p = self.affinity_page_len
        if p < 8 or p & (p - 1):
            # the same quantum rule as inference.kv_page_len: the hash key
            # must be a whole page run or affinity lands shared prefixes on
            # different replicas than the radix cache can reuse
            raise ValueError(
                f"router.affinity_page_len must be a power of two >= 8 "
                f"(match the fleet's inference.kv_page_len), got {p}")
        for name in ("load_queue_weight", "load_slot_weight",
                     "load_pool_weight", "load_ttft_weight",
                     "affinity_load_slack"):
            if getattr(self, name) < 0:
                raise ValueError(f"router.{name} must be >= 0")

    @classmethod
    def from_dict(cls, raw: dict[str, Any]) -> "RouterConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        cfg = cls(**{k: v for k, v in raw.items() if k in known})
        cfg.validate()
        return cfg


@dataclass
class FleetConfig:
    """Elastic fleet controller knobs (``tools/fleet.py``, docs/SERVING.md
    "Elastic fleet"). Like ``RouterConfig``, deliberately NOT a section of
    ``Config``: the controller owns a fleet of serve.py workers (each with
    its own experiment config) and is configured per deployment — one JSON
    object loaded with ``FleetConfig.from_dict`` (unknown keys ignored) or
    plain CLI flags.

    The control loop scrapes every worker's ``/metrics`` + ``/readyz`` each
    ``scrape_interval_s`` and walks a fixed decision ladder per role:
    replace dead workers first (budget-gated, never cooloff-gated — lost
    capacity must not wait), then grow on a sustained high-watermark
    breach, then drain on a sustained all-low reading. "Sustained" is
    ``hysteresis`` consecutive ticks; grow/drain additionally respect a
    per-role ``cooloff_s`` so one spike cannot thrash the fleet (the
    SpecController discipline, lifted to fleet scale)."""

    # -- control loop --
    scrape_interval_s: float = 1.0  # tick cadence (scrape + decide)
    scrape_timeout_s: float = 2.0  # per-HTTP-call scrape deadline
    # consecutive breached ticks (or failed worker probes) before acting
    hysteresis: int = 2
    cooloff_s: float = 10.0  # min seconds between grow/drain per role
    # -- watermarks (grow when ANY high is breached; drain only when ALL
    # signals sit below their lows) --
    queue_high: float = 8.0  # queued requests per worker (prefill queue
    # depth on prefill workers — the signal a disaggregated fleet watches)
    queue_low: float = 1.0
    pool_high: float = 0.85  # KV pool utilization [0, 1]
    pool_low: float = 0.30
    ttft_slo_s: float = 0.0  # TTFT p95 above this -> grow (0 = off)
    # -- fleet bounds (per role) --
    min_workers: int = 1
    max_workers: int = 8
    # -- dead-worker replacement ladder (reuses the _RestartBudget
    # semantics from tools/supervise.py: bounded attempts, exponential
    # backoff, healthy-uptime replenishment) --
    max_replaces: int = 3
    replace_backoff_s: float = 0.5
    replace_backoff_max_s: float = 30.0
    healthy_reset_s: float = 600.0
    launch_attempts: int = 2  # resilience.retry attempts per launch
    # -- drain protocol --
    drain_timeout_s: float = 120.0  # POST /drain -> worker exit deadline
    # on a scale-down drain, export the victim's hottest radix prefixes
    # to a surviving worker through the PR 15 page transport (GET
    # /kv/prefixes -> POST /kv/pages -> POST /kv/import) so the drained
    # worker's cache is not lost to the cluster; soft — any failure just
    # skips the export
    export_prefixes: bool = True
    export_prefix_limit: int = 4  # hottest cached prefixes per drain

    def validate(self) -> None:
        for name in ("scrape_interval_s", "scrape_timeout_s", "cooloff_s",
                     "replace_backoff_s", "replace_backoff_max_s",
                     "drain_timeout_s"):
            if getattr(self, name) <= 0:
                raise ValueError(f"fleet.{name} must be > 0")
        for name in ("hysteresis", "min_workers", "launch_attempts",
                     "export_prefix_limit"):
            if getattr(self, name) < 1:
                raise ValueError(f"fleet.{name} must be >= 1")
        if self.max_replaces < 0:
            raise ValueError("fleet.max_replaces must be >= 0 (0 = a dead "
                             "worker is never replaced)")
        if self.max_workers < self.min_workers:
            raise ValueError(
                f"fleet.max_workers ({self.max_workers}) must be >= "
                f"min_workers ({self.min_workers})")
        if self.replace_backoff_max_s < self.replace_backoff_s:
            raise ValueError(
                f"fleet.replace_backoff_max_s ({self.replace_backoff_max_s}) "
                f"must be >= replace_backoff_s ({self.replace_backoff_s})")
        for name in ("queue_high", "queue_low", "pool_high", "pool_low",
                     "ttft_slo_s", "healthy_reset_s"):
            if getattr(self, name) < 0:
                raise ValueError(f"fleet.{name} must be >= 0")
        if self.queue_low > self.queue_high:
            raise ValueError(
                f"fleet.queue_low ({self.queue_low}) must be <= queue_high "
                f"({self.queue_high}) — the hysteresis band inverts")
        if self.pool_low > self.pool_high:
            raise ValueError(
                f"fleet.pool_low ({self.pool_low}) must be <= pool_high "
                f"({self.pool_high}) — the hysteresis band inverts")

    @classmethod
    def from_dict(cls, raw: dict[str, Any]) -> "FleetConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        cfg = cls(**{k: v for k, v in raw.items() if k in known})
        cfg.validate()
        return cfg


@dataclass
class LoggingConfig:
    use_wandb: bool = False
    run_name: str = "picotron-tpu"
    log_frequency: int = 1
    # capture a jax.profiler trace for steps [profile_start, profile_stop)
    # into profile_dir (SURVEY.md §5.1 rebuild note); 0 = off
    profile_start: int = 0
    profile_stop: int = 0
    profile_dir: str = "profiles"


# The flagship benchmark model (reference README.md:7 headline:
# SmolLM-1.7B at ~50% MFU on 8xH100). Shared by bench.py and the driver
# entry so both always measure the same model.
SMOLLM_1_7B = dict(
    name="HuggingFaceTB/SmolLM-1.7B", num_hidden_layers=24,
    num_attention_heads=32, num_key_value_heads=32, hidden_size=2048,
    intermediate_size=8192, vocab_size=49152, max_position_embeddings=2048,
    dtype="bfloat16", attention_impl="auto",
)


@dataclass
class Config:
    distributed: DistributedConfig = field(default_factory=DistributedConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    training: TrainingConfig = field(default_factory=TrainingConfig)
    dataset: DatasetConfig = field(default_factory=DatasetConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    logging: LoggingConfig = field(default_factory=LoggingConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    inference: InferenceConfig = field(default_factory=InferenceConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)

    @property
    def world_size(self) -> int:
        d = self.distributed
        return d.tp_size * d.cp_size * d.pp_size * d.dp_size

    @property
    def global_batch_size(self) -> int:
        """micro_batch * grad_acc * dp  (reference data.py:17)."""
        return (
            self.training.micro_batch_size
            * self.training.gradient_accumulation_steps
            * self.distributed.dp_size
        )

    @property
    def tokens_per_step(self) -> int:
        return self.global_batch_size * self.training.seq_length

    def validate(self) -> None:
        """Divisibility constraints, surfaced as errors the way the reference
        uses asserts (train.py:85-86, model.py:94-95, tensor_parallel.py:226)."""
        d, m, t = self.distributed, self.model, self.training
        if t.seq_length % d.cp_size != 0:
            raise ValueError(f"seq_length {t.seq_length} % cp_size {d.cp_size} != 0")
        if d.cp_zigzag and t.seq_length % (2 * d.cp_size) != 0:
            raise ValueError(
                f"cp_zigzag needs seq_length % (2*cp_size) == 0, got "
                f"{t.seq_length} % {2 * d.cp_size}")
        if d.cp_impl not in ("ring", "ulysses"):
            raise ValueError(f"unknown cp_impl {d.cp_impl!r} (ring|ulysses)")
        if d.cp_impl == "ulysses" and d.cp_size > 1:
            if d.cp_zigzag:
                raise ValueError(
                    "cp_impl='ulysses' is incompatible with cp_zigzag (the "
                    "all-to-all layout is load-balanced by construction)")
            if (m.num_attention_heads // d.tp_size) % d.cp_size != 0:
                raise ValueError(
                    f"cp_impl='ulysses' needs local heads "
                    f"({m.num_attention_heads} / tp {d.tp_size}) divisible "
                    f"by cp_size {d.cp_size}")
        if d.tp_sequence_parallel and (
                t.seq_length // d.cp_size) % d.tp_size != 0:
            raise ValueError(
                f"tp_sequence_parallel needs the cp-local sequence "
                f"({t.seq_length} / cp {d.cp_size}) divisible by tp_size "
                f"{d.tp_size}")
        if m.num_attention_heads % d.tp_size != 0:
            raise ValueError(f"num_attention_heads {m.num_attention_heads} % tp_size {d.tp_size} != 0")
        if m.num_key_value_heads % d.tp_size != 0:
            raise ValueError(f"num_key_value_heads {m.num_key_value_heads} % tp_size {d.tp_size} != 0")
        if m.num_attention_heads % m.num_key_value_heads != 0:
            raise ValueError("num_attention_heads must be a multiple of num_key_value_heads")
        if m.vocab_size % d.tp_size != 0:
            raise ValueError(f"vocab_size {m.vocab_size} % tp_size {d.tp_size} != 0")
        if m.hidden_size % m.num_attention_heads != 0:
            raise ValueError("hidden_size must be divisible by num_attention_heads")
        if m.num_hidden_layers < d.pp_size:
            # Uneven splits are supported (remainder layers on the earliest
            # stages, reference pipeline_parallel.py:33-36, via a masked
            # padded layer stack — models/llama.py::pp_layer_layout), but
            # every stage must hold at least one real layer.
            raise ValueError(
                f"num_hidden_layers {m.num_hidden_layers} < pp_size {d.pp_size}")
        if d.pp_size > 1 and t.gradient_accumulation_steps < 1:
            raise ValueError("pipeline parallelism needs >= 1 microbatch")
        if d.pp_engine not in ("afab", "1f1b"):
            raise ValueError(f"unknown pp_engine {d.pp_engine!r} (afab|1f1b)")
        if d.stage_gating not in ("auto", "cond", "where"):
            raise ValueError(
                f"unknown stage_gating {d.stage_gating!r} (auto|cond|where)")
        if d.check_vma:
            if d.pp_engine == "afab" and d.pp_size > 1:
                raise ValueError(
                    "check_vma=True is incompatible with pp_engine='afab': "
                    "jax's scan transpose does not type varying manual axes "
                    "yet (differentiating the forward pipeline trips it); "
                    "use the 1f1b engine or turn the checker off")
            if d.pp_size > 1 and (
                    d.stage_gating == "cond"
                    or (d.stage_gating == "auto" and not d.use_cpu)):
                raise ValueError(
                    "check_vma=True is incompatible with lax.cond stage "
                    "gating (the checker's auto-inserted pvary transposes "
                    "put real psums inside single-stage branches, which "
                    "deadlocks); set stage_gating='where' — or, on a CPU "
                    "box, set use_cpu: true in the distributed config "
                    "section, which resolves the 'auto' gating to "
                    "where-masking")
        if d.stage_gating == "cond" and d.use_cpu and d.tp_size > 1:
            # the gated branches carry tp collectives, and the XLA CPU
            # runtime's rendezvous intermittently aborts when a collective
            # is reached by a subset of devices (models/llama.py::
            # _stage_gating) — surface it at load, not mid-run
            raise ValueError(
                "stage_gating='cond' on a CPU mesh requires tp_size == 1 "
                "(gated tp collectives can abort the XLA CPU rendezvous); "
                "use 'auto' or 'where'")
        if d.pp_interleave < 1:
            raise ValueError("pp_interleave must be >= 1")
        if d.pp_interleave > 1:
            if d.pp_size == 1:
                # Without this, the interleaved layout path still runs in
                # init_params and dies in pp_layer_layout with a bare assert.
                raise ValueError("pp_interleave > 1 requires pp_size > 1")
            if d.pp_engine != "1f1b":
                raise ValueError("pp_interleave > 1 requires pp_engine='1f1b'")
            if m.num_hidden_layers % (d.pp_size * d.pp_interleave) != 0:
                raise ValueError(
                    f"pp_interleave needs num_hidden_layers "
                    f"({m.num_hidden_layers}) divisible by pp_size * "
                    f"pp_interleave ({d.pp_size} * {d.pp_interleave})")
            if t.gradient_accumulation_steps % d.pp_size != 0:
                raise ValueError(
                    f"pp_interleave needs gradient_accumulation_steps "
                    f"({t.gradient_accumulation_steps}) divisible by pp_size "
                    f"({d.pp_size}) (microbatch groups cycle the chunks)")
        if d.fsdp:
            if d.zero1:
                raise ValueError(
                    "fsdp and zero1 are mutually exclusive (FSDP already "
                    "shards the layer stack's params, grads, and state)")
            if m.hidden_size % d.dp_size != 0:
                raise ValueError(
                    f"fsdp needs hidden_size ({m.hidden_size}) divisible by "
                    f"dp_size ({d.dp_size}) — every layer param shards on an "
                    f"H-sized axis")
        if m.attention_impl not in ("auto", "sdpa", "flash"):
            raise ValueError(
                f"unknown attention_impl {m.attention_impl!r} (auto|sdpa|flash)")
        if m.loss_impl not in ("auto", "fused", "gathered", "vocab_parallel"):
            raise ValueError(
                f"unknown loss_impl {m.loss_impl!r} "
                "(auto|fused|gathered|vocab_parallel)")
        if t.steps_per_call < 1:
            raise ValueError("steps_per_call must be >= 1")
        if t.num_samples is not None and t.num_samples < 1:
            raise ValueError("num_samples must be >= 1 when set")
        if self.dataset.max_in_memory_tokens < 1:
            raise ValueError("max_in_memory_tokens must be >= 1")
        if t.lr_schedule not in ("constant", "cosine", "linear"):
            raise ValueError(
                f"unknown lr_schedule {t.lr_schedule!r} (constant|cosine|linear)")
        if t.lr_warmup_steps < 0:
            raise ValueError("lr_warmup_steps must be >= 0")
        if not 0.0 <= t.lr_min_ratio <= 1.0:
            raise ValueError("lr_min_ratio must be in [0, 1]")
        if t.lr_decay_steps is not None and t.lr_decay_steps <= 0:
            raise ValueError("lr_decay_steps must be > 0 when set")
        if t.lr_schedule in ("cosine", "linear"):
            # the decay horizon defaults to total_train_steps
            # (train_step.lr_schedule); either way a horizon <= warmup would
            # silently clamp into a near-instant decay
            horizon = (t.lr_decay_steps if t.lr_decay_steps is not None
                       else t.total_train_steps)
            if horizon <= t.lr_warmup_steps:
                which = ("lr_decay_steps" if t.lr_decay_steps is not None
                         else "total_train_steps")
                raise ValueError(
                    f"{which} ({horizon}) must exceed lr_warmup_steps "
                    f"({t.lr_warmup_steps}) for a decaying schedule")
        if t.remat not in ("none", "full", "save_attn", "offload"):
            raise ValueError(
                f"unknown remat {t.remat!r} (none|full|save_attn|offload)")
        if t.grad_accum_dtype not in ("float32", "param"):
            raise ValueError(
                f"unknown grad_accum_dtype {t.grad_accum_dtype!r} (float32|param)")
        if m.flash_layout not in ("folded", "bshd", "merged"):
            raise ValueError(
                f"unknown flash_layout {m.flash_layout!r} "
                f"(folded|bshd|merged)")
        if m.flash_layout == "merged":
            from picotron_tpu.ops.pallas.flash_attention import LANE

            if m.head_dim % LANE:
                raise ValueError(
                    f"flash_layout 'merged' needs head_dim % {LANE} == 0 "
                    f"(Mosaic lane tiling); got head_dim={m.head_dim} — "
                    f"use 'folded'")
        for name, b in (("flash_block_q", m.flash_block_q),
                        ("flash_block_k", m.flash_block_k)):
            # Powers of two keep the kernel's halve-until-divides fallback
            # (_pick_block) landing on real tile sizes instead of degrading
            # to 1-row blocks (e.g. 24 -> 3 -> 1). The kernel accepts small
            # tiles (ring half-blocks generate them); for full lane
            # utilization prefer block_k >= 128 and block_q >= 8 x dtype
            # packing (the 512x512 defaults are the measured optimum).
            if b is not None and (b < 8 or b & (b - 1) != 0):
                raise ValueError(
                    f"{name} must be a power of two >= 8, got {b}")
        # grad_accum_dtype='param' is valid on every topology: the pipeline
        # engines accept acc_dtype (fp32 default = the reference's main_grad
        # policy; param dtype halves the accumulator + the dp sync wire and
        # is what lets 7B fit 16 GB v5e chips at tp2/pp2 — docs/PROJECTION.md)
        if t.seq_length > m.max_position_embeddings:
            raise ValueError(
                f"seq_length {t.seq_length} > max_position_embeddings "
                f"{m.max_position_embeddings}")
        r = self.resilience
        if r.anomaly_policy not in ("skip", "rollback", "abort"):
            raise ValueError(
                f"unknown anomaly_policy {r.anomaly_policy!r} "
                "(skip|rollback|abort)")
        if r.anomaly_policy == "rollback" and self.checkpoint.save_frequency <= 0:
            raise ValueError(
                "anomaly_policy='rollback' needs checkpoint.save_frequency > 0 "
                "(there is nothing to roll back to without checkpoints)")
        if not 0.0 < r.anomaly_ema_beta < 1.0:
            raise ValueError("anomaly_ema_beta must be in (0, 1)")
        if r.io_attempts < 1:
            raise ValueError("io_attempts must be >= 1")
        if r.io_backoff < 0 or r.io_jitter < 0:
            raise ValueError("io_backoff and io_jitter must be >= 0")
        if r.dispatch_attempts < 1:
            raise ValueError("dispatch_attempts must be >= 1")
        if r.dispatch_backoff < 0:
            raise ValueError("dispatch_backoff must be >= 0")
        if r.emergency_save_timeout_s < 0:
            raise ValueError(
                "emergency_save_timeout_s must be >= 0 (0 = wait forever)")
        if r.rollback_after < 1:
            raise ValueError("rollback_after must be >= 1")
        if r.max_rollbacks < 0:
            raise ValueError("max_rollbacks must be >= 0")
        inf = self.inference
        if inf.decode_block_len < 1:
            raise ValueError("inference.decode_block_len must be >= 1")
        if inf.dp_size < 1:
            raise ValueError(
                "inference.dp_size must be >= 1 (1 = tp-only serving "
                "mesh; N shards one logical engine's slot axis over a "
                "('dp', 'tp') mesh of N x tp_size devices)")
        if inf.prefill_chunk < 1:
            raise ValueError("inference.prefill_chunk must be >= 1")
        if inf.weight_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"unknown inference.weight_dtype {inf.weight_dtype!r} "
                "(bf16|int8) — set 'int8' for per-channel quantized "
                "weights served through the fused dequant matmul, or "
                "keep the 'bf16' full-precision default")
        if inf.kv_cache_dtype not in ("auto", "int8"):
            raise ValueError(
                f"unknown inference.kv_cache_dtype {inf.kv_cache_dtype!r} "
                "(auto|int8)")
        if inf.kv_layout not in ("contiguous", "paged"):
            raise ValueError(
                f"unknown inference.kv_layout {inf.kv_layout!r} "
                "(contiguous|paged)")
        if inf.kv_page_len < 8 or inf.kv_page_len & (inf.kv_page_len - 1):
            # powers of two keep page/window math exact and respect the
            # flash kernel's 8-row sublane tiling
            raise ValueError(
                f"inference.kv_page_len must be a power of two >= 8, got "
                f"{inf.kv_page_len}")
        if inf.kv_num_pages < 0:
            raise ValueError(
                "inference.kv_num_pages must be >= 0 (0 = auto-size)")
        if inf.kv_page_policy not in ("uniform", "hot_bf16"):
            raise ValueError(
                f"unknown inference.kv_page_policy {inf.kv_page_policy!r} "
                "(uniform|hot_bf16)")
        if inf.kv_page_policy == "hot_bf16":
            if inf.kv_layout != "paged":
                # the policy is defined over pool pages and their refcounts;
                # a contiguous strip has neither — name the fix, like the
                # check_vma/use_cpu rejection above does
                raise ValueError(
                    "inference.kv_page_policy 'hot_bf16' requires the paged "
                    "KV layout (per-page refcounts decide which pages read "
                    "as int8); set inference.kv_layout: 'paged', or keep "
                    "kv_page_policy: 'uniform' on the contiguous layout")
            if inf.kv_cache_dtype == "int8":
                raise ValueError(
                    "inference.kv_page_policy 'hot_bf16' manages its own "
                    "int8 representation for cold pages and is mutually "
                    "exclusive with kv_cache_dtype: 'int8' (a uniformly "
                    "quantized cache has no full-precision pages to keep "
                    "hot); set kv_cache_dtype: 'auto', or keep "
                    "kv_page_policy: 'uniform' for a fully int8 cache")
        if inf.role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"unknown inference.role {inf.role!r} "
                "(prefill|decode|both) — 'both' is the colocated default; "
                "'prefill'/'decode' split a disaggregated fleet")
        if inf.role != "both" and inf.kv_layout != "paged":
            raise ValueError(
                f"inference.role {inf.role!r} requires the paged KV "
                "layout (finished prefills hand off as pool pages — "
                "inference/page_transport.py); set inference.kv_layout: "
                "'paged', or keep role: 'both'")
        if not isinstance(inf.sample_on_device, bool):
            raise ValueError(
                f"inference.sample_on_device must be a JSON boolean "
                f"(true/false), got {inf.sample_on_device!r} — quoted "
                f"'true'/'false' strings are not parsed as booleans")
        if inf.attend_impl not in ("dense", "flash"):
            raise ValueError(
                f"unknown inference.attend_impl {inf.attend_impl!r} "
                "(dense|flash)")
        if inf.spec_len < 0:
            raise ValueError("inference.spec_len must be >= 0 (0 = off)")
        if inf.spec_ngram < 1:
            raise ValueError("inference.spec_ngram must be >= 1")
        if inf.drafter not in ("ngram", "learned"):
            raise ValueError(
                f"unknown inference.drafter {inf.drafter!r} (ngram|learned)"
                " — 'ngram' is the model-free prompt-lookup drafter, "
                "'learned' the EAGLE-style head over the target's last "
                "hidden state")
        if inf.spec_history_window < 0:
            raise ValueError(
                "inference.spec_history_window must be >= 0 (0 = "
                "unbounded match scan)")
        if not isinstance(inf.overlap, bool):
            raise ValueError(
                f"inference.overlap must be a JSON boolean (true/false), "
                f"got {inf.overlap!r}")
        if inf.key_schedule not in ("auto", "round", "slot"):
            raise ValueError(
                f"unknown inference.key_schedule {inf.key_schedule!r} "
                "(auto|round|slot)")
        if inf.overlap and inf.key_schedule == "round":
            raise ValueError(
                "inference.overlap requires the per-slot key schedule — "
                "round-keyed sampling ties token streams to round "
                "boundaries, which the lookahead pipeline changes; set "
                "inference.key_schedule: 'slot' (or leave it 'auto')")
        if not isinstance(inf.mixed_dispatch, bool):
            raise ValueError(
                f"inference.mixed_dispatch must be a JSON boolean "
                f"(true/false), got {inf.mixed_dispatch!r}")
        if inf.mixed_dispatch and inf.key_schedule == "round":
            raise ValueError(
                "inference.mixed_dispatch requires the per-slot key "
                "schedule — round-keyed sampling ties token streams to "
                "round boundaries, which fusing the prefill lane into "
                "decode rounds changes; set inference.key_schedule: "
                "'slot' (or leave it 'auto')")
        sc = inf.spec_controller
        if not isinstance(sc.enabled, bool):
            raise ValueError(
                f"inference.spec_controller.enabled must be a JSON "
                f"boolean, got {sc.enabled!r}")
        if sc.enabled and inf.spec_len < 1:
            raise ValueError(
                "inference.spec_controller.enabled requires "
                "inference.spec_len > 0 (spec_len is the controller's "
                "per-slot ceiling; there is no speculation to tune at 0)"
                " — set inference.spec_len, or disable the controller")
        if not 0.0 < sc.target <= 1.0:
            raise ValueError(
                "inference.spec_controller.target must be in (0, 1]")
        if not 0.0 <= sc.low <= sc.target:
            raise ValueError(
                "inference.spec_controller.low must satisfy 0 <= low <= "
                f"target (got low={sc.low}, target={sc.target}) — the "
                "[low, target) band is the hysteresis hold region")
        if sc.window < 1:
            raise ValueError("inference.spec_controller.window must be >= 1")
        if sc.hysteresis < 1:
            raise ValueError(
                "inference.spec_controller.hysteresis must be >= 1")
        if sc.cooloff < 0:
            raise ValueError(
                "inference.spec_controller.cooloff must be >= 0 rounds")
        if sc.latency_min_samples < 1:
            raise ValueError(
                "inference.spec_controller.latency_min_samples must be "
                ">= 1")
        if r.consensus_interval < 0:
            raise ValueError("consensus_interval must be >= 0 (0 = off)")
        if r.peer_timeout_s < 0:
            raise ValueError("peer_timeout_s must be >= 0 (0 = off)")
        if r.lease_interval_s <= 0:
            raise ValueError("lease_interval_s must be > 0")
        if 0 < r.peer_timeout_s <= 2 * r.lease_interval_s:
            # a timeout inside the renewal cadence would read normal lease
            # jitter as a dead host and kill healthy pods
            raise ValueError(
                f"peer_timeout_s ({r.peer_timeout_s}) must exceed "
                f"2 * lease_interval_s ({2 * r.lease_interval_s}) or be 0")
        chaos_on = False
        for name in ("chaos_raise_step", "chaos_nan_step",
                     "chaos_sigterm_step", "chaos_truncate_step"):
            v = getattr(r, name)
            if v < 0:
                raise ValueError(f"{name} must be >= 0 (0 = off)")
            chaos_on = chaos_on or v > 0
        for name in ("chaos_preempt_rank_at_step", "chaos_kill_rank_at_step",
                     "chaos_stall_rank_at_step"):
            rank, _ = parse_rank_at_step(name, getattr(r, name))
            if rank >= 0 and not self.checkpoint.save_dir:
                # a SIGKILLed/preempted pod replays the chaos step on
                # relaunch; only the fired marker persisted under save_dir
                # stops the fault re-tripping every incarnation until the
                # restart budget burns to zero
                raise ValueError(
                    f"{name} requires checkpoint.save_dir (the fired "
                    f"marker lives there; without it a supervised pod "
                    f"re-trips the fault on every relaunch)")
            chaos_on = chaos_on or rank >= 0
        if r.chaos_stall_rank_s < 0:
            raise ValueError("chaos_stall_rank_s must be >= 0")
        for name in ("chaos_dispatch_raise_round", "chaos_latency_round",
                     "chaos_poison_logits_round"):
            if getattr(r, name) < 0:
                raise ValueError(f"{name} must be >= 0 (0 = off)")
        if r.chaos_dispatch_fail_slot < -1:
            raise ValueError(
                "chaos_dispatch_fail_slot must be >= -1 (-1 = off)")
        if r.chaos_latency_s < 0:
            raise ValueError("chaos_latency_s must be >= 0")
        o = self.obs
        if o.span_ring < 1:
            raise ValueError("obs.span_ring must be >= 1")
        if o.sample_window < 1:
            raise ValueError("obs.sample_window must be >= 1")
        if o.profile_seconds <= 0:
            raise ValueError("obs.profile_seconds must be > 0")
        if chaos_on and t.steps_per_call != 1:
            # chaos fires at exact host-visible step boundaries (and NaN
            # injection swaps in a poisoned single-step program for exactly
            # one dispatch); inside a fused multi-step scan the target step
            # has no dispatch boundary of its own, so the event would
            # silently never fire — refuse instead
            raise ValueError(
                "chaos_*_step injection requires training.steps_per_call == 1")

    # ---- JSON round-trip (reference: train.py:62-63 consumes one JSON file) ----

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2)

    @classmethod
    def from_dict(cls, raw: dict[str, Any]) -> "Config":
        def build(dc, section: dict):
            known = {f.name for f in dataclasses.fields(dc)}
            return dc(**{k: v for k, v in section.items() if k in known})

        cfg = cls(
            distributed=build(DistributedConfig, raw.get("distributed", {})),
            model=build(ModelConfig, raw.get("model", {})),
            training=build(TrainingConfig, raw.get("training", {})),
            dataset=build(DatasetConfig, raw.get("dataset", {})),
            checkpoint=build(CheckpointConfig, raw.get("checkpoint", {})),
            logging=build(LoggingConfig, raw.get("logging", {})),
            resilience=build(ResilienceConfig, raw.get("resilience", {})),
            inference=build(InferenceConfig, raw.get("inference", {})),
            obs=build(ObsConfig, raw.get("obs", {})),
        )
        cfg.validate()
        return cfg

    @classmethod
    def from_json(cls, path: str) -> "Config":
        with open(path) as f:
            return cls.from_dict(json.load(f))
