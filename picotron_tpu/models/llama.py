"""Llama-family decoder as a pure function over a parameter pytree.

Architecture spec from the reference (picotron/model.py): Embedding ->
N x DecoderLayer (RMSNorm -> Attention(+RoPE, GQA) -> residual -> RMSNorm ->
SwiGLU MLP -> residual) -> final RMSNorm -> LM head (untied, model.py:226-271).
Init laws preserved so loss curves can match: linear weights
U(-sqrt(1/fan_in), sqrt(1/fan_in)) (model.py:109-119, 172-181), embedding
N(0, 1) (model.py:220-221), norm weights ones.

Parallelism is built in rather than layered on by module surgery
(reference train.py:174-193):
- TP: weights arrive pre-sharded by shard_map; column-parallel = tp_copy + local
  matmul, row-parallel = local matmul + tp_reduce (reference
  tensor_parallel.py:35-50 module-swap table). Head counts are local,
  nh/tp and nkv/tp, as in model.py:94-97.
- CP: attention switches to ring_attention when cp_size > 1 (the reference's
  CONTEXT_PARALLEL branch, model.py:147-150); RoPE tables are sliced to the
  local chunk (model.py:201).
- PP: ``stage_apply`` is the uniform per-stage program — embedding applied on
  the first stage, loss on the last, selected by the traced 'pp' axis index
  (replacing the reference's per-stage nn.Identity surgery,
  pipeline_parallel.py:12-15).

Parameter layout: linear weights are stored (in_features, out_features) so the
forward is ``x @ w``; decoder layers are stacked on a leading layer axis and
scanned, which is also the axis pipeline parallelism shards.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name as _ckpt_name
from jax.sharding import PartitionSpec as P

from picotron_tpu.config import Config, ModelConfig
from picotron_tpu.ops.attention import sdpa
from picotron_tpu.ops.cross_entropy import (
    cross_entropy_fused,
    cross_entropy_gathered,
    cross_entropy_vocab_parallel,
)
from picotron_tpu.ops.rmsnorm import rms_norm
from picotron_tpu.ops.rope import apply_rope, precompute_rope
from picotron_tpu.parallel.cp import ring_attention, ulysses_attention
from picotron_tpu.parallel.tp import (
    sp_gather,
    sp_scatter,
    tp_copy,
    tp_gather,
    tp_reduce,
)
from picotron_tpu.utils import (
    on_tpu,
    pvary_like,
    scan_carry_fixpoint,
    vma_checking,
)

Params = dict[str, Any]


# --------------------------------------------------------------------------- #
# init
# --------------------------------------------------------------------------- #


def _uniform(key, shape, fan_in, dtype):
    bound = math.sqrt(1.0 / fan_in)
    return jax.random.uniform(key, shape, jnp.float32, -bound, bound).astype(dtype)


def pp_layer_layout(L: int, pp: int, interleave: int = 1):
    """Stage layer counts + stacked-row positions for the pipeline layouts.

    Even/uneven contiguous splits (interleave == 1): remainder layers go to
    the earliest stages — the reference's distribution rule
    (pipeline_parallel.py:33-36). The SPMD pipeline shards a stacked layer
    axis over 'pp', which needs equal rows per stage, so the stack is padded
    to K = ceil(L/pp) rows per stage and the pad rows are masked identity
    layers (zero weights, skipped via a validity mask — FLOP waste =
    (K*pp - L)/L, e.g. 1/32 for Llama-2-7B on pp=3).

    Interleaved (virtual-stage) layout (interleave = v > 1, requires
    L % (pp*v) == 0): the model is cut into v*pp chunks of L/(pp*v) layers;
    device s owns chunks {s, pp+s, ..., (v-1)*pp+s}, stored chunk-major in
    its contiguous K-row shard — the Megatron-style layout that lets the
    interleaved 1F1B schedule shrink the pipeline bubble by v
    (parallel/pp.py::pipeline_1f1b_interleaved).

    Returns (K, counts, positions): counts[s] = real layers on stage s,
    positions[g] = row of global layer g in the [K*pp] stacked axis.
    """
    if interleave > 1:
        assert L % (pp * interleave) == 0, (L, pp, interleave)
        Kv = L // (pp * interleave)
        K = L // pp
        positions = []
        for g in range(L):
            chunk, i = divmod(g, Kv)  # virtual stage chunk = c*pp + s
            c, s = divmod(chunk, pp)
            positions.append(s * K + c * Kv + i)
        return K, [K] * pp, positions
    base, rem = divmod(L, pp)
    counts = [base + (1 if s < rem else 0) for s in range(pp)]
    K = base + (1 if rem else 0)
    positions = []
    for s, c in enumerate(counts):
        positions += [s * K + i for i in range(c)]
    return K, counts, positions


def remap_layout(params: Params, L: int, src: tuple,
                 dst: tuple = (1, 1)) -> Params:
    """Re-arrange the stacked layer rows of ``params`` from one pipeline
    layout to another: ``src``/``dst`` are ``(pp_size, interleave)`` pairs
    as taken by ``pp_layer_layout``. Global layer g moves from row
    ``src_positions[g]`` to row ``dst_positions[g]``; rows neither layout
    uses (padding of uneven splits) are zero. The main consumer is eval on
    interleaved-trained params: ``dst=(1, 1)`` restores the contiguous
    global order ``forward_logits`` scans, without the checkpoint
    save/load round-trip previously required."""
    if tuple(src) == tuple(dst):
        return params
    _, _, pos_s = pp_layer_layout(L, *src)
    K_d, _, pos_d = pp_layer_layout(L, *dst)
    pp_d = dst[0]
    src_idx = jnp.asarray(pos_s)
    dst_idx = jnp.asarray(pos_d)

    def re(v):
        rows = v[src_idx]  # [L, ...]: real layers in global order
        if K_d * pp_d == L and pos_d == list(range(L)):
            return rows  # contiguous unpadded target: pure permutation
        out = jnp.zeros((K_d * pp_d,) + v.shape[1:], v.dtype)
        return out.at[dst_idx].set(rows)

    return {**params, "layers": jax.tree.map(re, params["layers"])}


def init_params(key, m: ModelConfig, pp_size: int = 1,
                interleave: int = 1) -> Params:
    """Global (unsharded-shape) parameter pytree. Jit with out_shardings to
    materialize directly as sharded arrays — replaces the reference's
    meta-device init + materialization dance (checkpoint.py:15-48, 50-102).

    Real-layer weights are drawn with an [L, ...] leading axis regardless of
    ``pp_size``/``interleave``, then scattered into the stacked-row layout
    (padded for uneven splits, chunk-permuted for interleaved 1F1B) — so the
    model function is identical across topologies and the equivalence oracle
    holds for every layout."""
    H, I, V, L = m.hidden_size, m.intermediate_size, m.vocab_size, m.num_hidden_layers
    D = m.head_dim
    Hq, Hkv = m.num_attention_heads * D, m.num_key_value_heads * D
    dt = jnp.dtype(m.dtype)
    ks = {name: jax.random.fold_in(key, i) for i, name in enumerate(
        ["embed", "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"])}
    ones = lambda *shape: jnp.ones(shape, dt)
    layers = {
        "attn_norm": ones(L, H),
        "wq": _uniform(ks["wq"], (L, H, Hq), H, dt),
        "wk": _uniform(ks["wk"], (L, H, Hkv), H, dt),
        "wv": _uniform(ks["wv"], (L, H, Hkv), H, dt),
        "wo": _uniform(ks["wo"], (L, Hq, H), Hq, dt),
        "mlp_norm": ones(L, H),
        "w_gate": _uniform(ks["w_gate"], (L, H, I), H, dt),
        "w_up": _uniform(ks["w_up"], (L, H, I), H, dt),
        "w_down": _uniform(ks["w_down"], (L, I, H), I, dt),
    }
    if L % pp_size != 0 or interleave > 1:
        K, _, positions = pp_layer_layout(L, pp_size, interleave)
        idx = jnp.asarray(positions)
        layers = {
            k: jnp.zeros((K * pp_size,) + v.shape[1:], v.dtype).at[idx].set(v)
            for k, v in layers.items()
        }
    return {
        "embed": jax.random.normal(ks["embed"], (V, H), jnp.float32).astype(dt),
        "layers": layers,
        "final_norm": ones(H),
        "lm_head": _uniform(ks["lm_head"], (H, V), H, dt),
    }


# The matmul weights eligible for per-channel int8 quantization
# (inference.weight_dtype: "int8"): the seven decoder-layer projections
# plus the LM head ("lm_head" at the tree top). Embedding and norms stay
# full precision — they are tiny next to the stack and their error
# characteristics differ (the embedding is a gather, not a matmul).
QUANT_WEIGHT_LEAVES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def matmul(x, w):
    """``x @ w`` dispatching on the weight leaf's form: a plain array runs
    the dense matmul; a quantized ``{"q": int8, "s": fp32}`` pair (see
    ops/pallas/quant_matmul.py) runs the fused dequant matmul — the
    Pallas kernel on TPU, the XLA int8-einsum fallback elsewhere; an
    adapter-wrapped ``{"w", "a", "b", "ids"}`` leaf (multi-tenant
    serving, ops/pallas/lora_matmul.py) recurses on its base ``w`` —
    which may itself be the quantized pair — and adds the per-row
    segmented LoRA residual on top, so one dispatch mixes tenants while
    the base weights stay int8 or bf16 untouched. A trace-time Python
    branch, exactly like the attend_impl dispatch: each leaf form traces
    its own program, no runtime cost. Output dtype follows ``x`` on the
    quantized path (the dense path's promotion rule for same-dtype
    operands) and the base output on the adapter path (the fp32 residual
    casts onto it)."""
    from picotron_tpu.ops.pallas.lora_matmul import (
        is_lora_weight,
        lora_matmul,
    )
    from picotron_tpu.ops.pallas.quant_matmul import (
        is_quant_weight,
        quant_matmul,
    )

    if is_lora_weight(w):
        base = matmul(x, w["w"])
        return base + lora_matmul(x, w["a"], w["b"],
                                  w["ids"]).astype(base.dtype)
    if is_quant_weight(w):
        return quant_matmul(x, w["q"], w["s"])
    return x @ w


def quantize_params(params: Params) -> Params:
    """Quantize every eligible matmul weight (QUANT_WEIGHT_LEAVES +
    lm_head) to per-output-channel int8 pairs; embedding/norms pass
    through untouched. The stacked layer axis rides along (scales come
    out [L, out] — one scale vector per layer per leaf). The in-memory
    counterpart of checkpoint.load_* with ``weight_dtype="int8"`` (used
    by the random-init serving path and tests).

    Deliberately EAGER, leaf by leaf — op-by-op dispatch keeps scales
    bit-identical across every quantization path (this, the host numpy
    streamer, a restored sharded tree; a jitted variant drifts a ulp
    when XLA rewrites the /127), transients are bounded to one leaf's
    fp32 copy (sharded when the leaf is — restore against sharded
    ShapeDtypeStructs so a 7B tree never concentrates on one device),
    and each dense leaf frees as soon as the caller drops its tree."""
    from picotron_tpu.ops.pallas.quant_matmul import quantize_weight

    layers = {k: (quantize_weight(v) if k in QUANT_WEIGHT_LEAVES else v)
              for k, v in params["layers"].items()}
    return {**params, "layers": layers,
            "lm_head": quantize_weight(params["lm_head"])}


def dequantize_params(params: Params, dtype) -> Params:
    """The fake-quant reference tree: every quantized leaf dequantized
    back to ``dtype``. TESTS ONLY — a dense engine fed this tree is the
    oracle the int8 engine's generations are pinned against (the
    quantization error is in both; only the fused-matmul plumbing
    differs)."""
    from picotron_tpu.ops.pallas.quant_matmul import (
        dequantize_weight,
        is_quant_weight,
    )

    def deq(leaf):
        if is_quant_weight(leaf):
            return dequantize_weight(leaf["q"], leaf["s"], dtype)
        return leaf

    layers = {k: deq(v) for k, v in params["layers"].items()}
    return {**params, "layers": layers, "lm_head": deq(params["lm_head"])}


def param_bytes(params: Params) -> int:
    """Total bytes the parameter tree occupies (int8 values + fp32
    scales included) — the ``weight_bytes_total`` metric the int8 mode
    roughly halves (kv_cache.cache_bytes' weight-side twin)."""
    return sum(a.size * a.dtype.itemsize for a in jax.tree.leaves(params))


# FSDP: the axis (AFTER the scan slices off the leading layer-stack axis)
# each layer param rests dp-sharded on and is all-gathered over just in
# time inside decoder_layer. Every entry is an H-sized axis, so the single
# divisibility constraint is hidden_size % dp == 0 (config validation).
FSDP_GATHER_AXIS = {
    "attn_norm": 0, "wq": 0, "wk": 0, "wv": 0, "wo": 1,
    "mlp_norm": 0, "w_gate": 0, "w_up": 0, "w_down": 1,
}


def param_pspecs(_: ModelConfig, fsdp: bool = False,
                 weight_dtype: str = "bf16") -> Params:
    """PartitionSpecs: layer stack sharded over 'pp' (contiguous stage slices,
    the rule at reference pipeline_parallel.py:33-36), column-parallel weights
    shard out-features over 'tp', row-parallel shard in-features, embedding is
    vocab-parallel (reference tensor_parallel.py:35-50); embed/final_norm/
    lm_head are replicated across 'pp' stages. Everything replicated over
    'dp' and 'cp' — except with ``fsdp``, where each LAYER param additionally
    rests dp-sharded on its H-sized axis (FSDP_GATHER_AXIS) and is gathered
    just in time in decoder_layer.

    ``weight_dtype="int8"`` mirrors the quantized tree's shape: every
    eligible matmul leaf becomes a ``{"q", "s"}`` pair whose int8 values
    keep the dense spec and whose per-output-channel scales drop the
    contraction axis — scales shard WITH their channels (a tp-sharded
    column split carries its own channels' scales, replicated nowhere)."""
    layers = {
        "attn_norm": P("pp", None),
        "wq": P("pp", None, "tp"),
        "wk": P("pp", None, "tp"),
        "wv": P("pp", None, "tp"),
        "wo": P("pp", "tp", None),
        "mlp_norm": P("pp", None),
        "w_gate": P("pp", None, "tp"),
        "w_up": P("pp", None, "tp"),
        "w_down": P("pp", "tp", None),
    }
    if fsdp:
        if weight_dtype == "int8":
            # FSDP is a training rewrite; quantized weights are a serving
            # format (inference_config turns fsdp off) — reject the combo
            # rather than invent gather semantics for scale leaves
            raise ValueError(
                "fsdp and int8 weight quantization are mutually exclusive "
                "(quantized weights serve; FSDP trains)")
        for name, ax in FSDP_GATHER_AXIS.items():
            spec = list(layers[name])
            assert spec[ax + 1] is None, (name, spec)  # +1: stack axis
            spec[ax + 1] = "dp"
            layers[name] = P(*spec)
    specs = {
        "embed": P("tp", None),
        "layers": layers,
        "final_norm": P(),
        "lm_head": P(None, "tp"),
    }
    if weight_dtype == "int8":
        def qspec(spec):
            t = tuple(spec)
            return {"q": spec, "s": P(*t[:-2], t[-1])}

        specs["layers"] = {
            k: (qspec(v) if k in QUANT_WEIGHT_LEAVES else v)
            for k, v in layers.items()
        }
        specs["lm_head"] = qspec(specs["lm_head"])
    return specs


# Multi-tenant adapters: which projections contract over a tp-sharded
# axis (row-parallel) — their adapter A shards WITH the contraction so
# the residual's partial sums ride the same tp_reduce the base output
# does; everywhere else A replicates and B shards its out-features.
_ROW_PARALLEL = ("wo", "w_down")


def adapter_pspecs(specs: Params) -> Params:
    """Wrap a ``param_pspecs`` tree's seven projection leaves into the
    adapter leaf form ``{"w": base_spec, "a", "b", "ids"}`` (see
    ops/pallas/lora_matmul.py). a is [L, T, in, r] sharded 'pp' on the
    stack and — row-parallel leaves only — 'tp' on the contraction;
    b is [L, T, r, out] sharded 'pp' + 'tp' on out-features for
    column-parallel leaves; ids is the [L, B] per-row adapter-id
    broadcast, 'pp'-sharded with the stack. The base leaf spec (dense
    or quantized pair) nests untouched, so adapter engines shard their
    base weights exactly like non-adapter engines do."""
    layers = dict(specs["layers"])
    for name in QUANT_WEIGHT_LEAVES:
        row = name in _ROW_PARALLEL
        layers[name] = {
            "w": layers[name],
            "a": P("pp", None, "tp" if row else None, None),
            "b": P("pp", None, None, None if row else "tp"),
            "ids": P("pp", None),
        }
    return {**specs, "layers": layers}


def bind_adapters(params: Params, pack_leaves: dict, ids) -> Params:
    """Wrap the seven projection leaves with the adapter pack + this
    dispatch's per-row adapter ids (``ids`` [B] int32) — the host-side
    step before every adapter-engine dispatch. ``pack_leaves`` is
    AdapterPack.device_leaves(): ``{leaf: {"a": [L, T, in, R],
    "b": [L, T, R, out]}}``. ids broadcasts to [L, B] so the layer scan
    slices a per-layer [B] row alongside each weight. Cheap: a dict
    rebuild around existing device arrays plus one tiny broadcast."""
    from picotron_tpu.ops.pallas.lora_matmul import is_lora_weight

    if is_lora_weight(params["layers"]["wq"]):
        raise ValueError("params are already adapter-bound — bind once "
                         "per dispatch from the BASE tree")
    ids = jnp.asarray(ids, jnp.int32).reshape(-1)
    L = params["layers"]["attn_norm"].shape[0]
    ids_l = jnp.broadcast_to(ids[None, :], (L, ids.shape[0]))
    layers = dict(params["layers"])
    for name in QUANT_WEIGHT_LEAVES:
        layers[name] = {"w": layers[name], "a": pack_leaves[name]["a"],
                        "b": pack_leaves[name]["b"], "ids": ids_l}
    return {**params, "layers": layers}


def merge_adapter(params: Params, leaves: dict) -> Params:
    """The merged-weight reference tree ``W + A @ B`` — TESTS AND PARITY
    TOOLING ONLY (generate.py --check-adapter-parity): a dense engine
    fed this tree is the solo-tenant oracle the segmented multi-tenant
    dispatch's generations are pinned against. ``leaves`` maps leaf
    name -> (a [L, in, r], b [L, r, out]) (AdapterPack.random_leaves
    format). Dense trees only — an int8 engine's oracle merges into its
    fake-quant dense twin (llama.dequantize_params), mirroring the
    weight-parity gate."""
    from picotron_tpu.ops.pallas.quant_matmul import is_quant_weight

    layers = dict(params["layers"])
    for name, (a, b) in leaves.items():
        w = layers[name]
        if is_quant_weight(w):
            raise ValueError(
                f"merge_adapter needs dense weights; {name} is quantized "
                f"— dequantize_params first (the weight-parity recipe)")
        delta = jnp.einsum("lkr,lrn->lkn", jnp.asarray(a, jnp.float32),
                           jnp.asarray(b, jnp.float32),
                           preferred_element_type=jnp.float32)
        layers[name] = (w.astype(jnp.float32) + delta).astype(w.dtype)
    return {**params, "layers": layers}


# --------------------------------------------------------------------------- #
# forward pieces (all run inside shard_map; collectives over size-1 axes are free)
# --------------------------------------------------------------------------- #


def use_sp(cfg: Config) -> bool:
    """Sequence parallelism is active (a no-op rewrite at tp == 1)."""
    return cfg.distributed.tp_sequence_parallel and cfg.distributed.tp_size > 1


def embed_lookup(w, tokens, sp: bool = False):
    """Vocab-parallel embedding: mask out-of-shard tokens, psum partials
    (reference VocabParallelEmbedding, tensor_parallel.py:246-271). With
    sequence parallelism the partial sums are reduce-scattered straight to
    this rank's seq shard instead of fully reduced."""
    v_local = w.shape[0]
    start = lax.axis_index("tp") * v_local
    local = tokens - start
    ok = (local >= 0) & (local < v_local)
    e = jnp.take(w, jnp.clip(local, 0, v_local - 1), axis=0)
    e = e * ok[..., None].astype(w.dtype)
    return sp_scatter(e) if sp else tp_reduce(e)


def _attention(q, k, v, cfg: Config, cache=None, pos=None):
    """Full-sequence attention (training / prefill), or — when ``cache`` is
    given — the incremental decode path: ``cache`` is this layer's UPDATED
    cache block dict (``{"k","v"[, "k_scale","v_scale"]}``, each
    [B, max_len, n_kv_local, ...] with compact GQA heads, never repeated)
    and ``pos`` [B] is the first index just written per sequence; the
    ``k``/``v`` positional args are ignored. The decode kernel is a masked
    dot product over the cache (inference/kv_cache.py) — flash brings
    nothing at query length 1.
    """
    scale = 1.0 / math.sqrt(cfg.model.head_dim)
    if cache is not None:
        from picotron_tpu.inference.kv_cache import attend

        # S queries starting at per-sequence write index ``pos``: the valid
        # key count is pos + S (S == 1 decode, S > 1 chunked prefill or
        # speculative verify). ``inference.attend_impl`` picks the kernel —
        # the dense whole-window reference or the length-aware Pallas flash
        # decode (which reads int8 blocks as stored; the dense path
        # dequantizes whole blocks on the fly). The impl string is a Python
        # value, so each choice traces its own program under jit.
        return attend(q, cache, pos + q.shape[1], scale,
                      impl=cfg.inference.attend_impl)
    impl = cfg.model.attention_impl
    if impl == "auto":
        impl = "flash" if on_tpu() else "sdpa"
    if cfg.distributed.cp_size > 1:
        if cfg.distributed.cp_impl == "ulysses":
            # all-to-all seq<->head reshard around one full-sequence kernel
            return ulysses_attention(q, k, v, scale, "cp",
                                     cfg.distributed.cp_size, True,
                                     impl == "flash",
                                     cfg.model.flash_block_q,
                                     cfg.model.flash_block_k,
                                     cfg.model.flash_layout)
        # ring with Pallas flash blocks on TPU, XLA einsum blocks elsewhere
        return ring_attention(q, k, v, scale, "cp", cfg.distributed.cp_size,
                              True, impl == "flash",
                              cfg.distributed.cp_zigzag,
                              cfg.model.flash_block_q,
                              cfg.model.flash_block_k,
                              cfg.model.flash_layout)
    if impl == "flash":
        from picotron_tpu.ops.pallas.flash_attention import flash_attention

        return flash_attention(q, k, v, scale, causal=True,
                               block_q=cfg.model.flash_block_q,
                               block_k=cfg.model.flash_block_k,
                               layout=cfg.model.flash_layout)
    return sdpa(q, k, v, scale, causal=True)


def _norm(x, w, cfg: Config):
    use_pallas = cfg.model.use_pallas_rmsnorm
    if use_pallas is None:
        use_pallas = on_tpu()
    if use_pallas:
        from picotron_tpu.ops.pallas.rmsnorm import rms_norm_pallas

        return rms_norm_pallas(x, w, cfg.model.rms_norm_eps)
    return rms_norm(x, w, cfg.model.rms_norm_eps)


def decoder_layer(lp, h, cos, sin, cfg: Config, cache=None, pos=None,
                  return_kv: bool = False):
    """One decoder block with per-shard head counts (model.py:94-97,187-208).

    With sequence parallelism the residual stream ``h`` is seq-sharded over
    'tp': the norm runs on the local shard, the Megatron f/g collectives
    become all-gather (entering column-parallel) / reduce-scatter (leaving
    row-parallel), and attention/MLP still see the full (cp-local) sequence.

    Inference hooks (picotron_tpu/inference/):
    - ``return_kv=True`` (prefill): the full-sequence path runs unchanged
      but the layer also returns its compact pre-repeat rotated K/V block
      [B, S, n_kv_local, head_dim] for the caller to park in a KV cache —
      return value becomes ``(h, (k, v))``.
    - ``cache={"k","v"[,"k_scale","v_scale"]}`` + ``pos`` [B] (decode /
      chunked prefill / speculative verify): the new tokens' K/V are
      written into the per-layer cache block starting at each sequence's
      ``pos`` (int8 caches quantize on write — kv_cache.cache_write) and
      attention runs as a masked dot product over the cache
      (``_attention``'s decode path); ``cos``/``sin`` must then be the
      per-sequence [B, S, head_dim] tables from
      ``ops.rope.rope_at_positions``. S == 1 is the per-slot decode step;
      S > 1 with B == 1 is a single-slot prefill chunk; S > 1 with B > 1
      is the multi-token decode hook — EVERY slot scores S contiguous
      positions from its own offset in one pass (speculative decoding's
      verify dispatch, engine._verify_impl). Return value is
      ``(h, updated_cache_dict)``. All assume cp == 1 (the serving mesh
      is tp-only; inference/engine.py enforces it)."""
    m, tp = cfg.model, cfg.distributed.tp_size
    nh, nkv, D = m.num_attention_heads // tp, m.num_key_value_heads // tp, m.head_dim
    sp = use_sp(cfg)
    enter = sp_gather if sp else tp_copy
    leave = sp_scatter if sp else tp_reduce

    if cfg.distributed.fsdp:
        # FSDP just-in-time materialization: gather each dp-sharded layer
        # param for this layer only; the gather's AD transpose
        # reduce-scatters (dp-sums) the grads back onto the shards. Free
        # at dp == 1. The "peak = one layer's full params" property needs
        # a remat mode that RECOMPUTES the gather in backward (any mode
        # but "none"); under remat="none" the gathered params are saved
        # as AD residuals across the whole stack, keeping only the
        # grad/optimizer-state 1/dp savings.
        lp = {k: lax.all_gather(v, "dp", axis=FSDP_GATHER_AXIS[k],
                                tiled=True)
              for k, v in lp.items()}

    # attention sub-block: column(q,k,v) -> rope -> attn -> row(out)
    # (checkpoint_name tags are inert outside jax.checkpoint policies;
    # remat="save_attn" keeps flash_out/lse, remat="offload" parks every
    # tagged residual in pinned host memory — layers_forward docstring)
    x = _ckpt_name(enter(_norm(h, lp["attn_norm"], cfg)), "attn_in")
    B, S, _ = x.shape
    q = matmul(x, lp["wq"]).reshape(B, S, nh, D)
    k = matmul(x, lp["wk"]).reshape(B, S, nkv, D)
    v = _ckpt_name(matmul(x, lp["wv"]).reshape(B, S, nkv, D), "v_proj")
    q = _ckpt_name(apply_rope(q, cos, sin), "q_rope")
    k = _ckpt_name(apply_rope(k, cos, sin), "k_rope")

    new_cache = None
    if cache is not None:
        # incremental decode (S == 1, one row per slot), chunked prefill
        # (S > 1, one slot's contiguous block), or speculative verify
        # (S > 1, every slot's contiguous block): write the fresh K/V at
        # each sequence's position (quantizing for int8 caches), attend
        # over the whole cache block
        from picotron_tpu.inference.kv_cache import cache_write

        new_cache = cache_write(cache, k, v, pos)
        o = _attention(q, None, None, cfg, cache=new_cache, pos=pos)
    else:
        kv_compact = (k, v)  # pre-repeat: what a prefill parks in the cache
        cp, cp_impl = cfg.distributed.cp_size, cfg.distributed.cp_impl
        # GQA + context parallelism: the compact Hkv-head K/V ride the wire
        # (Hq/Hkv x less ICI traffic than the reference's pre-repeat,
        # model.py:141-142) whenever the CP algorithm supports it — always
        # for the ring (expand per block), for Ulysses when the local kv
        # heads split evenly over cp (expand after the all-to-all).
        compact_cp = cp > 1 and (cp_impl == "ring" or nkv % cp == 0)
        if nkv != nh and not compact_cp:
            k = jnp.repeat(k, nh // nkv, axis=2)
            v = jnp.repeat(v, nh // nkv, axis=2)
        o = _attention(q, k, v, cfg)
    o = o.reshape(B, S, nh * D)
    h = h + leave(matmul(o, lp["wo"]))

    # MLP sub-block: column(gate,up) -> SwiGLU -> row(down)  (model.py:163-185)
    x = _ckpt_name(enter(_norm(h, lp["mlp_norm"], cfg)), "mlp_in")
    g = _ckpt_name(matmul(x, lp["w_gate"]), "mlp_gate")
    u = _ckpt_name(matmul(x, lp["w_up"]), "mlp_up")
    y = _ckpt_name(jax.nn.silu(g) * u, "mlp_act")
    out = h + leave(matmul(y, lp["w_down"]))
    if new_cache is not None:
        return out, new_cache
    return (out, kv_compact) if return_kv else out


def layer_valid_mask(stacked, cfg: Config):
    """Validity mask for the scanned layer rows, or None when every row is a
    real layer (even split). Two cases for uneven splits:
    - rows == K (a stage's local slice inside the pipeline): row i is real
      iff i < counts[stage], with the stage from ``lax.axis_index('pp')``;
    - rows == K*pp (the full padded stack — eval paths like forward_logits
      running on a mesh that holds the whole stack): position p is real iff
      (p % K) < counts[p // K]."""
    L, pp = cfg.model.num_hidden_layers, cfg.distributed.pp_size
    if L % pp == 0:
        return None
    K, counts, _ = pp_layer_layout(L, pp)
    rows = jax.tree.leaves(stacked)[0].shape[0]
    if rows == K * pp:
        return jnp.asarray([(p % K) < counts[p // K] for p in range(rows)])
    base, rem = divmod(L, pp)
    n_s = base + (lax.axis_index("pp") < rem)
    return jnp.arange(K) < n_s


# every residual decoder_layer tags with checkpoint_name, in forward
# order — the remat="offload" policy parks these in pinned host memory
OFFLOAD_NAMES = ("attn_in", "q_rope", "k_rope", "v_proj", "flash_out",
                 "flash_lse", "mlp_in", "mlp_gate", "mlp_up", "mlp_act")


def layers_forward(stacked, h, cos, sin, cfg: Config):
    """Scan over the locally-held layer stack (this stage's contiguous slice).
    Pad rows of an uneven pipeline split are skipped via the validity mask
    (h passes through unchanged, so their weights get zero gradients).

    remat modes (training.remat):
    - "none": save every intermediate (XLA default) — fastest, most memory;
    - "full": jax.checkpoint per layer — recompute the whole layer forward
      during backward, save only layer-boundary activations;
    - "save_attn": per-layer checkpoint with a policy that keeps the flash-
      attention output + LSE (named inside the kernel's VJP,
      ops/pallas/flash_attention.py) — the backward recomputes the cheap
      norm/matmul chain but never re-runs the flash forward kernel, for
      ~(S*H + S) extra bf16/fp32 floats per layer;
    - "offload": every tagged residual (attn_in/q_rope/k_rope/v_proj/
      flash_out/flash_lse/mlp_in/mlp_gate/mlp_up/mlp_act — decoder_layer)
      is parked in pinned HOST memory during forward and streamed back for
      backward: near-zero recompute at near-zero HBM, paid for in
      host-link bandwidth. Pays only when the host link sustains
      ~bytes/FLOP of the model: ≈ (12H + 6I) bytes per token-layer
      against 2(4H^2 + 3HI) FLOPs — a crossover around H ~ 14k at an
      assumed 16 GB/s link, inversely proportional to the measured
      bandwidth (tools/measure_offload_bw) — docs/BENCH_7B.md has the
      arithmetic. The mode exists for the big-model pod regime; the
      single-chip bench ladder does not use it."""
    valid = layer_valid_mask(stacked, cfg)

    if valid is None:
        def body(h, lp):
            return decoder_layer(lp, h, cos, sin, cfg), None
        xs = stacked
    else:
        def body(h, xs):
            lp, v = xs
            return jnp.where(v, decoder_layer(lp, h, cos, sin, cfg), h), None
        xs = (stacked, valid)

    remat = cfg.training.remat
    if remat == "full":
        body = jax.checkpoint(body)
    elif remat == "save_attn":
        body = jax.checkpoint(
            body, policy=jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse"))
    elif remat == "offload":
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.save_and_offload_only_these_names(
                names_which_can_be_saved=[],
                names_which_can_be_offloaded=list(OFFLOAD_NAMES),
                offload_src="device", offload_dst="pinned_host"))
    if vma_checking("pp"):
        h = scan_carry_fixpoint(body, h, jax.tree.map(lambda a: a[0], xs))
    h, _ = lax.scan(body, h, xs)
    return h


def _head_input(params, h, cfg: Config):
    """Final norm + tp copy — the shared prefix of logits and loss paths.
    With sequence parallelism the norm runs on the local seq shard and the
    result is all-gathered to the full sequence for the vocab-sharded head."""
    x = _norm(h, params["final_norm"], cfg)
    return sp_gather(x) if use_sp(cfg) else tp_copy(x)


def head_logits(params, h, cfg: Config):
    """Final norm + untied LM head (the reference always creates a fresh
    untied head, checkpoint.py:88-91); logits stay vocab-sharded. The
    head matmul dispatches on the leaf form, so an int8-quantized head
    serves through the same fused dequant matmul as the layer stack."""
    return matmul(_head_input(params, h, cfg), params["lm_head"])


def loss_from_hidden(params, h, targets, cfg: Config):
    """Final norm -> LM head -> mean CE, by the configured loss_impl:
    - "fused" (default): row-chunked fused linear+CE — full fp32 logits are
      never materialized (ops/cross_entropy.py:cross_entropy_fused);
    - "gathered": reference-parity path — logits gathered over 'tp' then
      plain CE (tensor_parallel.py:48-50, train.py:46-49);
    - "vocab_parallel": materialized local logits, psum'd CE statistics."""
    impl = cfg.model.loss_impl
    if impl == "auto":
        impl = "fused"
    x = _head_input(params, h, cfg)
    if impl == "fused":
        return cross_entropy_fused(x, params["lm_head"], targets)
    logits = x @ params["lm_head"]
    if impl == "gathered":
        return cross_entropy_gathered(logits, targets)
    return cross_entropy_vocab_parallel(logits, targets)


def rope_tables(cfg: Config):
    """Full-sequence tables; sliced per cp rank inside the step."""
    return precompute_rope(
        cfg.training.seq_length, cfg.model.head_dim, cfg.model.rope_theta,
        jnp.dtype(cfg.model.dtype))


def slice_rope_for_cp(cos, sin, s_local, cfg: Config):
    """Each cp rank's rows of the angle tables, matching its token positions
    (reference model.py:201, context_parallel.py:189-195). Zigzag ranks own
    two non-adjacent chunks -> two dynamic slices."""
    rank = lax.axis_index("cp")
    if cfg.distributed.cp_zigzag and cfg.distributed.cp_size > 1:
        n = cfg.distributed.cp_size
        h = s_local // 2
        early = rank * h
        late = (2 * n - 1 - rank) * h

        def take(t):
            return jnp.concatenate(
                [lax.dynamic_slice_in_dim(t, early, h, 0),
                 lax.dynamic_slice_in_dim(t, late, h, 0)], axis=0)

        return take(cos), take(sin)
    start = rank * s_local
    return (lax.dynamic_slice_in_dim(cos, start, s_local, 0),
            lax.dynamic_slice_in_dim(sin, start, s_local, 0))


def _stage_gating(cfg: Config) -> bool:
    """Whether per-stage embed/loss gating uses ``lax.cond`` (true branch
    executed only on the owning stage) or a compute-both ``jnp.where`` mask.

    On TPU, collectives inside a cond taken by a subset of devices are safe
    as long as every replica group is entirely inside or outside the branch —
    true here, since the predicate depends only on the 'pp' index and the
    gated collectives reduce over 'tp'. The XLA *CPU* runtime's in-process
    rendezvous, however, intermittently aborts when a collective op is
    reached by a subset of devices, so the CPU test/dryrun path defaults to
    masking with ``where`` instead (the pre-gating semantics; the FLOP waste
    only matters on real chips).

    ``distributed.stage_gating`` overrides the default ("cond"/"where"):
    forcing "cond" on a CPU mesh lets the equivalence suite run the exact
    gated program a TPU pod executes — safe when the gated branches carry
    no collectives (tp=1 pipelines)."""
    mode = cfg.distributed.stage_gating
    if mode == "cond":
        return True
    if mode == "where":
        return False
    # "auto": a config that REQUESTS the CPU mesh (use_cpu) resolves to
    # where-masking regardless of what the default backend happens to be —
    # on_tpu() sniffs the process-global backend, which on a TPU host would
    # otherwise cond-gate a run that is actually executing on host devices
    # (and config.validate's check_vma guard predicts resolution from
    # use_cpu, so this keeps validation and resolution aligned).
    if cfg.distributed.use_cpu:
        return False
    return on_tpu()


def _stage_input(params, h_recv, tokens, cfg: Config, is_first=None):
    """Stage input: the embedding on the first (virtual) stage, the received
    activation elsewhere — gated so non-first stages never pay the
    vocab-parallel embedding lookup (the reference instantiates the
    embedding only on stage 0, pipeline_parallel.py:12-15). ``is_first``
    overrides the default first-stage predicate (the interleaved engine
    passes "device 0 AND chunk 0")."""
    dt = jnp.dtype(cfg.model.dtype)
    sp = use_sp(cfg)
    if cfg.distributed.pp_size == 1:
        return embed_lookup(params["embed"], tokens, sp).astype(dt)
    pred = (lax.axis_index("pp") == 0) if is_first is None else is_first
    if _stage_gating(cfg):
        # no vma casts here: cond gating + check_vma is rejected at config
        # validation (the checker's auto-inserted pvary transposes put real
        # psums inside single-stage branches), so this path never runs
        # under the checker
        return lax.cond(
            pred,
            lambda: embed_lookup(params["embed"], tokens, sp).astype(dt),
            lambda: h_recv,
        )
    emb = embed_lookup(params["embed"], tokens, sp).astype(dt)
    return jnp.where(pred, emb, h_recv)


def _stage_loss(params, h, targets, cfg: Config, is_last=None):
    """Loss, computed only on the last (virtual) stage (reference
    pipeline_parallel.py:67-69, 97-100) — gated so earlier stages skip the
    LM-head matmul (for SmolLM a 2048x49152 matmul, ~10% of model FLOPs).
    ``is_last`` overrides the default last-stage predicate (the interleaved
    engine passes "device pp-1 AND chunk v-1")."""
    pp = cfg.distributed.pp_size
    if pp == 1:
        return loss_from_hidden(params, h, targets, cfg)
    pred = (lax.axis_index("pp") == pp - 1) if is_last is None else is_last
    if _stage_gating(cfg):
        # cond gating + check_vma is rejected at validation; no casts here
        return lax.cond(
            pred,
            lambda: loss_from_hidden(params, h, targets, cfg),
            lambda: jnp.zeros((), jnp.float32),
        )
    loss = loss_from_hidden(params, h, targets, cfg)
    return jnp.where(pred, loss, 0.0)


def stage_apply(params, h_recv, tokens, targets, cos, sin, cfg: Config,
                is_first=None, is_last=None):
    """The uniform per-pipeline-stage program. Returns (h_out, loss) where
    h_out is the activation sent downstream (pre-final-norm) and loss is
    nonzero only on the last stage. Embedding and LM-head/loss are cond-gated
    to their owning (virtual) stages, so no stage wastes the other stages'
    FLOPs."""
    h = _stage_input(params, h_recv, tokens, cfg, is_first)
    s_local = tokens.shape[-1]
    cos_l, sin_l = slice_rope_for_cp(cos, sin, s_local, cfg)
    h = layers_forward(params["layers"], h, cos_l, sin_l, cfg)
    loss = _stage_loss(params, h, targets, cfg, is_last)
    return h, loss


def stage_fwd_save(params, h_recv, tokens, targets, cos, sin, cfg: Config,
                   is_first=None, is_last=None):
    """Forward for the manual-backward 1F1B engine: ``stage_apply`` that also
    returns the activations ``stage_bwd`` needs — the input to every local
    layer plus the final hidden state. This is the layer-granular
    checkpointing set, so a stage's in-flight memory is L_local + 1 boundary
    tensors per microbatch, never the full per-layer intermediates the
    reference's no-remat 1F1B holds
    (pipeline_parallel.py:46-52). Note the 1F1B engine is layer-remat *by
    construction*: ``training.remat`` governs the AD engines (afab /
    no_pipeline); here the backward always re-derives each layer's VJP from
    its boundary (docs/PP_COST.md)."""
    h = _stage_input(params, h_recv, tokens, cfg, is_first)
    s_local = tokens.shape[-1]
    cos_l, sin_l = slice_rope_for_cp(cos, sin, s_local, cfg)
    valid = layer_valid_mask(params["layers"], cfg)

    if valid is None:
        def body(h, lp):
            return decoder_layer(lp, h, cos_l, sin_l, cfg), h
        scan_xs = params["layers"]
    else:
        def body(h, xs):
            lp, v = xs
            return jnp.where(v, decoder_layer(lp, h, cos_l, sin_l, cfg), h), h
        scan_xs = (params["layers"], valid)
    if vma_checking("pp"):
        h = scan_carry_fixpoint(
            body, h, jax.tree.map(lambda a: a[0], scan_xs))
    h_final, layer_inputs = lax.scan(body, h, scan_xs)
    loss = _stage_loss(params, h_final, targets, cfg, is_last)
    # h_final IS buffered (not rederived from layer_inputs[-1] inside the
    # last-stage cond in stage_bwd): with cp>1 the rederiving decoder_layer
    # would put ring-attention ppermutes inside a partially-executed
    # conditional, which the XLA CPU runtime's global collective-permute
    # rendezvous aborts on (utils.collective_scan_unroll). psums inside
    # conds (embed/loss gating) are per-group rendezvous and safe.
    return h_final, loss, {"layer_inputs": layer_inputs, "h_final": h_final}


def stage_bwd(params, saved, tokens, targets, dh_out, dloss, cos, sin,
              cfg: Config, is_first=None, is_last=None):
    """Manual backward for one stage: given the saved layer boundaries, the
    downstream cotangent ``dh_out`` and the loss cotangent ``dloss``, return
    (dparams, dh_prev). Each layer's backward re-derives its VJP from the
    saved layer *input* — one forward recompute + backward per layer, i.e.
    exactly remat="full" cost (3x fwd FLOPs), with no whole-stage forward
    rebuild. Head/loss and embedding backwards are cond-gated to the owning
    stages, mirroring ``stage_apply``."""
    pp = cfg.distributed.pp_size
    stage = lax.axis_index("pp")
    pred_first = (stage == 0) if is_first is None else is_first
    pred_last = (stage == pp - 1) if is_last is None else is_last
    dt = jnp.dtype(cfg.model.dtype)
    s_local = tokens.shape[-1]
    cos_l, sin_l = slice_rope_for_cp(cos, sin, s_local, cfg)

    # ---- head/loss backward (last stage only)
    h_final = saved["h_final"]

    def loss_head(fn_w, lm_w, h):
        return loss_from_hidden({"final_norm": fn_w, "lm_head": lm_w}, h,
                                targets, cfg)

    def loss_vjp():
        out, vjp = jax.vjp(loss_head, params["final_norm"], params["lm_head"],
                           h_final)
        # vma cast: the schedule's dloss mask is built from pp-index
        # predicates only; the cotangent type must match the primal loss
        # (check_vma)
        return vjp(pvary_like(dloss, out))

    if _stage_gating(cfg):
        # cond gating + check_vma is rejected at validation; no casts here
        d_fnorm, d_lmhead, dh_loss = lax.cond(
            pred_last,
            loss_vjp,
            lambda: (jnp.zeros_like(params["final_norm"]),
                     jnp.zeros_like(params["lm_head"]),
                     jnp.zeros_like(h_final)),
        )
    else:
        # dloss is already masked to the last stage, and the vjp outputs are
        # linear in dloss, so no further masking is needed
        d_fnorm, d_lmhead, dh_loss = loss_vjp()
    dh = dh_out + dh_loss

    # ---- layers backward: reverse scan re-deriving each layer's VJP from its
    # saved input (ys keep xs order under reverse=True). Pad rows of an
    # uneven split mirror the forward's where-skip: cotangent passes through,
    # the pad layer's grads are zeroed.
    valid = layer_valid_mask(params["layers"], cfg)

    def layer_bwd(dh, xs):
        lp, x, v = xs
        _, vjp = jax.vjp(lambda lp, h: decoder_layer(lp, h, cos_l, sin_l, cfg),
                         lp, x)
        dlp, dx = vjp(dh)
        if valid is not None:
            dlp = jax.tree.map(lambda g: jnp.where(v, g, 0), dlp)
            dx = jnp.where(v, dx, dh)
        return dx, dlp

    n_rows = jax.tree.leaves(params["layers"])[0].shape[0]
    vmask = (jnp.ones(n_rows, bool) if valid is None else valid)
    dh, d_layers = lax.scan(layer_bwd, dh,
                            (params["layers"], saved["layer_inputs"], vmask),
                            reverse=True)

    # ---- embedding backward (first stage only)
    def embed_vjp():
        # vma cast on w: dh carries the schedule's pp-varying type while
        # the embed output would not, and a vjp cotangent must match its
        # primal exactly (check_vma); numerically the identity
        out, vjp = jax.vjp(
            lambda w: embed_lookup(w, tokens, use_sp(cfg)).astype(dt),
            pvary_like(params["embed"], dh))
        return vjp(pvary_like(dh, out))[0]

    if _stage_gating(cfg):
        # cond gating + check_vma is rejected at validation; no casts here
        d_embed = lax.cond(pred_first, embed_vjp,
                           lambda: jnp.zeros_like(params["embed"]))
    else:
        d_embed = jnp.where(pred_first, embed_vjp(), 0)
    dh_prev = jnp.where(pred_first, jnp.zeros_like(dh), dh)
    dparams = {"embed": d_embed, "layers": d_layers,
               "final_norm": d_fnorm, "lm_head": d_lmhead}
    return dparams, dh_prev


def forward_logits(params, tokens, cfg: Config, gather: bool = True,
                   seq_layout: str | None = None):
    """Whole-model forward to logits (no pipeline), for eval/tests. Runs inside
    shard_map; with a 1-device mesh this is the plain single-chip model.

    Zigzag layout contract: when ``cfg.distributed.cp_zigzag`` is set, the
    RoPE tables and causal masks follow the zigzag *data* layout, so
    ``tokens`` must already be permuted the way the training loader permutes
    them (``parallel.cp.zigzag_perm`` applied to the GLOBAL sequence axis,
    before any cp sharding), and the returned logits are in that same
    permuted order — apply ``parallel.cp.zigzag_inverse_perm`` to get
    original-order logits. The caller acknowledges this by passing
    ``seq_layout="zigzag"``; a zigzag config without it raises rather than
    silently computing with wrong positions/masks. (The permutation cannot
    be applied here: under cp>1 this function sees only a local sequence
    shard, while the permutation is global.)

    Interleaved layer layouts (pp_interleave > 1) are remapped to the
    contiguous global order on the fly (``remap_layout`` — a pure row
    permutation, since interleave requires L % (pp*v) == 0), so
    interleaved-trained params eval directly."""
    d = cfg.distributed
    zig = d.cp_zigzag and d.cp_size > 1
    if zig and seq_layout != "zigzag":
        raise ValueError(
            "this config trains with the zigzag sequence layout "
            "(cp_zigzag): pass seq_layout='zigzag' after permuting the "
            "global sequence axis with parallel.cp.zigzag_perm (invert "
            "logits with zigzag_inverse_perm) — original-order tokens "
            "would silently get wrong positions/masks")
    if not zig and seq_layout == "zigzag":
        raise ValueError(
            "seq_layout='zigzag' passed but the config does not use the "
            "zigzag layout (cp_zigzag with cp_size > 1)")
    if d.pp_interleave > 1 and d.pp_size > 1:
        params = remap_layout(params, cfg.model.num_hidden_layers,
                              (d.pp_size, d.pp_interleave))
    cos, sin = rope_tables(cfg)
    dt = jnp.dtype(cfg.model.dtype)
    h = embed_lookup(params["embed"], tokens, use_sp(cfg)).astype(dt)
    s_local = tokens.shape[-1]
    cos_l, sin_l = slice_rope_for_cp(cos, sin, s_local, cfg)
    h = layers_forward(params["layers"], h, cos_l, sin_l, cfg)
    logits = head_logits(params, h, cfg)
    return tp_gather(logits) if gather else logits


def num_params(m: ModelConfig) -> int:
    """Global parameter count (the reference reconstructs this across shards,
    utils.py:52-79; here it's arithmetic)."""
    H, I, V, L, D = (m.hidden_size, m.intermediate_size, m.vocab_size,
                     m.num_hidden_layers, m.head_dim)
    per_layer = (H * m.num_attention_heads * D + 2 * H * m.num_key_value_heads * D
                 + m.num_attention_heads * D * H + 3 * H * I + 2 * H)
    return V * H + L * per_layer + H + H * V
