from picotron_tpu.models import llama  # noqa: F401
