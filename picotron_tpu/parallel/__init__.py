from picotron_tpu.parallel.tp import tp_copy, tp_reduce, tp_gather  # noqa: F401
from picotron_tpu.parallel.cp import ring_attention  # noqa: F401
