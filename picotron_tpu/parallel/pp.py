"""Pipeline parallelism: AFAB and 1F1B schedules over the 'pp' mesh axis.

The reference runs its schedules as rank-divergent Python loops with blocking
NCCL p2p (pipeline_parallel.py:54-83 AFAB, :85-145 1F1B;
pp_communications.py). Under XLA's single-program SPMD model every device must
trace the same computation, so both schedules are re-derived as uniform
collective-permute pipelines: stage-s-to-s+1 sends become a non-circular
``lax.ppermute``, per-stage divergence (which microbatch a stage works on,
warmup/cooldown bubbles) becomes traced index arithmetic on
``lax.axis_index('pp')`` with masked no-op steps. Activations between stages
are constant-shape, exactly what a jitted permute wants (the reference also
fixes tensor_shapes once, train.py:201).

- AFAB: the forward pipeline is a ``lax.scan`` over M + pp - 1 ticks;
  ``jax.grad`` through the scan automatically yields the reversed
  (backward) pipeline — the transpose of ppermute is the opposite-direction
  ppermute. All-forward-then-all-backward memory (every in-flight microbatch's
  activations stored), like the reference's AFAB (:71-72). Microbatch grads
  accumulate in float32 via the fp32-master-params cast trick (see
  ``pipeline_afab``); AFAB's role is the independent correctness oracle.

- 1F1B: a manual phase-split schedule — (pp-1) forward-only warmup ticks,
  M full (one-forward-one-backward) ticks, (pp-1) backward-only cooldown
  ticks, so bubble ticks never execute a masked half and the critical path
  is standard non-interleaved 1F1B. The forward saves each microbatch's
  layer-boundary activations into an O(pp) ring buffer (the 1F1B memory
  win, reference :86); the backward re-derives each *layer's* VJP from its
  saved input — layer-granular remat, one layer forward recompute +
  backward, no whole-stage forward rebuild (see docs/PP_COST.md). Gradients
  accumulate in float32, the reference's main_grad dtype policy
  (data_parallel.py:66,81); the last microbatch's psum happens outside,
  matching require_backward_grad_sync-on-last-micro (train.py:40-41).

With pp_size == 1 both schedules degenerate to the plain gradient-accumulation
loop over microbatches (the reference's non-PP train_step, train.py:29-55).

stage_fn(params, h_recv, tokens_mb, targets_mb) -> (h_out, loss) is
models.llama.stage_apply partially applied.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from picotron_tpu.comm_trace import log as _trace
from picotron_tpu.utils import (
    collective_scan_unroll,
    scan_carry_fixpoint,
    vma_checking,
)


def _take_mb(arr, i):
    return lax.dynamic_index_in_dim(arr, i, 0, keepdims=False)


def _carry_fixpoint(body, carry):
    """Cast a tick-scan carry to ``body``'s vma fix-point (shard_map
    ``check_vma``) — see ``utils.scan_carry_fixpoint``. Skipped entirely
    on the checker-off production build: the extra abstract trace of the
    full fwd+bwd tick would buy casts that are provable no-ops there."""
    if not vma_checking("pp"):
        return carry
    return scan_carry_fixpoint(lambda c, t: (body(c, t), None), carry,
                               jnp.int32(0))


def _down_perm(pp):  # stage s -> s+1; stage 0 receives zeros
    return [(i, i + 1) for i in range(pp - 1)]


def _up_perm(pp):  # stage s -> s-1; last stage receives zeros
    return [(i + 1, i) for i in range(pp - 1)]


def no_pipeline(stage_fn, params, tokens, targets, h_shape, h_dtype,
                acc_dtype=jnp.float32):
    """pp_size == 1: plain gradient-accumulation over microbatches — the
    reference's non-PP train_step (train.py:29-55). A ``lax.scan`` over the
    microbatch axis with value_and_grad per microbatch, accumulating grads in
    ``acc_dtype`` (float32 = the reference's main_grad policy,
    data_parallel.py:66,81; the param dtype halves optimizer-step memory for
    single-chip benchmarking)."""
    M = tokens.shape[0]
    h0 = jnp.zeros(h_shape, h_dtype)

    def loss_fn(p, tok, tgt):
        _, loss = stage_fn(p, h0, tok, tgt)
        return loss

    gacc0 = jax.tree.map(lambda p: jnp.zeros(p.shape, acc_dtype), params)

    def body(carry, mb):
        gacc, loss_acc = carry
        tok, tgt = mb
        loss, g = jax.value_and_grad(loss_fn)(params, tok, tgt)
        gacc = jax.tree.map(lambda a, gi: a + gi.astype(acc_dtype), gacc, g)
        return (gacc, loss_acc + loss.astype(jnp.float32)), None

    # unroll on CPU: the stage body can contain ring-attention ppermutes,
    # which race across scan iterations in the XLA CPU runtime
    # (utils.collective_scan_unroll)
    carry0 = _carry_fixpoint(
        lambda c, _t: body(c, (_take_mb(tokens, 0), _take_mb(targets, 0)))[0],
        (gacc0, jnp.float32(0.0)))
    (gacc, loss_acc), _ = lax.scan(body, carry0, (tokens, targets),
                                   unroll=collective_scan_unroll())
    grads = jax.tree.map(lambda g: g / M, gacc)
    return loss_acc / M, grads


def pipeline_afab_loss(stage_fn, params, tokens, targets, pp_size, h_shape, h_dtype):
    """Differentiable pipelined loss. tokens/targets: [M, mbs, S_local].
    Returns the mean microbatch loss, identical (via pp-psum) on all stages."""
    M = tokens.shape[0]
    s = lax.axis_index("pp")
    T = M + pp_size - 1
    perm = _down_perm(pp_size)

    def tick(h_recv, t):
        mb = jnp.clip(t - s, 0, M - 1)
        h_out, loss_mb = stage_fn(params, h_recv, _take_mb(tokens, mb), _take_mb(targets, mb))
        valid = (t - s >= 0) & (t - s < M)
        contrib = jnp.where(valid, loss_mb, 0.0)  # loss_mb is already last-stage-only
        _trace("pp.afab send_recv act down", "pp", h_out)
        h_next = lax.ppermute(h_out, "pp", perm) if perm else jnp.zeros_like(h_out)
        return h_next, contrib

    h0 = _carry_fixpoint(lambda c, t: tick(c, t)[0],
                         jnp.zeros(h_shape, h_dtype))
    _, contribs = lax.scan(tick, h0, jnp.arange(T), unroll=collective_scan_unroll())
    return lax.psum(jnp.sum(contribs), "pp") / M


def pipeline_afab(stage_fn, params, tokens, targets, pp_size, h_shape, h_dtype,
                  acc_dtype=jnp.float32):
    """(loss, grads) via autodiff through the forward pipeline.

    Gradients accumulate across microbatch ticks in float32 — the reference's
    main_grad policy (data_parallel.py:66,81) — via a dtype trick: the
    differentiated function takes fp32 master params and casts them to the
    compute dtype *inside* the scan body, so each tick's param cotangent is
    cast-transposed to fp32 before the scan transpose sums it. With fp32
    compute dtype the casts are identity and XLA removes them. Costs one
    fp32 param copy; AFAB is the correctness oracle, 1F1B the production
    engine. With ``acc_dtype`` = the param dtype the cast trick is skipped
    and the scan transpose accumulates cotangents natively in param dtype
    (the opt-in memory saver)."""
    if all(p.dtype == acc_dtype for p in jax.tree.leaves(params)):
        return jax.value_and_grad(
            lambda p: pipeline_afab_loss(stage_fn, p, tokens, targets,
                                         pp_size, h_shape, h_dtype)
        )(params)
    dtypes = jax.tree.map(lambda p: p.dtype, params)
    params32 = jax.tree.map(lambda p: p.astype(jnp.float32), params)

    def cast_stage_fn(p32, h, tok, tgt):
        p = jax.tree.map(lambda x, dt: x.astype(dt), p32, dtypes)
        return stage_fn(p, h, tok, tgt)

    loss, grads = jax.value_and_grad(
        lambda p32: pipeline_afab_loss(cast_stage_fn, p32, tokens, targets,
                                       pp_size, h_shape, h_dtype)
    )(params32)
    return loss, grads


def _scan_phase(carry, ticks, body):
    """Scan a half- or full-tick body over a contiguous tick range (empty
    ranges are a no-op). Shared by both 1F1B engines."""
    if len(ticks) == 0:
        return carry
    out, _ = lax.scan(lambda c, t: (body(c, t), None), carry,
                      jnp.asarray(ticks), unroll=collective_scan_unroll())
    return out


def _full_tick(fwd_half, bwd_half):
    """Compose the two half-tick bodies into one steady-state tick."""
    def tick(carry, t):
        return bwd_half(fwd_half(carry, t), t)
    return tick


def pipeline_1f1b_interleaved(stage_fwd, stage_bwd, params, tokens, targets,
                              pp_size, v, h_shape, h_dtype,
                              acc_dtype=jnp.float32):
    """Interleaved (virtual-stage) 1F1B: each device holds ``v``
    non-contiguous model chunks (chunk-major rows of its 'pp' shard, layout
    ``llama.pp_layer_layout(L, pp, v)``), shrinking the pipeline bubble by
    ``v``. Beyond the reference (SURVEY §2.3: "no interleaved/virtual
    stages").

    The schedule is tick-uniform SPMD: every device processes (chunk,
    microbatch) *units* in the same global order — microbatches in groups of
    pp, each group passing chunk 0..v-1 (Megatron's grouping) — with

      fwd unit  k = t - s                and
      bwd unit  j = t - (pp-1-s) - OFF,  OFF = v*pp - 1,

    where the unit orders are
      fwd k -> chunk (k mod pp*v) // pp,  micro (k // (pp*v))*pp + k mod pp
      bwd j -> same but chunks descending.
    Boundary activations move on ONE circular ppermute per direction: the
    s -> s+1 edges carry same-chunk hand-off and the wrap edge pp-1 -> 0
    carries the chunk c -> c+1 transition (its garbage arrivals land exactly
    on units masked as first-virtual-stage/loss-seeded). This reproduces
    Megatron's interleaved warmup counts ((pp-s-1)*2 + (v-1)*pp) and
    steady-state exactly. Requires M % pp == 0 (validated in config).

    stage_fwd(chunk_params, h, tok, tgt, is_first, is_last)
        -> (h_out, loss, saved)
    stage_bwd(chunk_params, saved, tok, tgt, dh_out, dloss, is_first,
        is_last) -> (dparams, dh_prev)
    with is_first/is_last the first/last *virtual* stage predicates.
    """
    M = tokens.shape[0]
    N = M * v
    s = lax.axis_index("pp")
    OFF = v * pp_size - 1
    # bwd consumes units chunk-descending, so a chunk-0 slot lives up to
    # 2*v*pp - 2 fwd units before its backward claims it
    BUF = 2 * v * pp_size
    down = [(i, (i + 1) % pp_size) for i in range(pp_size)]  # circular
    up = [((i + 1) % pp_size, i) for i in range(pp_size)]
    K = jax.tree.leaves(params["layers"])[0].shape[0]
    Kv = K // v

    def chunk_params(c):
        layers = jax.tree.map(
            lambda x: lax.dynamic_slice_in_dim(x, c * Kv, Kv, 0),
            params["layers"])
        return {**params, "layers": layers}

    def unit_fwd(k):
        g = k // (pp_size * v)
        c = (k % (pp_size * v)) // pp_size
        m = g * pp_size + k % pp_size
        return c, m

    def unit_bwd(j):
        g = j // (pp_size * v)
        c = v - 1 - (j % (pp_size * v)) // pp_size
        m = g * pp_size + j % pp_size
        return c, m

    gacc0 = jax.tree.map(lambda p: jnp.zeros(p.shape, acc_dtype), params)
    h0 = jnp.zeros(h_shape, h_dtype)
    tok0, tgt0 = _take_mb(tokens, 0), _take_mb(targets, 0)
    t_pred = jnp.bool_(True)
    saved_shape = jax.eval_shape(
        lambda p, h, tok, tgt: stage_fwd(p, h, tok, tgt, t_pred, t_pred)[2],
        chunk_params(0), h0, tok0, tgt0)
    sbuf0 = jax.tree.map(
        lambda sh: jnp.zeros((BUF,) + tuple(sh.shape), sh.dtype), saved_shape)

    def fwd_half(carry, t):
        h_recv, dh_recv, sbuf, gacc, loss_acc = carry
        k = t - s
        fvalid = (k >= 0) & (k < N)
        kk = jnp.clip(k, 0, N - 1)
        c, m = unit_fwd(kk)
        is_first = (s == 0) & (c == 0)
        is_last = (s == pp_size - 1) & (c == v - 1)
        h_out, loss_mb, saved = stage_fwd(
            chunk_params(c), h_recv, _take_mb(tokens, m), _take_mb(targets, m),
            is_first, is_last)
        loss_acc = loss_acc + jnp.where(fvalid, loss_mb, 0.0)
        sbuf = jax.tree.map(
            lambda buf, val: lax.dynamic_update_index_in_dim(
                buf, jnp.where(fvalid, val, _take_mb(buf, kk % BUF)),
                kk % BUF, 0),
            sbuf, saved)
        _trace("pp.1f1b-ilv send_recv act down", "pp", h_out)
        h_next = lax.ppermute(h_out, "pp", down)
        return (h_next, dh_recv, sbuf, gacc, loss_acc)

    def bwd_half(carry, t):
        h_recv, dh_recv, sbuf, gacc, loss_acc = carry
        j = t - (pp_size - 1 - s) - OFF
        bvalid = (j >= 0) & (j < N)
        jj = jnp.clip(j, 0, N - 1)
        c, m = unit_bwd(jj)
        # fwd index of this unit: k - j = (2c - v + 1) * pp
        k_of_j = jj + (2 * c - v + 1) * pp_size
        saved_b = jax.tree.map(lambda buf: _take_mb(buf, k_of_j % BUF), sbuf)
        is_first = (s == 0) & (c == 0)
        is_last = (s == pp_size - 1) & (c == v - 1)
        dh_out = jnp.where(is_last, jnp.zeros_like(dh_recv), dh_recv)
        dloss = jnp.where(is_last & bvalid, 1.0 / M, 0.0).astype(jnp.float32)
        dparams, dh_prev = stage_bwd(
            chunk_params(c), saved_b, _take_mb(tokens, m), _take_mb(targets, m),
            dh_out, dloss, is_first, is_last)
        dparams = jax.tree.map(lambda g: jnp.where(bvalid, g, 0), dparams)
        # layer grads land in this chunk's rows of the [K]-row accumulator;
        # everything else accumulates whole
        glayers = jax.tree.map(
            lambda acc, g: lax.dynamic_update_slice_in_dim(
                acc,
                lax.dynamic_slice_in_dim(acc, c * Kv, Kv, 0)
                + g.astype(acc_dtype),
                c * Kv, 0),
            gacc["layers"], dparams["layers"])
        gacc = {
            k2: (glayers if k2 == "layers"
                 else jax.tree.map(lambda a, g: a + g.astype(acc_dtype),
                                   gacc[k2], dparams[k2]))
            for k2 in gacc
        }
        _trace("pp.1f1b-ilv send_recv grad up", "pp", dh_prev)
        dh_next = lax.ppermute(dh_prev, "pp", up)
        return (h_recv, dh_next, sbuf, gacc, loss_acc)

    carry = (h0, jnp.zeros(h_shape, h_dtype), sbuf0, gacc0, jnp.float32(0.0))
    carry = _carry_fixpoint(_full_tick(fwd_half, bwd_half), carry)
    carry = _scan_phase(carry, range(OFF), fwd_half)
    carry = _scan_phase(carry, range(OFF, N + pp_size - 1),
                        _full_tick(fwd_half, bwd_half))
    carry = _scan_phase(carry, range(N + pp_size - 1, N + pp_size - 1 + OFF),
                        bwd_half)
    loss_acc, gacc = carry[4], carry[3]
    loss = lax.psum(loss_acc, "pp") / M
    return loss, gacc


def pipeline_1f1b(stage_fwd, stage_bwd, params, tokens, targets, pp_size,
                  h_shape, h_dtype, acc_dtype=jnp.float32):
    """(loss, grads) via the one-forward-one-backward schedule; gradients
    accumulate across microbatch ticks in ``acc_dtype`` (float32 default =
    the reference's main_grad policy; param dtype is the opt-in memory
    saver that lets 7B-class configs fit v5e HBM — docs/PROJECTION.md).

    Tick t: stage s forwards microbatch ``t - s`` and backwards microbatch
    ``t - (2*pp - 2 - s)`` (both masked to [0, M)). The last stage backwards a
    microbatch the same tick it forwards it; stage s lags by pp-1-s ticks —
    the steady state of the reference's schedule (pipeline_parallel.py:86,
    :116-134). dh flows up the pipeline one tick behind the corresponding
    forward, via the reverse ppermute.

    The forward half-tick runs ``stage_fwd`` which also emits a ``saved``
    pytree (layer-boundary activations); a ring buffer holds the saved
    pytrees of in-flight microbatches, and the backward half-tick hands the
    matching slot to ``stage_bwd`` — a manual backward that re-derives each
    *layer's* VJP from its saved input. A steady-state tick therefore costs
    one stage forward + one layer-remat stage backward (≈ 3x fwd FLOPs),
    never a whole-stage forward rebuild, and warmup/cooldown ticks execute
    only their live half (phase split below); see docs/PP_COST.md for the
    measured FLOP accounting. This is the reference's residual-saving
    backward (pipeline_parallel.py:46-52) re-done at layer-checkpoint
    granularity, which is what a 7B-class model needs on TPU HBM anyway.

    stage_fwd(params, h_recv, tok, tgt) -> (h_out, loss, saved)
    stage_bwd(params, saved, tok, tgt, dh_out, dloss) -> (dparams, dh_prev)
    """
    M = tokens.shape[0]
    s = lax.axis_index("pp")
    is_last = s == pp_size - 1
    BUF = 2 * pp_size - 1  # max in-flight microbatches = 2*pp - 2 - 2*s < BUF
    down, up = _down_perm(pp_size), _up_perm(pp_size)

    gacc0 = jax.tree.map(lambda p: jnp.zeros(p.shape, acc_dtype), params)
    h0 = jnp.zeros(h_shape, h_dtype)
    tok0, tgt0 = _take_mb(tokens, 0), _take_mb(targets, 0)
    saved_shape = jax.eval_shape(
        lambda p, h, tok, tgt: stage_fwd(p, h, tok, tgt)[2],
        params, h0, tok0, tgt0)
    sbuf0 = jax.tree.map(
        lambda sh: jnp.zeros((BUF,) + tuple(sh.shape), sh.dtype), saved_shape)

    def fwd_half(carry, t):
        h_recv, dh_recv, sbuf, gacc, loss_acc = carry
        mb_f = t - s
        fvalid = (mb_f >= 0) & (mb_f < M)
        mbf = jnp.clip(mb_f, 0, M - 1)
        h_out, loss_mb, saved = stage_fwd(
            params, h_recv, _take_mb(tokens, mbf), _take_mb(targets, mbf))
        loss_acc = loss_acc + jnp.where(fvalid, loss_mb, 0.0)
        # store this microbatch's boundaries; guarded so bubble ticks can't
        # clobber a slot still awaiting its backward. The select runs on the
        # single slot (read-modify-write), not the whole buffer, so XLA can
        # update sbuf in place instead of copying (L/pp+1) x BUF tensors.
        sbuf = jax.tree.map(
            lambda buf, v: lax.dynamic_update_index_in_dim(
                buf, jnp.where(fvalid, v, _take_mb(buf, mbf % BUF)),
                mbf % BUF, 0),
            sbuf, saved)
        _trace("pp.1f1b send_recv act down", "pp", h_out)
        h_next = lax.ppermute(h_out, "pp", down) if down else jnp.zeros_like(h_out)
        return (h_next, dh_recv, sbuf, gacc, loss_acc)

    def bwd_half(carry, t):
        h_recv, dh_recv, sbuf, gacc, loss_acc = carry
        mb_b = t - (2 * pp_size - 2 - s)
        bvalid = (mb_b >= 0) & (mb_b < M)
        mbb = jnp.clip(mb_b, 0, M - 1)
        saved_b = jax.tree.map(lambda buf: _take_mb(buf, mbb % BUF), sbuf)
        tok_b, tgt_b = _take_mb(tokens, mbb), _take_mb(targets, mbb)
        dh_out = jnp.where(is_last, jnp.zeros_like(dh_recv), dh_recv)
        dloss = jnp.where(is_last & bvalid, 1.0 / M, 0.0).astype(jnp.float32)
        dparams, dh_prev = stage_bwd(params, saved_b, tok_b, tgt_b, dh_out, dloss)
        gacc = jax.tree.map(
            lambda a, g: a + jnp.where(bvalid, g, 0).astype(acc_dtype), gacc, dparams
        )
        _trace("pp.1f1b send_recv grad up", "pp", dh_prev)
        dh_next = lax.ppermute(dh_prev, "pp", up) if up else jnp.zeros_like(dh_prev)
        return (h_recv, dh_next, sbuf, gacc, loss_acc)

    # Three phases so bubble ticks never execute a masked half (a masked
    # backward costs 3x a forward). No stage backwards before tick pp-1 and
    # none forwards after tick M+pp-2, so the split is stage-uniform:
    #   warmup   ticks [0, pp-2]:          forward half only
    #   steady   ticks [pp-1, M+pp-2]:     forward + backward
    #   cooldown ticks [M+pp-1, M+2pp-3]:  backward half only
    # Total critical path = (pp-1) fwd + M (fwd+bwd) + (pp-1) bwd — standard
    # non-interleaved 1F1B (docs/PP_COST.md). The wire crossings match the
    # reference's fused send-fwd/recv-bwd pairs (pp_communications.py:34-46);
    # XLA schedules the two permutes of a steady tick together.
    carry = (h0, jnp.zeros(h_shape, h_dtype), sbuf0, gacc0, jnp.float32(0.0))
    carry = _carry_fixpoint(_full_tick(fwd_half, bwd_half), carry)
    carry = _scan_phase(carry, range(pp_size - 1), fwd_half)
    carry = _scan_phase(carry, range(pp_size - 1, M + pp_size - 1),
                        _full_tick(fwd_half, bwd_half))
    carry = _scan_phase(carry, range(M + pp_size - 1, M + 2 * pp_size - 2),
                        bwd_half)
    loss_acc, gacc = carry[4], carry[3]
    loss = lax.psum(loss_acc, "pp") / M
    return loss, gacc
