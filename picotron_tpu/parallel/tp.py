"""Tensor parallelism: the Megatron f/g conjugate collectives, JAX-style.

The reference implements these as autograd.Function pairs over NCCL
(tensor_parallel/tp_communications.py:19-72):

- f = CopyToModelParallelRegion: identity forward, all-reduce backward —
  placed where a replicated activation enters a column-parallel matmul.
- g = ReduceFromModelParallelRegion: all-reduce forward, identity backward —
  placed after a row-parallel matmul whose output shards are partial sums.
- GatherFromModelParallelRegion: all-gather forward, split backward — used to
  gather vocab-sharded logits (tensor_parallel.py:48-50).

Here each is a ~5-line ``jax.custom_vjp`` around ``lax.psum``/``all_gather``
on the 'tp' mesh axis, usable inside ``shard_map``. The reference's async
all-reduce-overlap variant (LinearWithAsyncAllReduce,
tp_communications.py:74-101) needs no equivalent: XLA's latency-hiding
scheduler overlaps the backward all-reduce with the grad-weight matmul
automatically.

The column/row/vocab-parallel *layers* themselves (reference
tensor_parallel.py:54-271) are not classes here — a column-parallel linear is
just ``tp_copy(x) @ w_shard`` and a row-parallel one ``tp_reduce(x @ w_shard)``
in the model (models/llama.py); the vocab-parallel embedding's mask-and-psum
trick (tensor_parallel.py:246-271) lives in models/llama.py:embed_lookup.
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax

from picotron_tpu.comm_trace import log as _trace


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_copy(x, axis: str = "tp"):
    """Identity forward / psum backward (Megatron f, tp_communications.py:19-33)."""
    return x


def _tp_copy_fwd(x, axis):
    return x, None


def _tp_copy_bwd(axis, _, g):
    _trace("tp_copy.bwd all_reduce", axis, g)
    return (lax.psum(g, axis),)


tp_copy.defvjp(_tp_copy_fwd, _tp_copy_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_reduce(x, axis: str = "tp"):
    """psum forward / identity backward (Megatron g, tp_communications.py:35-49)."""
    _trace("tp_reduce.fwd all_reduce", axis, x)
    return lax.psum(x, axis)


def _tp_reduce_fwd(x, axis):
    _trace("tp_reduce.fwd all_reduce", axis, x)
    return lax.psum(x, axis), None


def _tp_reduce_bwd(axis, _, g):
    return (g,)


tp_reduce.defvjp(_tp_reduce_fwd, _tp_reduce_bwd)


# --------------------------------------------------------------------------- #
# Sequence parallelism (Megatron-SP): between the TP blocks the activation's
# *sequence* axis is sharded over 'tp' instead of replicated. The f/g pair
# becomes g-bar/f-bar: entering a column-parallel matmul the seq shards are
# all-gathered; leaving a row-parallel matmul the partial sums are
# reduce-scattered back to seq shards (psum = all-gather + reduce-scatter, so
# the wire cost is identical to plain TP while the residual stream, norms and
# saved layer boundaries shrink by 1/tp). The reference only TODOs this
# (utils.py:66 "LayerNorm is also split across TP ranks"); SURVEY.md §2.3
# marks it nearly free in JAX. Norm-weight gradients become partial over the
# local seq shard and are psum'd over 'tp' in the train step
# (train_step.sync_sp_norm_grads).
# --------------------------------------------------------------------------- #


def all_gather_dim(x, axis: str, dim: int):
    """Tiled all-gather along array dimension ``dim`` over mesh axis ``axis``.
    Public building block shared by the SP collectives and the ZeRO-1 param
    all-gather (train_step)."""
    _trace("all_gather", axis, x, extra=f"dim={dim}")
    return lax.all_gather(x, axis, axis=dim, tiled=True)


def all_gather_dim_invariant(x, axis: str, dim: int):
    """``all_gather_dim`` whose result is TYPED replicated over ``axis``
    under shard_map's varying-axes checker — every rank contributes its
    shard and receives the same whole, and there is no legal demotion from
    a varying-typed plain gather. Falls back to the plain gather when the
    trace is not vma-typed (the invariant primitive's vjp demands
    vma-typed operands and fails on a checker-off build). Single home for
    the jax-internal import: consumers are the ZeRO-1 param unsplit
    (train_step) and the gathered CE loss (ops/cross_entropy)."""
    from picotron_tpu.utils import typeof_vma

    if axis in typeof_vma(x):
        try:
            # jax-internal: the invariant gather has no public spelling yet.
            # Reached only under check_vma=True (a vma-typed trace), which
            # itself requires a jax.shard_map-era release — so a failure
            # here means a jax upgrade moved/removed the private symbol.
            from jax._src.lax.parallel import all_gather_invariant
        except ImportError as e:
            import jax

            raise ImportError(
                "check_vma=True needs jax._src.lax.parallel."
                "all_gather_invariant (present in jax >= 0.6 releases with "
                f"jax.shard_map's vma checker); this jax build "
                f"({jax.__version__}) does not provide it — upgrade/"
                "downgrade jax or run with distributed.check_vma=false"
            ) from e

        _trace("all_gather", axis, x, extra=f"dim={dim} invariant")
        return all_gather_invariant(x, axis, axis=dim, tiled=True)
    return all_gather_dim(x, axis, dim)


def reduce_scatter_dim(x, axis: str, dim: int):
    """Tiled reduce-scatter along array dimension ``dim`` over mesh axis
    ``axis``. Public building block shared by the SP collectives and the
    ZeRO-1 gradient reduce-scatter (train_step)."""
    _trace("reduce_scatter", axis, x, extra=f"dim={dim}")
    return lax.psum_scatter(x, axis, scatter_dimension=dim, tiled=True)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def sp_gather(x, axis: str = "tp", dim: int = 1):
    """Seq all-gather forward / reduce-scatter backward (Megatron-SP g-bar):
    [B, S/tp, ...] -> [B, S, ...] entering a column-parallel region."""
    return all_gather_dim(x, axis, dim)


def _sp_gather_fwd(x, axis, dim):
    return all_gather_dim(x, axis, dim), None


def _sp_gather_bwd(axis, dim, _, g):
    return (reduce_scatter_dim(g, axis, dim),)


sp_gather.defvjp(_sp_gather_fwd, _sp_gather_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def sp_scatter(x, axis: str = "tp", dim: int = 1):
    """Seq reduce-scatter forward / all-gather backward (Megatron-SP f-bar):
    partial-sum [B, S, ...] -> reduced [B, S/tp, ...] leaving a row-parallel
    region. Replaces ``tp_reduce`` when sequence parallelism is on."""
    return reduce_scatter_dim(x, axis, dim)


def _sp_scatter_fwd(x, axis, dim):
    return reduce_scatter_dim(x, axis, dim), None


def _sp_scatter_bwd(axis, dim, _, g):
    return (all_gather_dim(g, axis, dim),)


sp_scatter.defvjp(_sp_scatter_fwd, _sp_scatter_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_gather(x, axis: str = "tp"):
    """All-gather on the last dim forward / take-own-slice backward
    (GatherFromModelParallelRegion, tp_communications.py:51-72)."""
    _trace("tp_gather.fwd all_gather", axis, x)
    return lax.all_gather(x, axis, axis=-1, tiled=True)


def _tp_gather_fwd(x, axis):
    _trace("tp_gather.fwd all_gather", axis, x)
    return lax.all_gather(x, axis, axis=-1, tiled=True), x.shape[-1]


def _tp_gather_bwd(axis, local_dim, g):
    idx = lax.axis_index(axis)
    return (lax.dynamic_slice_in_dim(g, idx * local_dim, local_dim, axis=-1),)


tp_gather.defvjp(_tp_gather_fwd, _tp_gather_bwd)
