"""Context parallelism: ring attention over the 'cp' mesh axis.

TPU-native re-design of the reference's RingAttentionFunc
(context_parallel/context_parallel.py:19-110): the async NCCL isend/irecv ring
(cp_communications.py:22-53) becomes ``lax.ppermute`` — XLA double-buffers the
permute against the block compute, which is exactly what the reference's
commit()/wait() staging achieves by hand.

Semantics preserved from the reference:
- contiguous (non-zigzag) sequence chunks: rank r owns queries/keys for global
  positions [r*S_local, (r+1)*S_local)  (data.py:102-116 slicing);
- causal block schedule: the block from source rank ``src`` contributes iff
  ``src <= r`` (context_parallel.py:36), diagonal block causally masked;
- numerically-stable LSE merge of partial outputs
  (update_out_and_lse, context_parallel.py:157-187);
- backward re-derives P from the saved LSE and sends the dK/dV accumulators
  around the ring alongside K/V so each contribution lands on the owning rank
  (the reference's second ring channel, context_parallel.py:60-110).

The known load imbalance of non-zigzag causal ring attention (acknowledged at
reference tests/test_dataloader.py:136) is faithful: in SPMD every rank runs
the full schedule, masking skipped blocks, so the wall-clock matches the
reference's slowest (last) rank. Zigzag is the first post-parity optimization.

Unlike the reference (pure-torch block math, TODO for flash at
context_parallel.py:22-23), the inner block runs through ops.block_attention,
which XLA fuses; a Pallas block kernel can be swapped in transparently.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from picotron_tpu.ops.attention import NEG_INF, block_attention
from picotron_tpu.utils import collective_scan_unroll


def _block_mask(s_q: int, s_k: int, src, rank, causal: bool):
    """True = attend. src/rank are traced cp indices; contiguous chunking means
    src < rank -> keys strictly before queries (attend all), src == rank ->
    diagonal causal block, src > rank -> keys after queries (skip)."""
    if not causal:
        return jnp.ones((s_q, s_k), dtype=bool)
    tri = jnp.arange(s_q)[:, None] >= jnp.arange(s_k)[None, :]
    full = jnp.ones_like(tri)
    none = jnp.zeros_like(tri)
    return jnp.where(src < rank, full, jnp.where(src == rank, tri, none))


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_attention(q, k, v, scale: float, axis: str, axis_size: int, causal: bool):
    """q, k, v: [B, S_local, H, D] (kv heads already GQA-repeated, as the
    reference repeats before the ring, model.py:141-142). Returns [B,S,H,D]."""
    out, _ = _ring_fwd_impl(q, k, v, scale, axis, axis_size, causal)
    return out


def _ring_fwd_impl(q, k, v, scale, axis, n, causal):
    rank = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    b, s, h, d = q.shape
    out0 = jnp.zeros((b, s, h, d), jnp.float32)
    lse0 = jnp.full((b, s, h), NEG_INF, jnp.float32)

    def step(carry, t):
        kv, out, lse = carry
        kt, vt = kv
        src = (rank - t) % n
        mask = _block_mask(s, s, src, rank, causal)
        blk_out, blk_lse = block_attention(q, kt, vt, scale, mask)
        # LSE merge (reference context_parallel.py:170-171):
        #   out <- out - sigmoid(blk_lse - lse) * (out - blk_out)
        #   lse <- logaddexp(lse, blk_lse)
        w = jax.nn.sigmoid(blk_lse - lse)[..., None]
        merged_out = out - w * (out - blk_out)
        merged_lse = jnp.logaddexp(lse, blk_lse)
        valid = jnp.logical_not(causal) | (src <= rank)
        out = jnp.where(valid, merged_out, out)
        lse = jnp.where(valid, merged_lse, lse)
        kv = lax.ppermute(kv, axis, perm)
        return (kv, out, lse), None

    (kv, out, lse), _ = lax.scan(step, ((k, v), out0, lse0), jnp.arange(n),
                                 unroll=collective_scan_unroll())
    return out.astype(q.dtype), lse


def _ring_fwd(q, k, v, scale, axis, n, causal):
    out, lse = _ring_fwd_impl(q, k, v, scale, axis, n, causal)
    return out, (q, k, v, out, lse)


def _ring_bwd(scale, axis, n, causal, res, dout):
    q, k, v, out, lse = res
    rank = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    b, s, h, d = q.shape

    q32 = q.astype(jnp.float32)
    do32 = dout.astype(jnp.float32)
    # D_i = sum_j dO_ij * O_ij (softmax backward rowsum, the reference's manual
    # 6-step derivation, context_parallel.py:130-155)
    D = jnp.sum(do32 * out.astype(jnp.float32), axis=-1)  # [B, S, H]
    D_t = D.transpose(0, 2, 1)[..., None]  # [B, H, Sq, 1]
    lse_t = lse.transpose(0, 2, 1)[..., None]  # [B, H, Sq, 1]

    dq0 = jnp.zeros((b, s, h, d), jnp.float32)
    dkv0 = (jnp.zeros((b, s, h, d), jnp.float32), jnp.zeros((b, s, h, d), jnp.float32))

    def step(carry, t):
        kv, dkv, dq = carry
        kt, vt = kv
        dk_acc, dv_acc = dkv
        src = (rank - t) % n
        mask = _block_mask(s, s, src, rank, causal)

        k32 = kt.astype(jnp.float32)
        v32 = vt.astype(jnp.float32)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q32, k32) * scale
        # P re-derived from the final LSE: exp(scores - lse) is each block's
        # true share of the global softmax (context_parallel.py:112-128).
        p = jnp.where(mask[None, None], jnp.exp(scores - lse_t), 0.0)
        dv_blk = jnp.einsum("bhqk,bqhd->bkhd", p, do32)
        dp = jnp.einsum("bqhd,bkhd->bhqk", do32, v32)
        ds = p * (dp - D_t) * scale
        dq_blk = jnp.einsum("bhqk,bkhd->bqhd", ds, k32)
        dk_blk = jnp.einsum("bhqk,bqhd->bkhd", ds, q32)

        dq = dq + dq_blk
        # accumulators travel the ring with their kv chunk and arrive home
        # after n rotations (reference's d_kv_comm channel,
        # context_parallel.py:104-106)
        dkv = (dk_acc + dk_blk, dv_acc + dv_blk)
        kv, dkv = lax.ppermute((kv, dkv), axis, perm)
        return (kv, dkv, dq), None

    (kv, dkv, dq), _ = lax.scan(step, ((k, v), dkv0, dq0), jnp.arange(n),
                                unroll=collective_scan_unroll())
    dk, dv = dkv
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_attention.defvjp(_ring_fwd, _ring_bwd)
