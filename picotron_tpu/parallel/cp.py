"""Context parallelism: ring attention over the 'cp' mesh axis.

TPU-native re-design of the reference's RingAttentionFunc
(context_parallel/context_parallel.py:19-110): the async NCCL isend/irecv ring
(cp_communications.py:22-53) becomes ``lax.ppermute`` — XLA double-buffers the
permute against the block compute, which is exactly what the reference's
commit()/wait() staging achieves by hand.

Semantics preserved from the reference:
- contiguous (non-zigzag) sequence chunks: rank r owns queries/keys for global
  positions [r*S_local, (r+1)*S_local)  (data.py:102-116 slicing);
- causal block schedule: the block from source rank ``src`` contributes iff
  ``src <= r`` (context_parallel.py:36), diagonal block causally masked;
- numerically-stable LSE merge of partial outputs
  (update_out_and_lse, context_parallel.py:157-187);
- backward re-derives P from the saved LSE and sends the dK/dV accumulators
  around the ring alongside K/V so each contribution lands on the owning rank
  (the reference's second ring channel, context_parallel.py:60-110).

The known load imbalance of non-zigzag causal ring attention (acknowledged at
reference tests/test_dataloader.py:136) is faithful: in SPMD every rank runs
the full schedule, masking skipped blocks, so the wall-clock matches the
reference's slowest (last) rank. Zigzag is the first post-parity optimization.

Unlike the reference (pure-torch block math, TODO for flash at
context_parallel.py:22-23), the inner block can run through the Pallas flash
kernel (``use_flash=True``, the TPU path): per ring step a ``lax.switch``
picks the causal-diagonal kernel, the unmasked kernel, or a skip — so
skipped blocks genuinely cost nothing, and the [S_local, S_local] score
matrix never exists in HBM. The XLA ``block_attention`` einsum path remains
for CPU and as the numerics oracle.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from picotron_tpu.ops.attention import NEG_INF, block_attention
from picotron_tpu.comm_trace import log as _trace
from picotron_tpu.utils import collective_scan_unroll, pvary_like


def chunk_positions(idx, s_local: int, n: int, zigzag: bool):
    """Global token positions held by cp index ``idx`` (traced ok).
    Contiguous: [idx*S_l, (idx+1)*S_l). Zigzag: the sequence is cut into 2n
    chunks and rank r owns chunks (r, 2n-1-r) — the standard load-balanced
    layout for causal ring attention (the reference acknowledges the
    contiguous imbalance at tests/test_dataloader.py:136 and leaves zigzag
    as a TODO)."""
    if not zigzag:
        return idx * s_local + jnp.arange(s_local)
    h = s_local // 2
    return jnp.concatenate([idx * h + jnp.arange(h),
                            (2 * n - 1 - idx) * h + jnp.arange(h)])


def zigzag_perm(seq_length: int, n: int) -> "np.ndarray":
    """Host-side permutation: position j of the permuted sequence holds
    original token perm[j]; contiguous shard r of the permuted sequence then
    owns exactly chunks (r, 2n-1-r) of the original."""
    import numpy as np

    h = seq_length // (2 * n)
    order = []
    for r in range(n):
        order.extend(range(r * h, (r + 1) * h))
        order.extend(range((2 * n - 1 - r) * h, (2 * n - r) * h))
    return np.asarray(order, dtype=np.int64)


def zigzag_inverse_perm(seq_length: int, n: int) -> "np.ndarray":
    """Inverse of ``zigzag_perm``: maps zigzag-layout sequence arrays back to
    original token order — ``arr_orig = arr_zig[..., inv]``. Use on per-token
    outputs (e.g. ``forward_logits`` of a zigzag-fed model) before comparing
    against original-order references."""
    import numpy as np

    perm = zigzag_perm(seq_length, n)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(seq_length, dtype=np.int64)
    return inv


def _block_mask(s_q: int, s_k: int, src, rank, causal: bool, n: int,
                zigzag: bool):
    """True = attend: global position of query >= global position of key.
    For contiguous chunking this reduces to the reference's 3-way rule
    (src < rank full, src == rank diagonal, src > rank skip,
    context_parallel.py:36)."""
    if not causal:
        return jnp.ones((s_q, s_k), dtype=bool)
    qpos = chunk_positions(rank, s_q, n, zigzag)
    kpos = chunk_positions(src, s_k, n, zigzag)
    return qpos[:, None] >= kpos[None, :]


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
def ring_attention(q, k, v, scale: float, axis: str, axis_size: int,
                   causal: bool, use_flash: bool = False,
                   zigzag: bool = False, block_q: int | None = None,
                   block_k: int | None = None,
                   flash_layout: str = "folded"):
    """q: [B, S_local, Hq, D]; k, v: [B, S_local, Hkv, D] with
    Hq % Hkv == 0. Returns [B, S, Hq, D]. GQA-aware: unlike the reference
    (which repeats kv heads BEFORE the ring, model.py:141-142), the ring
    circulates the compact Hkv-head K/V and dK/dV — Hq/Hkv x less ICI
    traffic for grouped-query models — expanding to Hq only at each block
    compute (and group-summing the grads back, the repeat's transpose).
    use_flash selects the Pallas block kernel (TPU) over the XLA einsum;
    zigzag expects the zigzag_perm() sequence layout and balances causal
    work across ranks."""
    out, _ = _ring_fwd_impl(q, k, v, scale, axis, axis_size, causal,
                            use_flash, zigzag, block_q, block_k, flash_layout)
    return out


def _gqa_expand(x, g: int):
    """[B, S, Hkv, D] -> [B, S, Hkv*g, D] by repeating each kv head g times
    (identity when g == 1)."""
    return jnp.repeat(x, g, axis=2) if g > 1 else x


def _gqa_fold(dx, g: int):
    """Transpose of _gqa_expand: group-sum [B, S, Hkv*g, D] -> [B, S, Hkv, D]."""
    if g == 1:
        return dx
    b, s, h, d = dx.shape
    return dx.reshape(b, s, h // g, g, d).sum(axis=3)


def _block_fwd(q, kt, vt, scale, src, rank, causal, use_flash, n, zigzag,
               block_q=None, block_k=None, flash_layout="folded"):
    """One ring block -> (out [B,S,H,D] fp32, lse [B,S,H] fp32), with skipped
    (sub-)blocks returning lse=-inf rows (identity under the merge)."""
    b, s, h, d = q.shape
    if not use_flash:
        mask = _block_mask(s, s, src, rank, causal, n, zigzag)
        blk_out, blk_lse = block_attention(q, kt, vt, scale, mask)
        # fully-masked rows carry lse ~ NEG_INF + log(s): tiny enough that
        # the sigmoid merge weight is exactly 0 against any real lse
        return blk_out.astype(jnp.float32), blk_lse

    from picotron_tpu.ops.pallas.flash_attention import flash_attention_with_lse

    flash = partial(flash_attention_with_lse, scale=scale,
                    block_q=block_q, block_k=block_k, layout=flash_layout)

    def full(_):
        o, l = flash(q, kt, vt, causal=False)
        return o.astype(jnp.float32), l

    def diag(_):
        # zigzag local pair (r, 2n-1-r) is position-monotonic, so the
        # diagonal step is a plain causal block in both layouts
        o, l = flash(q, kt, vt, causal=True)
        return o.astype(jnp.float32), l

    def skip(_):
        return (jnp.zeros((b, s, h, d), jnp.float32),
                jnp.full((b, s, h), NEG_INF, jnp.float32))

    def early(_):
        # zigzag, src < rank: every query sees only the source's early half
        o, l = flash(q, kt[:, : s // 2], vt[:, : s // 2], causal=False)
        return o.astype(jnp.float32), l

    def late(_):
        # zigzag, src > rank: only this rank's late half sees the source
        # (its whole chunk pair); early-half rows merge as identity
        o, l = flash(q[:, s // 2:], kt, vt, causal=False)
        return (jnp.concatenate(
                    [jnp.zeros((b, s // 2, h, d), jnp.float32),
                     o.astype(jnp.float32)], axis=1),
                jnp.concatenate(
                    [jnp.full((b, s // 2, h), NEG_INF, jnp.float32), l],
                    axis=1))

    if not causal:
        return full(None)
    # 0 = src > rank, 1 = src < rank, 2 = diagonal
    idx = jnp.where(src == rank, 2, jnp.where(src < rank, 1, 0))
    if zigzag:
        return lax.switch(idx, [late, early, diag], None)
    return lax.switch(idx, [skip, full, diag], None)


def _ring_fwd_impl(q, k, v, scale, axis, n, causal, use_flash, zigzag,
                   block_q=None, block_k=None, flash_layout="folded"):
    rank = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    b, s, h, d = q.shape
    if h % k.shape[2]:
        raise ValueError(
            f"ring_attention: q heads ({h}) must be a multiple of kv heads "
            f"({k.shape[2]})")
    g = h // k.shape[2]  # GQA group size; the ring carries Hkv-head chunks
    # vma cast: the accumulators absorb q@k terms, so the scan carry must
    # enter varying over everything q/k/v vary over (check_vma)
    out0 = pvary_like(jnp.zeros((b, s, h, d), jnp.float32), q, k, v)
    lse0 = pvary_like(jnp.full((b, s, h), NEG_INF, jnp.float32), q, k, v)

    def step(carry, t):
        kv, out, lse = carry
        kt, vt = _gqa_expand(kv[0], g), _gqa_expand(kv[1], g)
        src = (rank - t) % n
        blk_out, blk_lse = _block_fwd(q, kt, vt, scale, src, rank, causal,
                                      use_flash, n, zigzag, block_q, block_k,
                                      flash_layout)
        # LSE merge (reference context_parallel.py:170-171):
        #   out <- out - sigmoid(blk_lse - lse) * (out - blk_out)
        #   lse <- logaddexp(lse, blk_lse)
        w = jax.nn.sigmoid(blk_lse - lse)[..., None]
        out = out - w * (out - blk_out)
        lse = jnp.logaddexp(lse, blk_lse)
        _trace("ring.fwd send_recv kv", axis, kv[0], extra=f"ring_steps={n}")
        kv = lax.ppermute(kv, axis, perm)
        return (kv, out, lse), None

    (kv, out, lse), _ = lax.scan(step, ((k, v), out0, lse0), jnp.arange(n),
                                 unroll=collective_scan_unroll())
    return out.astype(q.dtype), lse


def _ring_fwd(q, k, v, scale, axis, n, causal, use_flash, zigzag,
              block_q=None, block_k=None, flash_layout="folded"):
    out, lse = _ring_fwd_impl(q, k, v, scale, axis, n, causal, use_flash,
                              zigzag, block_q, block_k, flash_layout)
    return out, (q, k, v, out, lse)


def _block_bwd_einsum(q, kt, vt, dout, out_unused, lse, D, scale, src, rank,
                      causal, n, zigzag):
    """One block's (dq, dk, dv) via XLA einsums; P re-derived from the final
    LSE: exp(scores - lse) is each block's true share of the global softmax
    (context_parallel.py:112-128)."""
    s = q.shape[1]
    mask = _block_mask(s, s, src, rank, causal, n, zigzag)
    q32 = q.astype(jnp.float32)
    do32 = dout.astype(jnp.float32)
    k32 = kt.astype(jnp.float32)
    v32 = vt.astype(jnp.float32)
    lse_t = lse.transpose(0, 2, 1)[..., None]  # [B, H, Sq, 1]
    D_t = D.transpose(0, 2, 1)[..., None]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q32, k32) * scale
    p = jnp.where(mask[None, None], jnp.exp(scores - lse_t), 0.0)
    dv_blk = jnp.einsum("bhqk,bqhd->bkhd", p, do32)
    dp = jnp.einsum("bqhd,bkhd->bhqk", do32, v32)
    ds = p * (dp - D_t) * scale
    dq_blk = jnp.einsum("bhqk,bkhd->bqhd", ds, k32)
    dk_blk = jnp.einsum("bhqk,bqhd->bkhd", ds, q32)
    return dq_blk, dk_blk, dv_blk


def _block_bwd_flash(q, kt, vt, dout, out, lse, scale, src, rank, causal,
                     zigzag, block_q=None, block_k=None,
                     flash_layout="folded"):
    """One block's (dq, dk, dv) via the Pallas backward kernels fed the
    globally-merged out/lse (skip branch costs nothing at runtime)."""
    from picotron_tpu.ops.pallas.flash_attention import flash_block_grads

    b, s, h, d = q.shape
    f32 = lambda t: tuple(x.astype(jnp.float32) for x in t)
    grads = partial(flash_block_grads, scale=scale,
                    block_q=block_q, block_k=block_k, layout=flash_layout)

    def full(_):
        return f32(grads(q, kt, vt, out, lse, dout, causal=False))

    def diag(_):
        return f32(grads(q, kt, vt, out, lse, dout, causal=True))

    def skip(_):
        z = jnp.zeros(q.shape, jnp.float32)
        return z, z, z

    def early(_):
        # zigzag, src < rank: all queries x source's early kv half
        dq, dk_h, dv_h = f32(grads(
            q, kt[:, : s // 2], vt[:, : s // 2], out, lse, dout, causal=False))
        zpad = jnp.zeros((b, s - s // 2, h, d), jnp.float32)
        return (dq, jnp.concatenate([dk_h, zpad], axis=1),
                jnp.concatenate([dv_h, zpad], axis=1))

    def late(_):
        # zigzag, src > rank: late query half x full source kv
        dq_h, dk, dv = f32(grads(
            q[:, s // 2:], kt, vt, out[:, s // 2:], lse[:, s // 2:],
            dout[:, s // 2:], causal=False))
        zpad = jnp.zeros((b, s // 2, h, d), jnp.float32)
        return jnp.concatenate([zpad, dq_h], axis=1), dk, dv

    if not causal:
        return full(None)
    idx = jnp.where(src == rank, 2, jnp.where(src < rank, 1, 0))
    if zigzag:
        return lax.switch(idx, [late, early, diag], None)
    return lax.switch(idx, [skip, full, diag], None)


def _ring_bwd(scale, axis, n, causal, use_flash, zigzag, block_q, block_k,
              flash_layout, res, dout):
    q, k, v, out, lse = res
    rank = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    b, s, h, d = q.shape
    g = h // k.shape[2]  # dK/dV ride the ring group-summed to Hkv heads

    # D_i = sum_j dO_ij * O_ij (softmax backward rowsum, the reference's manual
    # 6-step derivation, context_parallel.py:130-155)
    D = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    dq0 = pvary_like(jnp.zeros((b, s, h, d), jnp.float32), q, k, v, dout)
    hkv = h // g
    dkv0 = pvary_like((jnp.zeros((b, s, hkv, d), jnp.float32),
                       jnp.zeros((b, s, hkv, d), jnp.float32)),
                      q, k, v, dout)

    def step(carry, t):
        kv, dkv, dq = carry
        kt, vt = _gqa_expand(kv[0], g), _gqa_expand(kv[1], g)
        dk_acc, dv_acc = dkv
        src = (rank - t) % n
        if use_flash:
            dq_blk, dk_blk, dv_blk = _block_bwd_flash(
                q, kt, vt, dout, out, lse, scale, src, rank, causal, zigzag,
                block_q, block_k, flash_layout)
        else:
            dq_blk, dk_blk, dv_blk = _block_bwd_einsum(
                q, kt, vt, dout, out, lse, D, scale, src, rank, causal, n,
                zigzag)

        dq = dq + dq_blk
        # accumulators travel the ring with their kv chunk and arrive home
        # after n rotations (reference's d_kv_comm channel,
        # context_parallel.py:104-106), group-summed to the compact Hkv heads
        dkv = (dk_acc + _gqa_fold(dk_blk, g), dv_acc + _gqa_fold(dv_blk, g))
        _trace("ring.bwd send_recv kv+dkv", axis, kv[0], extra=f"ring_steps={n}")
        kv, dkv = lax.ppermute((kv, dkv), axis, perm)
        return (kv, dkv, dq), None

    (kv, dkv, dq), _ = lax.scan(step, ((k, v), dkv0, dq0), jnp.arange(n),
                                unroll=collective_scan_unroll())
    dk, dv = dkv
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_attention.defvjp(_ring_fwd, _ring_bwd)


# --------------------------------------------------------------------------- #
# Ulysses (DeepSpeed-style all-to-all sequence parallelism) — the second
# long-context mode, beyond the reference (SURVEY §2.3 marks Ulysses out of
# its scope). Instead of rotating K/V around a ring, one all-to-all swaps
# the sharded dimension from sequence to heads: every rank then holds the
# FULL sequence for H/cp heads and runs one ordinary (flash) causal
# attention; a second all-to-all swaps back. Wire cost is 4 all-to-alls per
# forward (q, k, v in; o out — the DeepSpeed-Ulysses accounting; 8 with the
# backward transposes, vs n ppermute rounds of K/V), compute is perfectly
# balanced with no masked/skipped blocks — preferable when heads >> cp and
# ICI all-to-all bandwidth is good. Gradients need no custom VJP: the
# transpose of all-to-all is the reverse all-to-all, and the inner
# attention brings its own.
# --------------------------------------------------------------------------- #


def ulysses_attention(q, k, v, scale: float, axis: str, axis_size: int,
                      causal: bool, use_flash: bool = False,
                      block_q: int | None = None,
                      block_k: int | None = None,
                      flash_layout: str = "folded"):
    """q: [B, S_local, Hq, D]; k, v: [B, S_local, Hkv, D], sequence
    CONTIGUOUSLY sharded over ``axis`` (no zigzag — Ulysses is
    load-balanced by construction), Hq % axis_size == 0. GQA-aware: when
    Hkv % axis_size == 0 the compact kv heads ride the all-to-alls
    (Hq/Hkv x less wire on 2 of the 3 inbound reshards) and are expanded
    to Hq only after resharding; otherwise the caller pre-repeats (the
    model layer handles this). Returns [B, S_local, Hq, D]."""
    n = axis_size

    def seq_to_heads(x):  # [B, S/n, H, D] -> [B, S, H/n, D]
        _trace("ulysses all_to_all seq->heads", axis, x)
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):  # [B, S, H/n, D] -> [B, S/n, H, D]
        _trace("ulysses all_to_all heads->seq", axis, x)
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    g = q.shape[2] // k.shape[2]
    if (q.shape[2] % k.shape[2] or q.shape[2] % n
            or (g > 1 and k.shape[2] % n)):
        raise ValueError(
            f"ulysses_attention: q heads ({q.shape[2]}) must be a multiple "
            f"of kv heads ({k.shape[2]}) and divisible by cp ({n}), and "
            f"compact GQA kv heads must be divisible by cp — pre-repeat kv "
            f"otherwise")
    if n == 1:
        qf, kf, vf = q, k, v
    else:
        qf, kf, vf = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # expand AFTER the reshard: [B, S, Hkv/n, D] -> [B, S, Hq/n, D]; the
    # grads of repeat (a group-sum) transpose back through the reverse
    # all-to-all automatically
    kf, vf = _gqa_expand(kf, g), _gqa_expand(vf, g)
    if use_flash:
        from picotron_tpu.ops.pallas.flash_attention import flash_attention

        o = flash_attention(qf, kf, vf, scale, causal=causal,
                            block_q=block_q, block_k=block_k,
                            layout=flash_layout)
    else:
        from picotron_tpu.ops.attention import sdpa

        o = sdpa(qf, kf, vf, scale, causal=causal)
    return o if n == 1 else heads_to_seq(o)
