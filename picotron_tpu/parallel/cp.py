"""Context parallelism: ring attention over the 'cp' mesh axis.

TPU-native re-design of the reference's RingAttentionFunc
(context_parallel/context_parallel.py:19-110): the async NCCL isend/irecv ring
(cp_communications.py:22-53) becomes ``lax.ppermute`` — XLA double-buffers the
permute against the block compute, which is exactly what the reference's
commit()/wait() staging achieves by hand.

Semantics preserved from the reference:
- contiguous (non-zigzag) sequence chunks: rank r owns queries/keys for global
  positions [r*S_local, (r+1)*S_local)  (data.py:102-116 slicing);
- causal block schedule: the block from source rank ``src`` contributes iff
  ``src <= r`` (context_parallel.py:36), diagonal block causally masked;
- numerically-stable LSE merge of partial outputs
  (update_out_and_lse, context_parallel.py:157-187);
- backward re-derives P from the saved LSE and sends the dK/dV accumulators
  around the ring alongside K/V so each contribution lands on the owning rank
  (the reference's second ring channel, context_parallel.py:60-110).

The known load imbalance of non-zigzag causal ring attention (acknowledged at
reference tests/test_dataloader.py:136) is faithful: in SPMD every rank runs
the full schedule, masking skipped blocks, so the wall-clock matches the
reference's slowest (last) rank. Zigzag is the first post-parity optimization.

Unlike the reference (pure-torch block math, TODO for flash at
context_parallel.py:22-23), the inner block can run through the Pallas flash
kernel (``use_flash=True``, the TPU path): per ring step a ``lax.switch``
picks the causal-diagonal kernel, the unmasked kernel, or a skip — so
skipped blocks genuinely cost nothing, and the [S_local, S_local] score
matrix never exists in HBM. The XLA ``block_attention`` einsum path remains
for CPU and as the numerics oracle.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from picotron_tpu.ops.attention import NEG_INF, block_attention
from picotron_tpu.utils import collective_scan_unroll


def _block_mask(s_q: int, s_k: int, src, rank, causal: bool):
    """True = attend. src/rank are traced cp indices; contiguous chunking means
    src < rank -> keys strictly before queries (attend all), src == rank ->
    diagonal causal block, src > rank -> keys after queries (skip)."""
    if not causal:
        return jnp.ones((s_q, s_k), dtype=bool)
    tri = jnp.arange(s_q)[:, None] >= jnp.arange(s_k)[None, :]
    full = jnp.ones_like(tri)
    none = jnp.zeros_like(tri)
    return jnp.where(src < rank, full, jnp.where(src == rank, tri, none))


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def ring_attention(q, k, v, scale: float, axis: str, axis_size: int,
                   causal: bool, use_flash: bool = False):
    """q, k, v: [B, S_local, H, D] (kv heads already GQA-repeated, as the
    reference repeats before the ring, model.py:141-142). Returns [B,S,H,D].
    use_flash selects the Pallas block kernel (TPU) over the XLA einsum."""
    out, _ = _ring_fwd_impl(q, k, v, scale, axis, axis_size, causal, use_flash)
    return out


def _block_fwd(q, kt, vt, scale, src, rank, causal, use_flash):
    """One ring block -> (out [B,S,H,D] fp32, lse [B,S,H] fp32), with skipped
    blocks returning lse=-inf (identity under the merge)."""
    b, s, h, d = q.shape
    if not use_flash:
        mask = _block_mask(s, s, src, rank, causal)
        blk_out, blk_lse = block_attention(q, kt, vt, scale, mask)
        if causal:
            valid = src <= rank
            blk_out = jnp.where(valid, blk_out, 0.0)
            blk_lse = jnp.where(valid, blk_lse, NEG_INF)
        return blk_out.astype(jnp.float32), blk_lse

    from picotron_tpu.ops.pallas.flash_attention import flash_attention_with_lse

    def full(_):
        o, l = flash_attention_with_lse(q, kt, vt, scale, causal=False)
        return o.astype(jnp.float32), l

    def diag(_):
        o, l = flash_attention_with_lse(q, kt, vt, scale, causal=True)
        return o.astype(jnp.float32), l

    def skip(_):
        return (jnp.zeros((b, s, h, d), jnp.float32),
                jnp.full((b, s, h), NEG_INF, jnp.float32))

    if not causal:
        return full(None)
    # 0 = skip (src > rank), 1 = unmasked (src < rank), 2 = diagonal causal
    idx = jnp.where(src == rank, 2, jnp.where(src < rank, 1, 0))
    return lax.switch(idx, [skip, full, diag], None)


def _ring_fwd_impl(q, k, v, scale, axis, n, causal, use_flash):
    rank = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    b, s, h, d = q.shape
    out0 = jnp.zeros((b, s, h, d), jnp.float32)
    lse0 = jnp.full((b, s, h), NEG_INF, jnp.float32)

    def step(carry, t):
        kv, out, lse = carry
        kt, vt = kv
        src = (rank - t) % n
        blk_out, blk_lse = _block_fwd(q, kt, vt, scale, src, rank, causal,
                                      use_flash)
        # LSE merge (reference context_parallel.py:170-171):
        #   out <- out - sigmoid(blk_lse - lse) * (out - blk_out)
        #   lse <- logaddexp(lse, blk_lse)
        w = jax.nn.sigmoid(blk_lse - lse)[..., None]
        out = out - w * (out - blk_out)
        lse = jnp.logaddexp(lse, blk_lse)
        kv = lax.ppermute(kv, axis, perm)
        return (kv, out, lse), None

    (kv, out, lse), _ = lax.scan(step, ((k, v), out0, lse0), jnp.arange(n),
                                 unroll=collective_scan_unroll())
    return out.astype(q.dtype), lse


def _ring_fwd(q, k, v, scale, axis, n, causal, use_flash):
    out, lse = _ring_fwd_impl(q, k, v, scale, axis, n, causal, use_flash)
    return out, (q, k, v, out, lse)


def _block_bwd_einsum(q, kt, vt, dout, out_unused, lse, D, scale, src, rank,
                      causal):
    """One block's (dq, dk, dv) via XLA einsums; P re-derived from the final
    LSE: exp(scores - lse) is each block's true share of the global softmax
    (context_parallel.py:112-128)."""
    s = q.shape[1]
    mask = _block_mask(s, s, src, rank, causal)
    q32 = q.astype(jnp.float32)
    do32 = dout.astype(jnp.float32)
    k32 = kt.astype(jnp.float32)
    v32 = vt.astype(jnp.float32)
    lse_t = lse.transpose(0, 2, 1)[..., None]  # [B, H, Sq, 1]
    D_t = D.transpose(0, 2, 1)[..., None]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q32, k32) * scale
    p = jnp.where(mask[None, None], jnp.exp(scores - lse_t), 0.0)
    dv_blk = jnp.einsum("bhqk,bqhd->bkhd", p, do32)
    dp = jnp.einsum("bqhd,bkhd->bhqk", do32, v32)
    ds = p * (dp - D_t) * scale
    dq_blk = jnp.einsum("bhqk,bkhd->bqhd", ds, k32)
    dk_blk = jnp.einsum("bhqk,bqhd->bkhd", ds, q32)
    return dq_blk, dk_blk, dv_blk


def _block_bwd_flash(q, kt, vt, dout, out, lse, scale, src, rank, causal):
    """One block's (dq, dk, dv) via the Pallas backward kernels fed the
    globally-merged out/lse (skip branch costs nothing at runtime)."""
    from picotron_tpu.ops.pallas.flash_attention import flash_block_grads

    f32 = lambda t: tuple(x.astype(jnp.float32) for x in t)

    def full(_):
        return f32(flash_block_grads(q, kt, vt, out, lse, dout, scale, False))

    def diag(_):
        return f32(flash_block_grads(q, kt, vt, out, lse, dout, scale, True))

    def skip(_):
        z = jnp.zeros(q.shape, jnp.float32)
        return z, z, z

    if not causal:
        return full(None)
    idx = jnp.where(src == rank, 2, jnp.where(src < rank, 1, 0))
    return lax.switch(idx, [skip, full, diag], None)


def _ring_bwd(scale, axis, n, causal, use_flash, res, dout):
    q, k, v, out, lse = res
    rank = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    b, s, h, d = q.shape

    # D_i = sum_j dO_ij * O_ij (softmax backward rowsum, the reference's manual
    # 6-step derivation, context_parallel.py:130-155)
    D = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    dq0 = jnp.zeros((b, s, h, d), jnp.float32)
    dkv0 = (jnp.zeros((b, s, h, d), jnp.float32), jnp.zeros((b, s, h, d), jnp.float32))

    def step(carry, t):
        kv, dkv, dq = carry
        kt, vt = kv
        dk_acc, dv_acc = dkv
        src = (rank - t) % n
        if use_flash:
            dq_blk, dk_blk, dv_blk = _block_bwd_flash(
                q, kt, vt, dout, out, lse, scale, src, rank, causal)
        else:
            dq_blk, dk_blk, dv_blk = _block_bwd_einsum(
                q, kt, vt, dout, out, lse, D, scale, src, rank, causal)

        dq = dq + dq_blk
        # accumulators travel the ring with their kv chunk and arrive home
        # after n rotations (reference's d_kv_comm channel,
        # context_parallel.py:104-106)
        dkv = (dk_acc + dk_blk, dv_acc + dv_blk)
        kv, dkv = lax.ppermute((kv, dkv), axis, perm)
        return (kv, dkv, dq), None

    (kv, dkv, dq), _ = lax.scan(step, ((k, v), dkv0, dq0), jnp.arange(n),
                                unroll=collective_scan_unroll())
    dk, dv = dkv
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_attention.defvjp(_ring_fwd, _ring_bwd)
