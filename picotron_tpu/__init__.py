"""picotron-tpu: a minimal TPU-native 4D-parallel pre-training framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of rkinas/picotron
(torch.distributed + NCCL + CUDA/Triton) for TPU:

- one named device mesh ``('dp', 'pp', 'cp', 'tp')`` over ICI/DCN instead of
  torch.distributed process groups (reference: picotron/process_group_manager.py)
- ``shard_map`` + ``lax`` collectives (psum / all_gather / ppermute) instead of
  NCCL all-reduce / batched p2p (reference: the four */_communications.py files)
- Pallas TPU kernels for flash attention and RMSNorm instead of flash-attn CUDA
  and Triton kernels (reference: picotron/model.py:32-64)
- optax AdamW, HF datasets/tokenizers, orbax-style sharded checkpoints.
"""

__version__ = "0.1.0"

from picotron_tpu.config import Config  # noqa: F401
from picotron_tpu.topology import Topology  # noqa: F401
