"""Deterministic, config-driven fault injection.

Robustness code that is only exercised by real outages regresses silently —
the chaos injector gives every recovery path a reproducible trigger so the
tier-1 suite can prove kill→resume equivalence on a CPU mesh:

- ``chaos_raise_step``    — raise ``ChaosError`` after step k completes
                            (an unhandled crash; the train loop's
                            try/finally must still flush a checkpoint);
- ``chaos_nan_step``      — step k's dispatch runs a loss/grad-poisoned
                            program (``train_step.build_train_step(...,
                            poison_nonfinite=True)``), driving the on-device
                            non-finite gate and the host detector;
- ``chaos_sigterm_step``  — SIGTERM to our own pid after step k (a
                            preemption; the PreemptionGuard path);
- ``chaos_truncate_step`` — after step k's save, truncate the largest file
                            of the newest checkpoint step (a partial write;
                            the restore-fallback path).

Pod-scale (rank-targeted) events — the cluster fault-tolerance test surface
(resilience/cluster.py, docs/MULTIHOST.md). Specs are ``"RANK:STEP"``
strings ("" = off): the event fires only on the process whose
``jax.process_index()`` equals RANK, after step STEP completes:

- ``chaos_preempt_rank_at_step`` — SIGTERM to self: ONE host of the pod is
                                   preempted; the consensus path must turn
                                   it into a coordinated save + exit 75 on
                                   every host;
- ``chaos_kill_rank_at_step``    — SIGKILL to self: a dead host; peers must
                                   detect the silence (ClusterMonitor) and
                                   exit EXIT_CLUSTER_FAILED instead of
                                   wedging in the next collective;
- ``chaos_stall_rank_at_step``   — sleep ``chaos_stall_rank_s`` seconds: a
                                   straggler; drives the supervisor's
                                   heartbeat stall detector at pod scale.

Each event fires at most once per process, so a rollback that replays step k
does not re-trip the same fault (which would livelock the rollback policy).
Rank-targeted events additionally persist a fired marker under ``save_dir``:
a SIGKILL leaves no checkpoint past the chaos step, so the relaunched pod
REPLAYS it — without the marker the fault would re-fire every incarnation
and the restart budget would burn to zero. All steps are 1-indexed optimizer
steps; 0 disables an event.

``ServingChaos`` is the SERVING-side injector: the same config-driven,
deterministic discipline, but keyed to engine dispatch rounds instead of
optimizer steps and delivered through the InferenceEngine's dispatch hooks
(``engine.hooks``) — dispatch exceptions, latency spikes, and poisoned
(NaN) logits, the faults the batcher's retry/isolation path, the serving
watchdog, and the sampler's non-finite gate each exist to absorb.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time

from picotron_tpu.config import parse_rank_at_step
from picotron_tpu.utils import log0


class ChaosError(RuntimeError):
    """The injected crash — deliberately NOT caught anywhere in the trainer,
    so it exercises the same try/finally path a real bug would."""


def truncate_latest_checkpoint(save_dir: str) -> str:
    """Truncate the largest file under the newest orbax step directory to
    simulate a partial/interrupted write. Returns the truncated path."""
    steps = [d for d in os.listdir(save_dir)
             if d.isdigit() and os.path.isdir(os.path.join(save_dir, d))]
    if not steps:
        raise FileNotFoundError(f"no checkpoint step dirs under {save_dir}")
    step_dir = os.path.join(save_dir, max(steps, key=int))
    victim, size = None, -1
    for root, _, files in os.walk(step_dir):
        for f in files:
            p = os.path.join(root, f)
            s = os.path.getsize(p)
            if s > size:
                victim, size = p, s
    if victim is None:
        raise FileNotFoundError(f"no files under {step_dir}")
    with open(victim, "r+b") as f:
        f.truncate(max(1, size // 2))
    return victim


class ChaosInjector:
    def __init__(self, r, save_dir: str = "", rank: "int | None" = None):
        """``r`` is a ResilienceConfig; ``save_dir`` is the checkpoint dir
        (truncation target + rank-targeted fired markers). ``rank``
        overrides ``jax.process_index()`` for tests; it is resolved lazily
        so constructing an injector never forces a backend."""
        self.raise_step = int(r.chaos_raise_step)
        self.nan_step = int(r.chaos_nan_step)
        self.sigterm_step = int(r.chaos_sigterm_step)
        self.truncate_step = int(r.chaos_truncate_step)
        self.preempt_rank, self.preempt_step = parse_rank_at_step(
            "chaos_preempt_rank_at_step", r.chaos_preempt_rank_at_step)
        self.kill_rank, self.kill_step = parse_rank_at_step(
            "chaos_kill_rank_at_step", r.chaos_kill_rank_at_step)
        self.stall_rank, self.stall_step = parse_rank_at_step(
            "chaos_stall_rank_at_step", r.chaos_stall_rank_at_step)
        self.stall_s = float(r.chaos_stall_rank_s)
        self.save_dir = save_dir
        self._rank = rank
        self._fired: set = set()

    @property
    def active(self) -> bool:
        return (any(s > 0 for s in (self.raise_step, self.nan_step,
                                    self.sigterm_step, self.truncate_step))
                or any(k >= 0 for k in (self.preempt_rank, self.kill_rank,
                                        self.stall_rank)))

    def _my_rank(self) -> int:
        if self._rank is None:
            import jax

            self._rank = jax.process_index()
        return self._rank

    def _fire_once(self, event: str, at: int, step: int) -> bool:
        if at > 0 and step == at and event not in self._fired:
            self._fired.add(event)
            return True
        return False

    def _marker_path(self, event: str, rank: int, at: int) -> str:
        return os.path.join(self.save_dir, f".chaos_{event}_p{rank}_s{at}")

    def _fire_rank_once(self, event: str, rank: int, at: int,
                        step: int) -> bool:
        """Rank-targeted one-shot: fires only on the targeted process, at
        most once per RUN — the fired marker under save_dir survives a pod
        restart, because the replayed step would otherwise re-trip a fault
        (SIGKILL) that never let a checkpoint advance past it."""
        if rank < 0 or step != at or event in self._fired:
            return False
        self._fired.add(event)  # marker or not, never re-check this process
        if rank != self._my_rank():
            return False
        if self.save_dir:
            marker = self._marker_path(event, rank, at)
            if os.path.exists(marker):
                return False
            try:
                os.makedirs(self.save_dir, exist_ok=True)
                with open(marker, "w") as f:
                    f.write(f"step {step}\n")
            except OSError:
                pass  # no marker beats no chaos drill at all
        return True

    def poison_step(self, step: int) -> bool:
        """Whether the dispatch about to run step ``step`` should use the
        NaN-poisoned program. Consumes the event."""
        if self._fire_once("nan", self.nan_step, step):
            log0(f"chaos: poisoning step {step} with a non-finite loss")
            return True
        return False

    def after_step(self, step: int, manager=None) -> None:
        """Fire post-step events. Truncation runs before sigterm/raise so a
        combined config corrupts, then dies — the worst realistic ordering.
        Rank-targeted pod events run next (stall, then preempt, then kill —
        escalating severity); raise fires last (it does not return). The
        rank-targeted prints deliberately bypass the log0 process-0 gate:
        the targeted rank is usually NOT the logging controller."""
        if self._fire_once("truncate", self.truncate_step, step):
            if manager is not None:
                manager.wait_until_finished()  # corrupt a COMPLETE write
            victim = truncate_latest_checkpoint(self.save_dir)
            log0(f"chaos: truncated {victim} after step {step}")
        if self._fire_once("sigterm", self.sigterm_step, step):
            log0(f"chaos: SIGTERM to self after step {step}")
            os.kill(os.getpid(), signal.SIGTERM)
        if self._fire_rank_once("stall", self.stall_rank, self.stall_step,
                                step):
            print(f"chaos[p{self._my_rank()}]: stalling {self.stall_s}s "
                  f"after step {step}", flush=True)
            time.sleep(self.stall_s)
        if self._fire_rank_once("preempt", self.preempt_rank,
                                self.preempt_step, step):
            print(f"chaos[p{self._my_rank()}]: SIGTERM to self (pod "
                  f"preemption of one host) after step {step}", flush=True)
            os.kill(os.getpid(), signal.SIGTERM)
        if self._fire_rank_once("kill", self.kill_rank, self.kill_step,
                                step):
            print(f"chaos[p{self._my_rank()}]: SIGKILL to self (dead host) "
                  f"after step {step}", flush=True)
            sys.stdout.flush()
            os.kill(os.getpid(), signal.SIGKILL)
        if self._fire_once("raise", self.raise_step, step):
            raise ChaosError(f"chaos: injected crash after step {step}")


class ServingChaos:
    """Deterministic fault injection for the serving stack, installed as an
    engine's dispatch hooks (``InferenceEngine(..., hooks=ServingChaos(r))``
    or ``engine.hooks = ...``).

    Rounds are 1-indexed decode/verify dispatch invocations (prefill
    dispatches pass through untouched — admission faults are a different
    layer) and every dispatch the engine attempts counts, INCLUDING the
    batcher's retry and per-slot isolation re-dispatches: that is what lets
    ``chaos_dispatch_raise_round`` prove the retry path (fires once, the
    retry lands) while ``chaos_dispatch_fail_slot`` proves isolation (every
    dispatch that slot participates in fails, so only its solo re-dispatch
    keeps failing and only it finishes ``"error"``).

    - ``chaos_dispatch_raise_round``  — raise ``ChaosError`` before round N
      (once per process);
    - ``chaos_dispatch_fail_slot``    — raise whenever this slot is active
      (PERSISTENT, -1 = off);
    - ``chaos_latency_round``         — sleep ``chaos_latency_s`` before
      round N (once; drives the serve watchdog's stall detector);
    - ``chaos_poison_logits_round``   — round N's decode/verify dispatch
      runs the NaN-poisoned program (once; drives the sampler's — or, on
      speculative engines, ``speculative_accept``'s — non-finite gate).
    """

    def __init__(self, r, sleep=time.sleep):
        self.dispatch_raise_round = int(r.chaos_dispatch_raise_round)
        self.fail_slot = int(r.chaos_dispatch_fail_slot)
        self.latency_round = int(r.chaos_latency_round)
        self.latency_s = float(r.chaos_latency_s)
        self.poison_round = int(r.chaos_poison_logits_round)
        self._sleep = sleep  # injectable so tests don't wall-clock wait
        self.round = 0  # dispatch rounds seen so far (decode/verify only)
        self._fired: set = set()

    @property
    def active(self) -> bool:
        return (self.fail_slot >= 0
                or any(s > 0 for s in (self.dispatch_raise_round,
                                       self.latency_round,
                                       self.poison_round)))

    def _fire_once(self, event: str, at: int) -> bool:
        if at > 0 and self.round == at and event not in self._fired:
            self._fired.add(event)
            return True
        return False

    def before_dispatch(self, kind: str, slots: list) -> None:
        """Engine hook: called at the top of every host-facing dispatch with
        the active slot indices. Latency fires before the exception faults
        (a spike then a failure is the worst realistic ordering)."""
        if kind not in ("decode", "verify"):
            return
        self.round += 1
        if self._fire_once("latency", self.latency_round):
            log0(f"chaos: {self.latency_s}s latency spike before dispatch "
                 f"round {self.round}")
            self._sleep(self.latency_s)
        if self.fail_slot >= 0 and self.fail_slot in slots:
            raise ChaosError(
                f"chaos: persistent dispatch fault (slot {self.fail_slot} "
                f"active, round {self.round})")
        if self._fire_once("raise", self.dispatch_raise_round):
            raise ChaosError(
                f"chaos: injected dispatch exception at round {self.round}")

    def poison_logits(self, kind: str) -> bool:
        """Engine hook: whether THIS dispatch (the round ``before_dispatch``
        just opened) should run the NaN-poisoned program. Consumes the
        event."""
        if kind not in ("decode", "verify"):
            return False
        if self._fire_once("poison", self.poison_round):
            log0(f"chaos: poisoning dispatch round {self.round} logits")
            return True
        return False


class RouterChaos:
    """Deterministic fault injection for the multi-replica router drill
    (``tools/router.py``, docs/SERVING.md "Multi-replica fabric").

    Two injection surfaces, matching where real faults land:

    **Replica-side** (operates on in-process ``serve.Server`` objects —
    the ``make router-chaos-smoke`` fleet):

    - ``kill(server)``      — the in-process SIGKILL: the dispatch loop
      dies on its next step (in-flight waiters are released with
      ``finish_reason "error"`` — the contract the router's replay path
      depends on) and the HTTP listener closes (probes see connection
      refused);
    - ``stall(server, s)``  — ``/healthz`` answers only after ``s``
      seconds: a probe timeout shorter than ``s`` reads the replica as
      wedged (the hard-failure ladder) without the replica being down;
      ``unstall`` heals it;
    - ``flap(server, down)`` — health surfaces flip 503/200: the
      breaker's open -> half-open -> closed walk under an unstable
      replica.

    **Router-side** (installed as ``Router(..., chaos=RouterChaos())``):

    - ``fail_scrape(name)``     — the prober's ``/metrics`` read fails:
      the replica's scrape goes stale and it falls out of the candidate
      set WITHOUT tripping the breaker;
    - ``sever_stream(name, n)`` — the router's ``/generate`` stream from
      that replica raises ``ConnectionResetError`` after the n-th token
      row (once): the raw connection-drop flavor of a mid-stream death,
      as opposed to ``kill``'s dispatch-death flavor;
    - ``kill_on_export(name, server)`` — the disaggregation drill's
      prefill-worker death MID-HANDOFF: the moment the router opens a
      ``/kv/export`` toward ``name``, the backing server is killed (the
      POST lands on a closed listener) — the router must fall back to
      re-prefill at a survivor with the client none the wiser (once);
    - ``sever_export(name)``    — the page stream severs MID-TRANSFER:
      the export response's body read raises ``ConnectionResetError``
      after the head arrived, the torn-payload flavor (a partial body
      would also die at the transport's CRC) (once).

    Thread-safety: the injection sets are mutated by the drill thread and
    read by prober/handler threads; one leaf lock guards them (the same
    discipline as the router's own counters — picolint PICO-C003).
    """

    def __init__(self):
        self._mu = threading.Lock()
        self._scrape_fail: set = set()
        self._sever: dict = {}  # replica name -> sever after N token rows
        self._stalled: dict = {}  # id(front) -> original healthy()
        self._flapped: dict = {}  # id(front) -> (healthy, ready)
        self._kill_on_export: dict = {}  # replica name -> serve.Server
        self._sever_export: set = set()  # replica names (fire once)

    # ---- replica-side ------------------------------------------------------

    def kill(self, server) -> None:
        front = server.front

        def _bomb(*a, **k):
            raise ChaosError("router chaos: replica killed mid-step")

        front._batcher.step = _bomb
        front._wake.set()
        # the listener goes away like the process did; established
        # connections live on just long enough for the dying dispatch
        # loop's terminal "error" results to reach their streams
        server.httpd.shutdown()
        server.httpd.server_close()

    def stall(self, server, seconds: float) -> None:
        front = server.front
        with self._mu:
            if id(front) not in self._stalled:
                self._stalled[id(front)] = front.healthy

        def _slow(orig=front.healthy, s=float(seconds)):
            time.sleep(s)
            return orig()

        front.healthy = _slow  # instance attr shadows the method

    def unstall(self, server) -> None:
        front = server.front
        with self._mu:
            self._stalled.pop(id(front), None)
        try:
            del front.healthy  # restores the class method
        except AttributeError:
            pass

    def flap(self, server, down: bool) -> None:
        front = server.front
        if down:
            front.healthy = lambda: False
            front.ready = lambda: False
        else:
            for attr in ("healthy", "ready"):
                try:
                    delattr(front, attr)
                except AttributeError:
                    pass

    # ---- router-side -------------------------------------------------------

    def fail_scrape(self, name: str, on: bool = True) -> None:
        with self._mu:
            if on:
                self._scrape_fail.add(name)
            else:
                self._scrape_fail.discard(name)

    def scrape_fails(self, name: str) -> bool:
        """Router prober hook: should this replica's /metrics read fail?"""
        with self._mu:
            return name in self._scrape_fail

    def kill_on_export(self, name: str, server) -> None:
        """Arm: the next /kv/export the router opens toward ``name``
        kills ``server`` first (prefill-worker death mid-handoff)."""
        with self._mu:
            self._kill_on_export[name] = server

    def sever_export(self, name: str) -> None:
        """Arm: the next /kv/export response from ``name`` severs while
        the router reads the page payload (torn transfer)."""
        with self._mu:
            self._sever_export.add(name)

    def on_export(self, name: str) -> None:
        """Router handoff hook: fires as an export toward ``name`` opens.
        Consumes a kill_on_export event — the POST then lands on a dead
        listener, the realistic mid-handoff death."""
        with self._mu:
            server = self._kill_on_export.pop(name, None)
        if server is not None:
            self.kill(server)

    def on_export_read(self, name: str) -> None:
        """Router handoff hook: fires between the export response head
        and its body read. Consumes a sever_export event."""
        with self._mu:
            fire = name in self._sever_export
            self._sever_export.discard(name)
        if fire:
            raise ConnectionResetError(
                f"router chaos: export page stream from {name} severed "
                f"mid-transfer")

    def sever_stream(self, name: str, after_tokens: int) -> None:
        with self._mu:
            self._sever[name] = int(after_tokens)

    def on_stream_row(self, name: str, tokens_so_far: int) -> None:
        """Router stream hook: called before each NDJSON row is processed
        with the count of token rows already consumed from this attempt.
        Consumes the sever event (fires once)."""
        with self._mu:
            at = self._sever.get(name)
            if at is not None and tokens_so_far >= at:
                del self._sever[name]
                fire = True
            else:
                fire = False
        if fire:
            raise ConnectionResetError(
                f"router chaos: stream from {name} severed after "
                f"{tokens_so_far} tokens")


class FleetChaos:
    """Deterministic fault injection for the elastic fleet drill
    (``tools/fleet.py``, docs/SERVING.md "Elastic fleet"). Composes with
    ``RouterChaos``: RouterChaos breaks the data plane (streams, probes,
    handoffs), FleetChaos breaks the CONTROL plane the fleet controller
    runs on — the three ways an autoscaler itself goes wrong:

    - ``kill_worker(handle)``   — SIGKILL-under-load: delegates to the
      worker handle's own ``kill()`` (a real ``SIGKILL`` for subprocess
      workers, the RouterChaos dispatch-bomb for in-process smoke
      workers). The controller must detect the death off probe/liveness
      signals and replace within its budget ladder;
    - ``stall_scrape(name)``    — the controller's OWN telemetry read
      from ``name`` fails: stale metrics must read as "unknown", never
      as "dead" — a wedged scrape plane must not trigger a replacement
      storm (``unstall_scrape`` heals it);
    - ``inject_spike(n)``       — arms an admission spike: the drill's
      load generator drains the armed count (``take_spike``) and fires
      that many extra concurrent requests, the demand step the
      controller must answer with a scale-up inside its cooloff window.

    Thread-safety: armed state is mutated by the drill thread and read
    by the controller tick / load-generator threads; one leaf lock
    (picolint PICO-C003 discipline, same as RouterChaos).
    """

    def __init__(self):
        self._mu = threading.Lock()
        self._scrape_stall: set = set()
        self._spike = 0
        self.kills = 0  # drill accounting: workers killed so far

    def kill_worker(self, handle) -> None:
        """SIGKILL one fleet worker through its handle (fires its
        ``kill()`` — no drain, no goodbye; the crash flavor the
        controller's replace path exists for)."""
        with self._mu:
            self.kills += 1
        handle.kill()

    def stall_scrape(self, name: str, on: bool = True) -> None:
        with self._mu:
            if on:
                self._scrape_stall.add(name)
            else:
                self._scrape_stall.discard(name)

    def unstall_scrape(self, name: str) -> None:
        self.stall_scrape(name, on=False)

    def scrape_stalls(self, name: str) -> bool:
        """Fleet-controller scrape hook: should this worker's telemetry
        read fail this tick?"""
        with self._mu:
            return name in self._scrape_stall

    def inject_spike(self, n: int) -> None:
        """Arm ``n`` extra concurrent requests for the drill's load
        generator to fire on its next pass."""
        with self._mu:
            self._spike += int(n)

    def take_spike(self) -> int:
        """Load-generator hook: drain the armed spike count (consumes)."""
        with self._mu:
            n, self._spike = self._spike, 0
            return n
