"""Cluster control plane: multi-host preemption consensus + peer liveness.

The framework is a multi-controller program (docs/MULTIHOST.md): one Python
process per host, and the collectives — including orbax checkpoint saves —
span all of them. That makes single-process fault tolerance insufficient on
a pod:

- **Preemption tears.** Preemptible pools deliver SIGTERM per *host*. If one
  host's ``PreemptionGuard`` breaks out of the train loop alone, its
  emergency save is a collective that its peers never joined: the signaled
  host wedges inside orbax, the grace window burns, and the checkpoint is
  lost. ``ClusterCoordinator`` fixes the decision, not the save: every
  process contributes its local ``guard.triggered`` flag to a tiny jitted
  all-reduce (``jnp.max`` over the full device mesh) at step boundaries, so
  when ANY host is preempted, EVERY host learns it at the same step, takes
  the same collective emergency save, and exits ``EXIT_PREEMPTED`` together.

- **Dead hosts wedge survivors.** A SIGKILLed/OOMed/vaporized host leaves
  its peers blocked inside a collective that will never complete (gloo and
  the TPU runtime both hang far longer than any scheduler's patience).
  ``ClusterMonitor`` is the escape hatch: a per-process background thread
  renews a lease file in a shared directory and watches the peers' leases;
  a peer silent past ``resilience.peer_timeout_s`` means a dead host inside
  a collective, and the monitor kills THIS process with
  ``EXIT_CLUSTER_FAILED`` via ``os._exit`` (the main thread is stuck in C —
  a Python exception could never unwind it). The pod supervisor
  (``tools/supervise.py --num-procs``) sees the exit code and restarts the
  pod together.

Exit-code ladder (what a supervisor keys restarts off):

======================  ====================================================
``0``                   done — do not restart
``EXIT_PREEMPTED`` 75   coordinated emergency checkpoint written — relaunch
                        resumes (EX_TEMPFAIL semantics)
``EXIT_ANOMALY`` 76     loss diverged under policy 'abort' — human attention
``EXIT_CLUSTER_FAILED`` a peer died inside a collective — restart the whole
``77``                  pod; auto-resume recovers from the last checkpoint
anything else           a local crash
======================  ====================================================

Single-host behavior is unchanged: with one JAX process the coordinator is
inert (the local flag IS the global truth, checked every step as before)
and the monitor has no peers to watch.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

# A peer process died inside a collective: this process exits instead of
# wedging forever. Distinct from 75 (no checkpoint was written — resume
# falls back to the last periodic save) and from a local crash (the fault
# was elsewhere; the supervisor restarts the whole pod, not just one rank).
EXIT_CLUSTER_FAILED = 77


class ClusterCoordinator:
    """Preemption consensus: a jitted ``jnp.max`` all-reduce of the local
    preemption flag over the full device mesh, evaluated at step boundaries
    every ``interval`` steps.

    All processes run the identical deterministic step sequence, so gating
    rounds on the step counter gives every process the same consensus
    schedule — each round is a collective and MUST be entered by everyone.
    ``interval`` trades signal latency for overhead: a round is a scalar
    all-reduce (microseconds on ICI, ~ms on DCN/gloo), so ``1`` (every
    boundary) is the production default; raising it delays how long a
    SIGTERM sits host-local before the pod reacts, eating into the
    preemption grace window.

    With one JAX process the coordinator is inert: ``preempt_now`` returns
    the local flag on every step, exactly the pre-cluster behavior.
    """

    def __init__(self, interval: int = 1, process_count: Optional[int] = None):
        import jax

        self.interval = max(1, int(interval))
        self._nproc = (jax.process_count() if process_count is None
                       else int(process_count))
        self._last_round_step: Optional[int] = None
        self.rounds = 0  # consensus rounds actually evaluated
        self._reduce = None
        self._sharding = None
        if self.active:
            self._build()

    @property
    def active(self) -> bool:
        return self._nproc > 1

    def _build(self) -> None:
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        # One element per device over a private 1-axis mesh: each process
        # fills its addressable entries with its local flag, the jitted max
        # reduces across the whole pod, and the replicated result is read
        # back from a local shard (a multi-process array cannot be read
        # whole-array on any one host).
        self._mesh = Mesh(np.asarray(jax.devices()), ("cluster",))
        self._sharding = NamedSharding(self._mesh, PartitionSpec("cluster"))
        replicated = NamedSharding(self._mesh, PartitionSpec())
        self._reduce = jax.jit(jnp.max, out_shardings=replicated)

    def due(self, step: int) -> bool:
        """Whether ``step``'s boundary holds a consensus round. Pure function
        of the step sequence — identical on every process, INCLUDING after an
        anomaly rollback: the restore rewinds ``step`` below the last round
        on every process at once, so restarting the schedule there keeps the
        rounds aligned (waiting for the old high-water mark instead would
        leave the whole replay deaf to preemptions)."""
        if not self.active:
            return True
        return (self._last_round_step is None
                or step < self._last_round_step
                or step - self._last_round_step >= self.interval)

    def preempt_now(self, step: int, local_flag: bool) -> bool:
        """Consensus entry point, called at the top of every loop iteration
        by EVERY process. Returns True when the pod should break for a
        coordinated emergency save at this boundary.

        Between rounds a locally-set flag returns False — breaking alone
        would tear the collective save; the flag is raised at the next
        round instead (that latency is the ``interval`` trade-off)."""
        if not self.active:
            return bool(local_flag)
        if not self.due(step):
            return False
        self._last_round_step = step
        self.rounds += 1
        return self._any_true(bool(local_flag))

    def _any_true(self, flag: bool) -> bool:
        import jax
        import numpy as np

        n = len(self._mesh.devices.ravel())
        local = np.asarray([1 if flag else 0], dtype=np.int32)
        arr = jax.make_array_from_callback((n,), self._sharding,
                                           lambda idx: local)
        out = jax.block_until_ready(self._reduce(arr))
        return int(np.asarray(out.addressable_data(0))) > 0


class ClusterMonitor:
    """Peer-liveness watchdog: lease files as cross-host heartbeats.

    Each process's monitor thread touches ``lease_p<id>`` in a shared
    directory (content: the last completed step, for the post-mortem log
    line) every ``lease_interval_s`` and checks the peers' lease mtimes. A
    peer lease stale past ``peer_timeout_s`` — and not marked done — means
    the peer died; any collective this process enters (or is already wedged
    inside) will never complete, so the monitor exits the process with
    ``EXIT_CLUSTER_FAILED`` via ``os._exit``.

    Clean exits (completion, coordinated preemption) call
    ``stop(mark_done=True)``, which drops a ``done_p<id>`` marker so peers
    still flushing their final save don't mistake the natural end of a rank
    for its death. A crash must NOT mark done — the stale lease is exactly
    how the peers learn to stop waiting. ``train()`` handles this by
    marking done only when no exception is unwinding.

    The directory must be on storage every host mounts (the checkpoint
    tier works: ``resilience.cluster_dir`` defaults to
    ``<save_dir>/_cluster``). The pod supervisor relaunches every rank
    together over the same directory, so a PREVIOUS incarnation's files
    linger until each rank's own ``reset()`` removes them — peers gate on
    freshness instead of trusting them: a peer file whose mtime predates
    this monitor's start is stale (a dead incarnation's lease must not
    read as an instant timeout, and its done marker must not blind this
    incarnation to that rank's next death).
    """

    def __init__(self, cluster_dir: str, process_id: int, num_processes: int,
                 peer_timeout_s: float, lease_interval_s: float = 2.0,
                 exit_fn: Optional[Callable[[int, float], None]] = None,
                 clock: Callable[[], float] = time.time):
        self.dir = cluster_dir
        self.pid = int(process_id)
        self.nproc = int(num_processes)
        self.peer_timeout_s = float(peer_timeout_s)
        self.lease_interval_s = float(lease_interval_s)
        self._exit = exit_fn or self._default_exit
        self._clock = clock
        self.step = 0  # last completed local step (advisory, for logging)
        self._births: dict[int, float] = {}
        self._done: set[int] = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- paths ------------------------------------------------------------ #

    def lease_path(self, pid: int) -> str:
        return os.path.join(self.dir, f"lease_p{pid}")

    def done_path(self, pid: int) -> str:
        return os.path.join(self.dir, f"done_p{pid}")

    # -- lifecycle --------------------------------------------------------- #

    def start(self) -> "ClusterMonitor":
        os.makedirs(self.dir, exist_ok=True)
        self.reset()
        self._renew()
        now = self._clock()
        # a peer that NEVER leases counts its silence from our start: a host
        # that failed to come up at all is detected too, not just one that
        # died mid-run
        self._births = {p: now for p in range(self.nproc) if p != self.pid}
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="cluster-monitor", daemon=True)
        self._thread.start()
        return self

    def reset(self) -> None:
        """Clear THIS process's markers from a previous incarnation (the
        pod supervisor restarts every rank together, same cluster_dir): a
        leftover done marker would blind the peers to this rank's next
        death, and a stale lease would look like an instant timeout."""
        for p in (self.lease_path(self.pid), self.done_path(self.pid)):
            try:
                os.remove(p)
            except OSError:
                pass

    def stop(self, mark_done: bool = True) -> None:
        """Stop watching. ``mark_done=True`` (clean/coordinated exits only)
        tells the peers this rank's silence from here on is natural."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if mark_done:
            try:
                with open(self.done_path(self.pid), "w") as f:
                    f.write(str(self.step))
            except OSError:
                pass

    def notify_step(self, step: int) -> None:
        """Record loop progress (written into the lease by the next renewal;
        purely advisory — liveness is the mtime, not the content)."""
        self.step = int(step)

    # -- the watch loop ---------------------------------------------------- #

    def _renew(self) -> None:
        try:
            with open(self.lease_path(self.pid), "w") as f:
                f.write(str(self.step))
        except OSError:
            # one missed renewal is survivable (peer_timeout_s spans several
            # intervals); a persistently dead mount eventually reads as OUR
            # death to the peers, which is the correct verdict anyway
            pass

    # Peer files older than this much before our own start belong to a dead
    # incarnation (the slack absorbs cross-host mtime/clock jitter; a
    # LEGITIMATE done/lease can't predate us by more — the peer must have
    # joined collectives with this incarnation first).
    _STALE_SLACK_S = 1.0

    def _fresh_mtime(self, path: str, birth: float) -> Optional[float]:
        """The file's mtime, or None when missing OR left over from a
        previous incarnation of the pod (same cluster_dir, relaunched
        together — the owner's reset() may not have run yet)."""
        try:
            m = os.path.getmtime(path)
        except OSError:
            return None
        return m if m >= birth - self._STALE_SLACK_S else None

    def check_peers(self) -> Optional[tuple[int, float]]:
        """Returns ``(peer_id, silence_s)`` for the first peer found silent
        past the timeout, or None. Split from the thread loop so tests can
        drive it synchronously."""
        now = self._clock()
        for p in sorted(self._births):
            if p in self._done:
                continue
            birth = self._births[p]
            if self._fresh_mtime(self.done_path(p), birth) is not None:
                self._done.add(p)
                continue
            lease = self._fresh_mtime(self.lease_path(p), birth)
            # no (fresh) lease: silence counts from our start — covers a
            # host that never came up AND a dead incarnation's leftovers
            age = now - lease if lease is not None else now - birth
            if age > self.peer_timeout_s:
                return p, age
        return None

    def _peer_step(self, p: int) -> str:
        try:
            with open(self.lease_path(p)) as f:
                return f.read().strip() or "?"
        except OSError:
            return "?"

    def _run(self) -> None:
        poll = min(self.lease_interval_s, max(self.peer_timeout_s / 4, 0.05))
        while not self._stop.wait(poll):
            self._renew()
            dead = self.check_peers()
            if dead is not None:
                self._exit(*dead)
                return  # test exit_fns return; the real one never does

    def _default_exit(self, peer: int, age: float) -> None:
        # The main thread is (or soon will be) wedged inside a collective:
        # only an immediate process exit escapes. Write the post-mortem
        # straight to fd 2 — never through the log0 gate (the dead peer may
        # BE process 0) and never through buffered stdio.
        msg = (f"cluster monitor [p{self.pid} step {self.step}]: peer "
               f"{peer} (last step {self._peer_step(peer)}) silent "
               f"{age:.1f}s > peer_timeout_s={self.peer_timeout_s}s — dead "
               f"host inside a collective; exiting {EXIT_CLUSTER_FAILED}\n")
        try:
            os.write(2, msg.encode())
        except OSError:
            pass
        os._exit(EXIT_CLUSTER_FAILED)
