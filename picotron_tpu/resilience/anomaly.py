"""Host-side loss-anomaly detection with configurable recovery policies.

Two layers catch a diverging run:

1. The jit-compatible non-finite gate inside ``train_step`` (a ``jnp.where``
   on loss finiteness) guarantees a NaN/Inf step applies **no** param or
   optimizer update — that part must live on-device because by the time the
   host sees the loss, a donated update would already have been applied.
2. This detector sees every per-step loss on the host and flags both
   non-finite values and finite *spikes* against an EMA baseline, then the
   train loop applies the configured policy:

   - ``skip``     — log and continue (the device gate already dropped the
                    update for non-finite steps);
   - ``rollback`` — after K consecutive anomalies, restore the last
                    checkpoint and replay (bounded by ``max_rollbacks``);
   - ``abort``    — raise ``AnomalyAbort`` (exit code ``EXIT_ANOMALY``).

Spike statistics are EMA(loss) and EMA of squared deviation; a loss is a
spike when its deviation exceeds ``zscore * std`` (with an absolute floor so
a near-zero-variance plateau isn't hair-trigger). Anomalous values are NOT
absorbed into the EMA — one spike must not drag the baseline up and mask
the next one.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional


class AnomalyAbort(RuntimeError):
    """Raised by the train loop when the anomaly policy says stop."""


@dataclass
class Anomaly:
    step: int
    loss: float
    kind: str  # "nonfinite" | "spike"
    ema: Optional[float]  # baseline at detection time (None pre-warmup)
    consecutive: int  # length of the current anomaly streak, this one included


class LossAnomalyDetector:
    def __init__(self, ema_beta: float = 0.95, zscore: float = 6.0,
                 warmup_steps: int = 20, min_deviation: float = 0.05):
        self.ema_beta = float(ema_beta)
        self.zscore = float(zscore)
        self.warmup_steps = int(warmup_steps)
        self.min_deviation = float(min_deviation)
        self.reset()

    def reset(self) -> None:
        """Forget all statistics — called after a rollback so the replayed
        window re-warms instead of being judged against post-spike stats."""
        self._ema: Optional[float] = None
        self._var = 0.0
        self._n = 0
        self.consecutive = 0

    def observe(self, step: int, loss: float) -> Optional[Anomaly]:
        """Feed one per-step loss; returns an ``Anomaly`` or None."""
        loss = float(loss)
        if not math.isfinite(loss):
            self.consecutive += 1
            return Anomaly(step, loss, "nonfinite", self._ema, self.consecutive)

        if self._ema is not None and self._n >= self.warmup_steps:
            std = math.sqrt(max(self._var, 0.0))
            if loss - self._ema > max(self.min_deviation, self.zscore * std):
                self.consecutive += 1
                return Anomaly(step, loss, "spike", self._ema, self.consecutive)

        # healthy step: absorb into the baseline
        self.consecutive = 0
        if self._ema is None:
            self._ema = loss
        else:
            b = self.ema_beta
            dev = loss - self._ema
            self._ema = b * self._ema + (1.0 - b) * loss
            self._var = b * self._var + (1.0 - b) * dev * dev
        self._n += 1
        return None
