"""Bounded retry with exponential backoff + jitter for flaky I/O.

Checkpoint saves/restores and safetensors reads cross NFS/GCS mounts where
transient errors (stale handles, connection resets, throttling) are routine
on big fleets. One shared primitive keeps the policy uniform: attempts are
bounded (a deterministic failure surfaces quickly, with the original
exception), delays grow exponentially, and jitter decorrelates the herd of
hosts that all hit the same flake at the same step.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Tuple, Type

from picotron_tpu.utils import log0


def retry(
    fn: Callable,
    attempts: int = 3,
    backoff: float = 0.5,
    jitter: float = 0.25,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    desc: str = "",
    sleep: Callable[[float], None] = time.sleep,
    rng: Callable[[], float] = random.random,
):
    """Call ``fn()`` up to ``attempts`` times; return its result.

    Delay before attempt k (1-indexed) is ``backoff * 2**(k-1)`` scaled by a
    uniform jitter in [1, 1+jitter]. The final failure re-raises the original
    exception unchanged. ``KeyboardInterrupt``/``SystemExit`` are never
    swallowed (they are not ``Exception`` subclasses). ``sleep``/``rng`` are
    injectable so tests run instantly and deterministically.
    """
    if attempts < 1:
        raise ValueError(f"retry needs attempts >= 1, got {attempts}")
    from picotron_tpu.obs import global_counter

    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except retry_on as e:
            # process-wide resilience counter (docs/OBSERVABILITY.md):
            # retry() has no per-run registry to hand its numbers to, so
            # failed attempts count globally, labeled by call site
            global_counter("picotron_retries_total",
                           "failed attempts absorbed by retry()",
                           desc=desc or "unnamed").inc()
            if attempt == attempts:
                raise
            delay = backoff * (2 ** (attempt - 1)) * (1.0 + jitter * rng())
            log0(f"retry{f' [{desc}]' if desc else ''}: attempt "
                 f"{attempt}/{attempts} failed ({type(e).__name__}: {e}); "
                 f"retrying in {delay:.2f}s", flush=True)
            sleep(delay)
