"""Preemption safety: catch SIGTERM/SIGINT, finish the dispatch, save, exit.

Preemptible TPU pools deliver SIGTERM with a short grace window. The guard
turns that into a cooperative shutdown: the first signal only sets a flag —
the train loop checks it at dispatch boundaries, writes an emergency
checkpoint, and the process exits with ``EXIT_PREEMPTED`` so supervisors can
tell "re-run the same command" from a crash. A second signal falls through
to a KeyboardInterrupt (the operator really means it); the original handlers
are restored on uninstall so embedding processes (pytest, notebooks) are
left untouched.
"""

from __future__ import annotations

import signal
import threading
from typing import Callable, Optional

from picotron_tpu.utils import log0

# EX_TEMPFAIL from sysexits.h: "transient failure, invoke again later" — the
# exact semantics of a preempted-but-checkpointed run.
EXIT_PREEMPTED = 75

_LAST: Optional["PreemptionGuard"] = None


class PreemptionGuard:
    """Install with ``guard = PreemptionGuard().install()``; poll
    ``guard.triggered`` at dispatch boundaries; ``uninstall()`` in a finally.
    Also usable as a context manager."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._prev: dict = {}
        self.triggered = False
        self.signame: Optional[str] = None

    def install(self) -> "PreemptionGuard":
        global _LAST
        for s in self._signals:
            try:
                self._prev[s] = signal.signal(s, self._handle)
            except ValueError:
                # not the main thread (embedded runs): signal handlers are
                # unavailable there — degrade to a no-op guard
                log0("preemption guard: not on the main thread, "
                     "signal handling disabled")
                break
        _LAST = self
        return self

    def uninstall(self) -> None:
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()

    def _handle(self, signum, frame) -> None:
        if self.triggered:
            # second signal: the grace period is over — restore defaults and
            # surface an interrupt so even a wedged loop dies
            self.uninstall()
            raise KeyboardInterrupt(f"second {signal.Signals(signum).name}")
        self.triggered = True
        self.signame = signal.Signals(signum).name

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def emergency_save(self, fn: Callable[[], None],
                       timeout_s: float = 0.0) -> bool:
        """Run the emergency checkpoint flush OFF the signal path: ``fn``
        executes on a background thread and the caller joins it with a
        deadline, so a save wedged on a dead mount delays the exit by at
        most ``timeout_s`` seconds of the preemption grace window instead
        of eating all of it (0 = wait forever — the save is worth more
        than the exit). Atomicity is the save layer's job (orbax commits a
        step by atomic directory rename; ``CheckpointManager`` mirrors the
        same way), so an abandoned thread can never leave a half-step a
        resume would trust. Returns True when ``fn`` completed in time;
        its exception, if any, is re-raised on THIS thread (the caller's
        error handling stays unchanged). False = deadline expired, the
        daemon thread dies with the process."""
        state: dict = {}

        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 - relayed to caller
                state["err"] = e

        t = threading.Thread(target=run, name="emergency-save", daemon=True)
        t.start()
        t.join(timeout_s if timeout_s and timeout_s > 0 else None)
        if t.is_alive():
            log0(f"emergency save still running after {timeout_s}s "
                 f"deadline; exiting without it (the last periodic "
                 f"checkpoint stands)", flush=True)
            return False
        if "err" in state:
            raise state["err"]
        return True


def was_preempted() -> bool:
    """Whether the most recently installed guard caught a signal — the
    entry point (``train.main``) keys its exit code off this."""
    return _LAST is not None and _LAST.triggered
