"""Preemption safety: catch SIGTERM/SIGINT, finish the dispatch, save, exit.

Preemptible TPU pools deliver SIGTERM with a short grace window. The guard
turns that into a cooperative shutdown: the first signal only sets a flag —
the train loop checks it at dispatch boundaries, writes an emergency
checkpoint, and the process exits with ``EXIT_PREEMPTED`` so supervisors can
tell "re-run the same command" from a crash. A second signal falls through
to a KeyboardInterrupt (the operator really means it); the original handlers
are restored on uninstall so embedding processes (pytest, notebooks) are
left untouched.
"""

from __future__ import annotations

import signal
import threading
from typing import Callable, Optional

from picotron_tpu.utils import log0

# EX_TEMPFAIL from sysexits.h: "transient failure, invoke again later" — the
# exact semantics of a preempted-but-checkpointed run.
EXIT_PREEMPTED = 75

_LAST: Optional["PreemptionGuard"] = None
# was_preempted() after the guard is gone: uninstall() snapshots the
# verdict here (train's finally uninstalls BEFORE main reads the exit
# code) and clears _LAST — a later run in the same process (pytest,
# notebooks) must not read a dead guard's stale verdict, so install()
# AND every uninstall() overwrite it with the current guard's state.
_LAST_VERDICT = False


class PreemptionGuard:
    """Install with ``guard = PreemptionGuard().install()``; poll
    ``guard.triggered`` at dispatch boundaries; ``uninstall()`` in a finally.
    Also usable as a context manager."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._prev: dict = {}
        self.triggered = False
        self.signame: Optional[str] = None
        self._adopted = False

    def install(self) -> "PreemptionGuard":
        global _LAST, _LAST_VERDICT
        _LAST_VERDICT = False
        for s in self._signals:
            try:
                self._prev[s] = signal.signal(s, self._handle)
            except ValueError:
                # not the main thread (embedded runs): signal handlers are
                # unavailable there — degrade to a no-op guard
                log0("preemption guard: not on the main thread, "
                     "signal handling disabled")
                break
        _LAST = self
        return self

    def uninstall(self) -> None:
        global _LAST, _LAST_VERDICT
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()
        # Snapshot THIS run's verdict and drop the module reference: a
        # dead guard must answer was_preempted() for its own run's exit
        # code, but never leak a stale True into the next run in the same
        # process. A never-installed guard (handle_signals=False) records
        # False here for the same reason.
        _LAST_VERDICT = self.triggered
        if _LAST is self:
            _LAST = None

    def _handle(self, signum, frame) -> None:
        if self._adopted:
            # triggered was set synthetically from a peer's verdict; this
            # host's own first REAL signal is the expected pod-wide delivery,
            # not the operator's escalation — record it and keep flushing
            self._adopted = False
            self.signame = signal.Signals(signum).name
            return
        if self.triggered:
            # second signal: the grace period is over — restore defaults and
            # surface an interrupt so even a wedged loop dies
            self.uninstall()
            raise KeyboardInterrupt(f"second {signal.Signals(signum).name}")
        self.triggered = True
        self.signame = signal.Signals(signum).name

    def adopt(self, signame: str = "PEER-PREEMPT") -> None:
        """Adopt a preemption verdict learned out-of-band (cluster
        consensus: a PEER was signaled). Sets ``triggered`` so the loop
        breaks for the coordinated save, but keeps this host's own first
        real signal benign — providers SIGTERM every host of a preempted
        pod, so the local copy is usually still in flight and must not
        read as a 'second signal' escalation that would interrupt the
        collective emergency save."""
        self.triggered = True
        self.signame = signame
        self._adopted = True

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def emergency_save(self, fn: Callable[[], None],
                       timeout_s: float = 0.0) -> bool:
        """Run the emergency checkpoint flush OFF the signal path: ``fn``
        executes on a background thread and the caller joins it with a
        deadline, so a save wedged on a dead mount delays the exit by at
        most ``timeout_s`` seconds of the preemption grace window instead
        of eating all of it (0 = wait forever — the save is worth more
        than the exit). Atomicity is the save layer's job (orbax commits a
        step by atomic directory rename; ``CheckpointManager`` mirrors the
        same way), so an abandoned thread can never leave a half-step a
        resume would trust. Returns True when ``fn`` completed in time;
        its exception, if any, is re-raised on THIS thread (the caller's
        error handling stays unchanged). False = deadline expired, the
        daemon thread dies with the process."""
        state: dict = {}

        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 - relayed to caller
                state["err"] = e

        t = threading.Thread(target=run, name="emergency-save", daemon=True)
        t.start()
        t.join(timeout_s if timeout_s and timeout_s > 0 else None)
        from picotron_tpu.obs import global_counter

        if t.is_alive():
            log0(f"emergency save still running after {timeout_s}s "
                 f"deadline; exiting without it (the last periodic "
                 f"checkpoint stands)", flush=True)
            global_counter("picotron_emergency_saves_total",
                           "emergency checkpoint flushes by outcome",
                           outcome="abandoned").inc()
            return False
        if "err" in state:
            global_counter("picotron_emergency_saves_total",
                           "emergency checkpoint flushes by outcome",
                           outcome="failed").inc()
            raise state["err"]
        global_counter("picotron_emergency_saves_total",
                       "emergency checkpoint flushes by outcome",
                       outcome="completed").inc()
        return True


def was_preempted() -> bool:
    """Whether the current run's guard caught a signal — the entry point
    (``train.main``) keys its exit code off this. Live guards answer
    directly; after uninstall the snapshotted verdict of the most recently
    finished run answers (and is reset by the next install/uninstall, so
    it can never go stale across runs in one process)."""
    return _LAST.triggered if _LAST is not None else _LAST_VERDICT
