"""Fault tolerance: preemption safety, loss-anomaly policies, retrying I/O,
and deterministic chaos injection.

Production TPU pods are preemptible and large runs hit faults daily —
MegaScale and the Llama-3 infrastructure report both attribute most lost
throughput to restarts and loss spikes, not steady-state speed. This package
holds the host-side machinery that turns those events from run-killers into
bounded hiccups:

- ``preemption``  — SIGTERM/SIGINT guard: finish the in-flight dispatch,
  write an emergency checkpoint, exit with ``EXIT_PREEMPTED``;
- ``anomaly``     — EMA loss-spike detector with skip/rollback/abort
  policies (the jit-side non-finite gate lives in ``train_step``);
- ``retry``       — bounded exponential-backoff retry for checkpoint and
  safetensors I/O;
- ``chaos``       — config-driven deterministic fault injector (raise /
  NaN loss / SIGTERM / checkpoint truncation at step k, plus rank-targeted
  preempt/kill/stall for pods) so recovery has a tier-1 test surface
  instead of being exercised only by real outages;
- ``cluster``     — the pod-level control plane: preemption consensus (any
  host's SIGTERM triggers the same coordinated save on every host) and a
  peer-liveness monitor that exits ``EXIT_CLUSTER_FAILED`` instead of
  wedging inside a collective when a host dies.

The supervisor (``tools/supervise.py``) sits one level above: a bounded-
restart watchdog around ``python -m picotron_tpu.train`` — per process or
per pod (``--num-procs``) — keyed off these exit codes and heartbeat files.
"""

from picotron_tpu.resilience.anomaly import (  # noqa: F401
    Anomaly,
    AnomalyAbort,
    LossAnomalyDetector,
)
from picotron_tpu.resilience.chaos import (  # noqa: F401
    ChaosError,
    ChaosInjector,
    ServingChaos,
)
from picotron_tpu.resilience.cluster import (  # noqa: F401
    EXIT_CLUSTER_FAILED,
    ClusterCoordinator,
    ClusterMonitor,
)
from picotron_tpu.resilience.preemption import (  # noqa: F401
    EXIT_PREEMPTED,
    PreemptionGuard,
    was_preempted,
)
from picotron_tpu.resilience.retry import retry  # noqa: F401

# Distinct exit code for an anomaly-policy abort (vs 1 = crash, EXIT_PREEMPTED
# = graceful preemption): the supervisor and schedulers can tell "the loss
# diverged, human attention needed" from "re-run me".
EXIT_ANOMALY = 76
