"""The one-line JSON bench-record contract, shared by its producers and
consumers.

The benches (`bench.py`, `bench_7b.py`) print exactly one line starting
``{"metric"`` per run — a real capture, a stale in-round republish
(``stale_from`` present), or a diagnosed null (``value: null``, optionally
``code_failure: true``). The tunnel watcher and the orchestrator's
stale-capture fallback both need to FIND and CLASSIFY those lines in step
logs, and the watcher cannot import ``bench`` itself (it stays
import-light: ``bench`` touches jax at module top). This module is the
single home for the metric names and the line scan so a rename or framing
change cannot silently desynchronize a consumer.
"""

from __future__ import annotations

import json

# the ON-TPU metric each bench script publishes, keyed by the agenda step
# name (chip_agenda.STEP_TIMEOUTS) that runs it
BENCH_METRICS = {
    "bench": "smollm_1.7b_mfu_1chip",
    "bench_7b": "llama2_7b_proxy_mfu_1chip",
    "bench_decode": "smollm_1.7b_decode_toks_s_chip",
}


def iter_metric_records(log_path: str):
    """Yield every one-line JSON metric record in a step log. Missing or
    unreadable logs yield nothing."""
    try:
        with open(log_path, errors="replace") as f:
            for line in f:
                if not line.startswith('{"metric"'):
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    continue
    except OSError:
        return
