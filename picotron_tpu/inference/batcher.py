"""Continuous batching: admit/retire variable-length requests into fixed
engine slots.

The engine's decode program has a fixed batch width (``engine.slots``), so
throughput under mixed-length traffic is a scheduling problem: a slot whose
sequence hits EOS must be recycled to a waiting request immediately, not
when the whole batch drains (static batching's tail loss). The batcher is
the host-side loop that does exactly that:

  admit:  while a slot is free and requests wait, prefill the next prompt
          (padded to its power-of-two bucket), insert its K/V into the
          slot, and sample its first token from the prefill logits;
  decode: ONE ``decode_step`` advances every occupied slot together —
          per-slot sampling params ride along as arrays, so mixed
          greedy/temperature/top-k/top-p traffic shares the program;
  retire: slots that hit EOS, their token budget, or their wall-clock
          deadline release (a 1-element length write — stale K/V rows
          become unreachable) and free capacity for the next admit.

Free slots still flow through the decode program (fixed shapes are the
deal with XLA); they carry token 0 at length 0 and their outputs are
ignored. The whole loop is deterministic given the seed: one PRNG key
chain, split once per admit and once per decode round.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

import jax
import numpy as np

from picotron_tpu.inference import sampling


@dataclass
class Request:
    """One generation request. ``temperature == 0`` = greedy; ``top_k <= 0``
    and ``top_p >= 1`` disable those filters. ``timeout_s`` is a wall-clock
    budget from admission: a stuck or over-budget request finishes with
    reason "timeout" and frees its slot instead of occupying it forever
    (None = no deadline)."""

    uid: str
    prompt: list
    max_new_tokens: int = 32
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: Optional[int] = None
    timeout_s: Optional[float] = None


@dataclass
class GenerationResult:
    uid: str
    prompt: list
    tokens: list  # generated ids, EOS included when hit
    finish_reason: str  # "eos" | "length" | "timeout"


@dataclass
class _Slot:
    req: Request
    generated: list = field(default_factory=list)
    deadline: Optional[float] = None  # clock() time after which we retire


class ContinuousBatcher:
    """Drive an InferenceEngine over a stream of requests.

    >>> b = ContinuousBatcher(engine, params)
    >>> b.submit(Request("a", [1, 2, 3], max_new_tokens=16))
    >>> results = b.run()           # {"a": GenerationResult(...)}

    ``params`` must already be placed on the engine mesh
    (``engine.shard_params``). One batcher owns one cache; interleaving two
    batchers on one engine is fine (separate caches), sharing a cache is
    not (decode_step consumes it).
    """

    def __init__(self, engine, params, seed: int = 0, clock=time.monotonic):
        self.engine = engine
        self.params = params
        self._clock = clock  # injectable so deadline tests are deterministic
        self._key = jax.random.PRNGKey(seed)
        self._cache = engine.init_cache()
        self._slots: list = [None] * engine.slots
        self._pending: deque = deque()
        self._results: dict = {}
        n = engine.slots
        self._last_tok = np.zeros(n, np.int32)
        self._temp = np.zeros(n, np.float32)
        self._top_k = np.zeros(n, np.int32)
        self._top_p = np.ones(n, np.float32)

    # ---- queue surface ----------------------------------------------------

    def submit(self, req: Request) -> None:
        if not req.prompt:
            # fail at submission, not inside run(): an admit-time prefill
            # error would throw away every already-finished result
            raise ValueError(f"request {req.uid!r}: empty prompt")
        budget = self.engine.max_seq_len - len(req.prompt)
        if budget < 1:
            raise ValueError(
                f"request {req.uid!r}: prompt of {len(req.prompt)} tokens "
                f"leaves no room to generate under max_seq_len "
                f"{self.engine.max_seq_len}")
        self._pending.append(req)

    @property
    def busy(self) -> bool:
        return bool(self._pending) or any(s is not None for s in self._slots)

    def run(self, requests=None) -> dict:
        """Submit ``requests`` (optional) and step until every submitted
        request has finished. Returns {uid: GenerationResult}."""
        for r in requests or ():
            self.submit(r)
        while self.busy:
            self.step()
        out, self._results = self._results, {}
        return out

    # ---- one scheduler round ----------------------------------------------

    def _split(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _finish(self, i: int, reason: str) -> None:
        s = self._slots[i]
        self._results[s.req.uid] = GenerationResult(
            s.req.uid, list(s.req.prompt), list(s.generated), reason)
        self._slots[i] = None
        self._cache = self.engine.release(self._cache, i)
        self._last_tok[i] = 0
        self._temp[i] = 0.0
        self._top_k[i] = 0
        self._top_p[i] = 1.0

    def _token_done(self, i: int, tok: int) -> None:
        """Record one generated token for slot i; retire on EOS/budget."""
        s = self._slots[i]
        s.generated.append(tok)
        r = s.req
        if r.eos_id is not None and tok == r.eos_id:
            self._finish(i, "eos")
        elif (len(s.generated) >= r.max_new_tokens
              or len(r.prompt) + len(s.generated) >= self.engine.max_seq_len):
            self._finish(i, "length")
        else:
            self._last_tok[i] = tok

    def _admit(self) -> None:
        for i in range(len(self._slots)):
            if not self._pending:
                return
            if self._slots[i] is not None:
                continue
            req = self._pending.popleft()
            kv, logits = self.engine.prefill(self.params, req.prompt)
            self._cache = self.engine.insert(
                self._cache, kv, i, len(req.prompt))
            deadline = (self._clock() + req.timeout_s
                        if req.timeout_s is not None else None)
            self._slots[i] = _Slot(req, deadline=deadline)
            self._temp[i] = req.temperature
            self._top_k[i] = req.top_k
            self._top_p[i] = req.top_p
            first = int(sampling.sample(
                logits, self._split(),
                np.float32([req.temperature]),
                np.int32([req.top_k]),
                np.float32([req.top_p]))[0])
            self._token_done(i, first)

    def _expire_deadlines(self) -> None:
        """Retire every slot past its deadline with reason "timeout" — the
        slot frees immediately, so a stuck or over-budget request cannot
        starve the queue behind it. Runs once per scheduler round, before
        the decode dispatch (an expired request gets no further tokens)."""
        now = self._clock()
        for i, s in enumerate(self._slots):
            if s is not None and s.deadline is not None and now >= s.deadline:
                self._finish(i, "timeout")

    def step(self) -> None:
        """Admit waiting requests into free slots, then advance every
        occupied slot one token."""
        self._admit()
        self._expire_deadlines()
        if not any(s is not None for s in self._slots):
            return
        self._cache, toks, _ = self.engine.decode_step(
            self.params, self._cache, self._last_tok, self._split(),
            self._temp, self._top_k, self._top_p)
        toks = np.asarray(toks)
        for i, s in enumerate(self._slots):
            if s is not None:
                self._token_done(i, int(toks[i]))
