"""Continuous batching: admit/retire variable-length requests into fixed
engine slots.

The engine's decode program has a fixed batch width (``engine.slots``), so
throughput under mixed-length traffic is a scheduling problem: a slot whose
sequence hits EOS must be recycled to a waiting request immediately, not
when the whole batch drains (static batching's tail loss). The batcher is
the host-side loop that does exactly that:

  expire: slots past their wall-clock deadline retire FIRST, so a slot
          freed by a timeout is refilled in the same round, not the next;
  admit:  while a slot is free and requests wait, prefill the next prompt
          (pow-2-bucketed one-shot at or under ``engine.prefill_chunk``,
          chunked straight into the slot above it), and sample its first
          token from the prefill logits;
  decode: ONE ``decode_block`` advances every occupied slot by up to
          ``engine.decode_block_len`` tokens — per-slot sampling params,
          EOS ids, and token budgets ride along as arrays, and the
          EOS/budget stop state lives ON DEVICE, so the host syncs once
          per block instead of once per token (``decode_block_len == 1``
          is the classic per-token loop). On a SPECULATIVE engine
          (``engine.spec_len > 0``) the decode phase is draft-verify
          instead: the drafter proposes ``spec_len`` continuation tokens
          per occupied slot from the slot's own history (host-side,
          between dispatches — free), and one ``engine.verify`` dispatch
          scores, accepts, and rewinds, emitting a VARIABLE 1..spec_len+1
          tokens per slot per dispatch;
  retire: slots that hit EOS or their token budget — decided on device,
          confirmed host-side from the block's produced counts — release
          (a 1-element length write; stale K/V rows become unreachable)
          and free capacity for the next admit. Post-EOS pad tokens in a
          block row are trimmed via the produced counts.

Free slots still flow through the decode program (fixed shapes are the
deal with XLA); they carry a zero budget at length 0 and their outputs are
ignored. The whole loop is deterministic given the seed: one PRNG key
chain, split once per admit and once per in-block step (so the chain —
and with it every sampled stream — is identical across block lengths as
long as requests finish at block boundaries, and identical to the
per-token loop at ``decode_block_len == 1``).

``decode_dispatches`` / ``prefill_dispatches`` / ``generated_tokens``
count engine calls and output tokens across the batcher's lifetime —
``decode_dispatches / generated_tokens`` is the dispatches-per-token
metric bench_decode.py tracks (1 for the per-token loop, ~1/block_len
when every slot stays busy). Speculative runs add ``draft_proposed`` /
``draft_accepted`` (``accept_rate`` = their ratio): an accept rate of r
means the average verify dispatch emitted ~1 + r*spec_len tokens.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

import jax
import numpy as np

from picotron_tpu.inference import sampling


@dataclass
class Request:
    """One generation request. ``temperature == 0`` = greedy; ``top_k <= 0``
    and ``top_p >= 1`` disable those filters. ``timeout_s`` is a wall-clock
    budget from admission: a stuck or over-budget request finishes with
    reason "timeout" and frees its slot instead of occupying it forever
    (None = no deadline)."""

    uid: str
    prompt: list
    max_new_tokens: int = 32
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: Optional[int] = None
    timeout_s: Optional[float] = None


@dataclass
class GenerationResult:
    uid: str
    prompt: list
    tokens: list  # generated ids, EOS included when hit
    finish_reason: str  # "eos" | "length" | "timeout"


@dataclass
class _Slot:
    req: Request
    generated: list = field(default_factory=list)
    deadline: Optional[float] = None  # clock() time after which we retire


class ContinuousBatcher:
    """Drive an InferenceEngine over a stream of requests.

    >>> b = ContinuousBatcher(engine, params)
    >>> b.submit(Request("a", [1, 2, 3], max_new_tokens=16))
    >>> results = b.run()           # {"a": GenerationResult(...)}

    ``params`` must already be placed on the engine mesh
    (``engine.shard_params``). One batcher owns one cache; interleaving two
    batchers on one engine is fine (separate caches), sharing a cache is
    not (the decode programs consume it).
    """

    def __init__(self, engine, params, seed: int = 0, clock=time.monotonic,
                 drafter=None):
        self.engine = engine
        self.params = params
        self._clock = clock  # injectable so deadline tests are deterministic
        self._key = jax.random.PRNGKey(seed)
        # speculative engines get a drafter (the prompt-lookup default, or
        # an injected one — e.g. a scripted drafter in tests, a draft
        # model later); spec-off engines ignore it
        if drafter is None and engine.spec_len > 0:
            from picotron_tpu.inference.speculative import NgramDrafter

            drafter = NgramDrafter(engine.spec_ngram)
        self.drafter = drafter
        self._cache = engine.init_cache()
        self._slots: list = [None] * engine.slots
        self._pending: deque = deque()
        self._results: dict = {}
        n = engine.slots
        self._last_tok = np.zeros(n, np.int32)
        self._temp = np.zeros(n, np.float32)
        self._top_k = np.zeros(n, np.int32)
        self._top_p = np.ones(n, np.float32)
        self._eos = np.full(n, -1, np.int32)
        self._budget = np.zeros(n, np.int32)
        # lifetime dispatch/throughput counters (bench + tests)
        self.decode_dispatches = 0
        self.prefill_dispatches = 0
        self.generated_tokens = 0
        self.draft_proposed = 0
        self.draft_accepted = 0

    @property
    def accept_rate(self) -> Optional[float]:
        """Fraction of proposed draft tokens that entered an emitted
        stream (None before any speculative dispatch)."""
        if not self.draft_proposed:
            return None
        return self.draft_accepted / self.draft_proposed

    # ---- queue surface ----------------------------------------------------

    def submit(self, req: Request) -> None:
        if not req.prompt:
            # fail at submission, not inside run(): an admit-time prefill
            # error would throw away every already-finished result
            raise ValueError(f"request {req.uid!r}: empty prompt")
        budget = self.engine.max_seq_len - len(req.prompt)
        if budget < 1:
            raise ValueError(
                f"request {req.uid!r}: prompt of {len(req.prompt)} tokens "
                f"leaves no room to generate under max_seq_len "
                f"{self.engine.max_seq_len}")
        self._pending.append(req)

    @property
    def busy(self) -> bool:
        return bool(self._pending) or any(s is not None for s in self._slots)

    def run(self, requests=None) -> dict:
        """Submit ``requests`` (optional) and step until every submitted
        request has finished. Returns {uid: GenerationResult}."""
        for r in requests or ():
            self.submit(r)
        while self.busy:
            self.step()
        out, self._results = self._results, {}
        return out

    # ---- one scheduler round ----------------------------------------------

    def _split(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _finish(self, i: int, reason: str) -> None:
        s = self._slots[i]
        self._results[s.req.uid] = GenerationResult(
            s.req.uid, list(s.req.prompt), list(s.generated), reason)
        self._slots[i] = None
        self._cache = self.engine.release(self._cache, i)
        self._last_tok[i] = 0
        self._temp[i] = 0.0
        self._top_k[i] = 0
        self._top_p[i] = 1.0
        self._eos[i] = -1
        self._budget[i] = 0

    def _remaining(self, i: int) -> int:
        """Tokens slot i may still produce: its max_new_tokens budget capped
        by the sequence window — the host truth the device's on-block
        budget state mirrors."""
        s = self._slots[i]
        r = s.req
        cap = min(r.max_new_tokens,
                  self.engine.max_seq_len - len(r.prompt))
        return max(cap - len(s.generated), 0)

    def _token_done(self, i: int, tok: int) -> None:
        """Record one generated token for slot i; retire on EOS/budget."""
        s = self._slots[i]
        s.generated.append(tok)
        self.generated_tokens += 1
        r = s.req
        if r.eos_id is not None and tok == r.eos_id:
            self._finish(i, "eos")
        elif (len(s.generated) >= r.max_new_tokens
              or len(r.prompt) + len(s.generated) >= self.engine.max_seq_len):
            self._finish(i, "length")
        else:
            self._last_tok[i] = tok

    def _admit(self) -> None:
        for i in range(len(self._slots)):
            if not self._pending:
                return
            if self._slots[i] is not None:
                continue
            req = self._pending.popleft()
            if len(req.prompt) > self.engine.prefill_chunk:
                # long prompt: fixed-width chunks straight into the slot —
                # O(1) compiled shapes in prompt length
                n_chunks = -(-len(req.prompt) // self.engine.prefill_chunk)
                self._cache, logits = self.engine.prefill_chunked(
                    self.params, self._cache, req.prompt, i)
                self.prefill_dispatches += n_chunks
            else:
                kv, logits = self.engine.prefill(self.params, req.prompt)
                self._cache = self.engine.insert(
                    self._cache, kv, i, len(req.prompt))
                self.prefill_dispatches += 1
            deadline = (self._clock() + req.timeout_s
                        if req.timeout_s is not None else None)
            self._slots[i] = _Slot(req, deadline=deadline)
            self._temp[i] = req.temperature
            self._top_k[i] = req.top_k
            self._top_p[i] = req.top_p
            self._eos[i] = req.eos_id if req.eos_id is not None else -1
            first = int(sampling.sample(
                logits, self._split(),
                np.float32([req.temperature]),
                np.int32([req.top_k]),
                np.float32([req.top_p]))[0])
            self._token_done(i, first)

    def _expire_deadlines(self) -> None:
        """Retire every slot past its deadline with reason "timeout" — the
        slot frees immediately, so a stuck or over-budget request cannot
        starve the queue behind it. Runs FIRST in each scheduler round
        (before admission), so a slot freed by a timeout is refilled in the
        same round instead of idling one full block."""
        now = self._clock()
        for i, s in enumerate(self._slots):
            if s is not None and s.deadline is not None and now >= s.deadline:
                self._finish(i, "timeout")

    def step(self) -> None:
        """Expire overdue slots, admit waiting requests into free slots,
        then advance every occupied slot by one decode block (up to
        ``engine.decode_block_len`` tokens per slot, one dispatch) — or,
        on a speculative engine, by one draft-verify dispatch (1 to
        ``engine.spec_len + 1`` tokens per slot)."""
        self._expire_deadlines()
        self._admit()
        if not any(s is not None for s in self._slots):
            return
        for i, s in enumerate(self._slots):
            self._budget[i] = self._remaining(i) if s is not None else 0
        if self.engine.spec_len > 0:
            toks, counts = self._spec_round()
        else:
            block = self.engine.decode_block_len
            keys = np.stack([np.asarray(self._split())
                             for _ in range(block)])
            self._cache, toks, counts = self.engine.decode_block(
                self.params, self._cache, self._last_tok, keys,
                self._eos, self._budget, self._temp, self._top_k,
                self._top_p)
            self.decode_dispatches += 1
            toks = np.asarray(toks)
            counts = np.asarray(counts)
        for i in range(len(self._slots)):
            if self._slots[i] is None:
                continue
            # the device already stopped this row at EOS/budget; walking the
            # produced prefix through _token_done applies the same rules
            # host-side (appending the tokens and retiring the slot)
            for t in toks[i, : counts[i]]:
                if self._slots[i] is None:  # device/host rule mismatch guard
                    break
                self._token_done(i, int(t))

    def _spec_round(self) -> tuple:
        """One draft-verify round: propose ``spec_len`` tokens per occupied
        slot from its own history (prompt + generated — the drafter runs
        host-side while the device is free), dispatch ONE ``engine.verify``
        pass, and return its (emitted tokens, per-slot counts). Acceptance
        stats accumulate here; the shared step() tail walks the emitted
        prefixes through ``_token_done`` exactly like a decode block's."""
        g = self.engine.spec_len
        n = len(self._slots)
        tokens = np.zeros((n, g + 1), np.int32)
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            tokens[i, 0] = self._last_tok[i]
            hist = np.asarray(list(s.req.prompt) + s.generated, np.int32)
            tokens[i, 1:] = self.drafter.propose(hist, g)
        self._cache, emitted, counts, accepted = self.engine.verify(
            self.params, self._cache, tokens, self._split(), self._eos,
            self._budget, self._temp, self._top_k, self._top_p)
        self.decode_dispatches += 1
        counts = np.asarray(counts)
        accepted = np.asarray(accepted)
        for i, s in enumerate(self._slots):
            if s is not None:
                self.draft_proposed += g
                self.draft_accepted += int(accepted[i])
        return np.asarray(emitted), counts
