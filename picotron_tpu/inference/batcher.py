"""Continuous batching: admit/retire variable-length requests into fixed
engine slots.

The engine's decode program has a fixed batch width (``engine.slots``), so
throughput under mixed-length traffic is a scheduling problem: a slot whose
sequence hits EOS must be recycled to a waiting request immediately, not
when the whole batch drains (static batching's tail loss). The batcher is
the host-side loop that does exactly that:

  expire: slots past their wall-clock deadline retire FIRST, so a slot
          freed by a timeout is refilled in the same round, not the next;
  admit:  while a slot is free and requests wait, prefill the next prompt
          (pow-2-bucketed one-shot at or under ``engine.prefill_chunk``,
          chunked straight into the slot above it), and sample its first
          token from the prefill logits;
  decode: ONE ``decode_block`` advances every occupied slot by up to
          ``engine.decode_block_len`` tokens — per-slot sampling params,
          EOS ids, and token budgets ride along as arrays, and the
          EOS/budget stop state lives ON DEVICE, so the host syncs once
          per block instead of once per token (``decode_block_len == 1``
          is the classic per-token loop). On a SPECULATIVE engine
          (``engine.spec_len > 0``) the decode phase is draft-verify
          instead: the drafter proposes ``spec_len`` continuation tokens
          per occupied slot from the slot's own history (host-side,
          between dispatches — free), and one ``engine.verify`` dispatch
          scores, accepts, and rewinds, emitting a VARIABLE 1..spec_len+1
          tokens per slot per dispatch;
  retire: slots that hit EOS or their token budget — decided on device,
          confirmed host-side from the block's produced counts — release
          (a 1-element length write; stale K/V rows become unreachable)
          and free capacity for the next admit. Post-EOS pad tokens in a
          block row are trimmed via the produced counts.

Free slots still flow through the decode program (fixed shapes are the
deal with XLA); they carry a zero budget at length 0 and their outputs are
ignored. The whole loop is deterministic given the seed: one PRNG key
chain, split once per admit and once per in-block step (so the chain —
and with it every sampled stream — is identical across block lengths as
long as requests finish at block boundaries, and identical to the
per-token loop at ``decode_block_len == 1``).

``decode_dispatches`` / ``prefill_dispatches`` / ``generated_tokens``
count engine calls and output tokens across the batcher's lifetime —
``decode_dispatches / generated_tokens`` is the dispatches-per-token
metric bench_decode.py tracks (1 for the per-token loop, ~1/block_len
when every slot stays busy). Speculative runs add ``draft_proposed`` /
``draft_accepted`` (``accept_rate`` = their ratio): an accept rate of r
means the average verify dispatch emitted ~1 + r*spec_len tokens.

**Fault handling** (docs/SERVING.md): every jitted dispatch runs under
``resilience.retry`` with bounded backoff (``resilience.dispatch_attempts``
/ ``dispatch_backoff``). A prefill that still fails costs only the request
being admitted (finish_reason ``"error"``); a decode/verify dispatch that
still fails triggers SLOT ISOLATION — the same round is re-dispatched once
per occupied slot with everyone else's budget masked to 0, so only the
slots that fail alone finish ``"error"`` while the survivors' tokens are
bit-identical to a fault-free round (same shapes, same keys: row b's draw
depends only on row b's logits and the shared key). A failure that
consumed the donated cache (buffers deleted mid-execution) cannot be
isolated: every occupied slot fails ``"error"`` and the cache is rebuilt,
so the PROCESS keeps serving either way — an exception in one dispatch is
never a server death. ``finish()`` accounting is tracked in ``counters``
(admitted/completed/expired/errored/shed) with queue-wait and
time-to-first-token samples surfaced by ``stats()`` — the ``/statz``
payload of tools/serve.py.

**Telemetry** (picotron_tpu/obs, docs/OBSERVABILITY.md): the batcher
records into the ENGINE's metrics registry — ``counters`` is a
``CounterDict`` view over ``picotron_requests_total{state}``, the
queue-wait/TTFT percentile windows live in registry histograms (the same
instruments ``GET /metrics`` renders, so ``/statz`` and Prometheus can
never disagree), and every dispatch's wall/host-sync time lands in
``picotron_dispatch_seconds{kind}``. Spans make one request traceable
end-to-end: a ``request`` root opens at submit; ``queue_wait``,
``prefill`` (radix-hit/dispatch counts), one ``decode``/``verify`` child
per dispatch round (draft len, accepted, host-sync time), and the serve
front end's ``delivery`` all parent to it — ``GET /tracez`` or
``tools/trace_dump.py`` shows the chain. ``obs.enabled: false`` swaps
all of it for no-ops.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from picotron_tpu.inference import sampling
from picotron_tpu.resilience.retry import retry
from picotron_tpu.utils import log0


def _sid(span) -> Optional[int]:
    """A span's exportable id (None for no span / the null span's 0)."""
    return span.span_id or None if span is not None else None


def _log_dispatch_failure(kind: str, ident, e: BaseException) -> None:
    log0(f"serving: {kind} dispatch failed for {ident} "
         f"({type(e).__name__}: {e})", flush=True)


@dataclass
class Request:
    """One generation request. ``temperature == 0`` = greedy; ``top_k <= 0``
    and ``top_p >= 1`` disable those filters. ``timeout_s`` is a wall-clock
    budget from admission: a stuck or over-budget request finishes with
    reason "timeout" and frees its slot instead of occupying it forever
    (None = no deadline)."""

    uid: str
    prompt: list
    max_new_tokens: int = 32
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: Optional[int] = None
    timeout_s: Optional[float] = None
    # disaggregated handoff (inference/page_transport.py, paged engines
    # only): a transport payload whose pages are imported at admission.
    # With a ``first_token`` covering the FULL prompt, the slot seats
    # ready to decode — zero prefill dispatches; otherwise the payload is
    # a prefix HINT (imported into the radix cache, the normal admission
    # radix-hits it and prefills only the uncovered suffix).
    kv_import: Optional[dict] = None
    # ---- multi-tenant serving (inference/tenancy.py) ----------------------
    # tenant name ("" = anonymous base traffic: the null adapter, the
    # default radix domain, class-1 priority, no SLOs). The serve front
    # end resolves names against its TenantRegistry and fills the fields
    # below; direct batcher users (bench, tests) set them explicitly.
    tenant: str = ""
    # admission class: higher classes admit first out of the queue; the
    # LOWEST queued class sheds first when the front end's budget gate
    # needs room for a higher-class arrival (shed_lower_priority)
    priority: int = 1
    # resolved adapter pack slot (0 = the reserved null adapter)
    adapter_slot: int = 0
    # SLO targets in milliseconds (None = best-effort): ttft steers
    # admission order and chunked-prefill interleaving; tpot feeds the
    # spec controller's dispatch-width cap and, with ttft, the
    # per-tenant attainment metrics
    ttft_slo_ms: Optional[float] = None
    tpot_slo_ms: Optional[float] = None


@dataclass
class GenerationResult:
    uid: str
    prompt: list
    tokens: list  # generated ids, EOS included when hit
    # "eos" | "length" | "timeout" | "shed" (dropped unstarted at drain) |
    # "error" (dispatch failure isolated to this request)
    finish_reason: str
    queue_wait_s: Optional[float] = None  # submit -> admit (None: never admitted)
    ttft_s: Optional[float] = None  # submit -> first token
    # the request's root span in the process trace ring (None with obs
    # off): late children — the serve front end's delivery span — parent
    # onto it after the batcher has already retired the slot
    span_id: Optional[int] = None
    # decode/verify rounds this request's slot took part in — this
    # request's own dispatches-per-token is dispatches / len(tokens),
    # the per-slot convergence metric the spec controller is judged on
    dispatches: int = 0
    # speculative engines: the slot's spec_len and drafter kind at
    # retirement (the controller's converged choice; the static config
    # values without a controller)
    spec_len_final: Optional[int] = None
    drafter: Optional[str] = None


@dataclass
class _Slot:
    req: Request
    generated: list = field(default_factory=list)
    deadline: Optional[float] = None  # clock() time after which we retire
    submit_t: Optional[float] = None  # clock() at submit (stats)
    queue_wait_s: Optional[float] = None
    ttft_s: Optional[float] = None
    dispatches: int = 0  # rounds this slot was active in
    # mixed_dispatch: the slot is being prefilled chunk-by-chunk through
    # its shard's fused lane — it rides every decode/verify dispatch
    # INACTIVE (budget 0) until the final chunk lands its first token
    prefilling: bool = False


class ContinuousBatcher:
    """Drive an InferenceEngine over a stream of requests.

    >>> b = ContinuousBatcher(engine, params)
    >>> b.submit(Request("a", [1, 2, 3], max_new_tokens=16))
    >>> results = b.run()           # {"a": GenerationResult(...)}

    ``params`` must already be placed on the engine mesh
    (``engine.shard_params``). One batcher owns one cache; interleaving two
    batchers on one engine is fine (separate caches), sharing a cache is
    not (the decode programs consume it).
    """

    def __init__(self, engine, params, seed: int = 0, clock=time.monotonic,
                 drafter=None, on_token: Optional[Callable] = None,
                 obs=None):
        self.engine = engine
        self.params = params
        self._clock = clock  # injectable so deadline tests are deterministic
        # telemetry rides on the engine's bundle unless injected: one
        # registry (and the process span ring) covers engine + batcher +
        # front end, so /metrics is a single coherent page
        self.obs = obs if obs is not None else engine.obs
        self._key = jax.random.PRNGKey(seed)
        # streaming hook: called as on_token(uid, token) for every token a
        # request emits, from inside step()/run() — the serve front end
        # pushes these straight into the response stream
        self.on_token = on_token
        # speculative engines get a drafter (selected by
        # inference.drafter — the prompt-lookup n-gram default or the
        # EAGLE-style learned head — or injected, e.g. a scripted drafter
        # in tests); spec-off engines ignore it
        inf = engine.cfg.inference
        if drafter is None and engine.spec_len > 0:
            from picotron_tpu.inference.speculative import (
                LearnedDrafter,
                NgramDrafter,
            )

            if engine.drafter_kind == "learned":
                drafter = LearnedDrafter(engine, params)
            else:
                drafter = NgramDrafter(engine.spec_ngram,
                                       window=inf.spec_history_window)
        self.drafter = drafter
        # the drafter pool the controller switches between, primary
        # first: a learned primary always carries the free n-gram
        # fallback; an injected custom drafter runs alone
        self._drafters: dict = {}
        if engine.spec_len > 0 and drafter is not None:
            self._drafters[drafter.kind] = drafter
            if drafter.kind == "learned":
                from picotron_tpu.inference.speculative import NgramDrafter

                self._drafters["ngram"] = NgramDrafter(
                    engine.spec_ngram, window=inf.spec_history_window)
        # the closed-loop spec_len policy (inference.spec_controller):
        # per-slot draft lengths + drafter choice, fed by the registry's
        # live accept counters and dispatch-latency histograms
        self.controller = None
        if engine.spec_len > 0 and inf.spec_controller.enabled:
            from picotron_tpu.inference.speculative import SpecController

            self.controller = SpecController(
                inf.spec_controller, self.obs.registry,
                slots=engine.slots, max_spec_len=engine.spec_len,
                block_len=engine.decode_block_len,
                kinds=tuple(self._drafters))
        # the learned drafter's input: each slot's last hidden state,
        # kept ON DEVICE between dispatches (engine.return_hidden)
        self._hidden = None
        if engine.return_hidden:
            self._hidden = jnp.zeros(
                (engine.slots, engine.cfg.model.hidden_size),
                jnp.dtype(engine.cfg.model.dtype))
        self._cache = engine.init_cache()
        self._slots: list = [None] * engine.slots
        self._pending: deque = deque()
        self._results: dict = {}
        n = engine.slots
        self._last_tok = np.zeros(n, np.int32)
        self._temp = np.zeros(n, np.float32)
        self._top_k = np.zeros(n, np.int32)
        self._top_p = np.ones(n, np.float32)
        self._eos = np.full(n, -1, np.int32)
        self._budget = np.zeros(n, np.int32)
        # per-slot adapter pack slots (multi-tenant engines): every
        # decode/verify dispatch ships this [slots] row so one dispatch
        # mixes tenants; 0 (the null adapter) for free/base slots
        self._adapter = np.zeros(n, np.int32)
        # lifetime dispatch/throughput counters (bench + tests)
        self.decode_dispatches = 0
        self.prefill_dispatches = 0
        self.generated_tokens = 0
        self.draft_proposed = 0
        self.draft_accepted = 0
        # dp rebalance accounting (the planner in _rebalance): completed
        # cross-shard slot migrations and the raw page bytes they moved
        self.rebalance_count = 0
        self.rebalance_bytes = 0
        self._rebalance_cooloff = 0  # rounds to sit out after a migration
        # request accounting: every submitted request lands in exactly one
        # terminal counter (completed = eos|length, expired = timeout,
        # errored = dispatch failure, shed = dropped unstarted) — the
        # serve-chaos acceptance sums these against submissions. A
        # CounterDict: plain-dict reads/compares, writes mirrored into
        # the registry as picotron_requests_total{state}.
        reg = self.obs.registry
        self.counters = reg.counter_dict(
            "picotron_requests_total",
            ("admitted", "completed", "expired", "errored", "shed"),
            help="request accounting by terminal state (+ admitted)")
        self._submit_t: dict = {}  # uid -> clock() at submit
        # latency windows (the /statz percentile payloads AND the
        # /metrics histograms — one instrument, two renderings)
        self._queue_wait_hist = reg.histogram(
            "picotron_queue_wait_seconds", "submit -> admit")
        self._ttft_hist = reg.histogram(
            "picotron_ttft_seconds", "submit -> first token")
        self._tokens_total = reg.counter(
            "picotron_generated_tokens_total", "tokens emitted to streams")
        self._draft_proposed_total = reg.counter(
            "picotron_draft_proposed_total",
            "draft tokens proposed (speculative engines)")
        self._draft_accepted_total = reg.counter(
            "picotron_draft_accepted_total",
            "draft tokens accepted into emitted streams")
        # disaggregation: payload imports that carried a usable remote
        # prefix, and admissions seated directly from a handoff (zero
        # prefill dispatches) — the cross-replica acceptance counters
        self._remote_hits_total = reg.counter(
            "picotron_prefix_remote_hits_total",
            "transport imports that landed a remote-prefilled prefix")
        # pre-register both migration outcomes so /metrics carries the
        # family (at 0) from the first scrape, not from the first move
        for outcome in ("ok", "aborted"):
            reg.counter("picotron_slot_migrations_total",
                        "cross-shard slot migrations by outcome",
                        outcome=outcome)
        self.handoff_seated = 0
        # per-tenant accounting (multi-tenant serving): a host-side tally
        # for /statz next to the labeled picotron_tenant_* registry
        # families — one instrument set, two renderings, like the global
        # counters above
        self._tenant_stats: dict = {}
        # prefill tokens admitted THIS scheduler round (the SLO-aware
        # chunked-prefill interleaving budget — see _prefill_gate)
        self._round_prefill_tokens = 0
        self._req_spans: dict = {}  # uid -> live request root span
        self._last_prefill: dict = {}  # scratch: dispatch/radix-hit counts
        self._host_sync_s = 0.0  # scratch: last dispatch's host-sync time
        self._retry = dict(
            attempts=engine.cfg.resilience.dispatch_attempts,
            backoff=engine.cfg.resilience.dispatch_backoff,
            desc="serving dispatch")
        # ---- overlapped (zero-bubble) scheduling state --------------------
        # inference.overlap: issue dispatch N+1 BEFORE syncing dispatch N
        # (_step_overlap). The engine resolved the knobs at construction;
        # the batcher mirrors them so every branch below is one attribute
        # read, and flips the engine to deferred page-table advance: under
        # overlap the paged host_len bookkeeping lands at SYNC time (after
        # the late-stop mask) via engine.apply_advance, never inside the
        # dispatch wrapper.
        self._overlap = bool(getattr(engine, "overlap", False))
        self._sched = getattr(engine, "key_schedule", "round")
        if self._overlap:
            engine.defer_advance = True
        # per-slot PRNG bases (key_schedule == "slot"): the token at
        # 0-based sequence index p is keyed fold_in(base, p - 1) no matter
        # how positions are grouped into rounds — the round-count-
        # independent schedule the overlap bit-identity gate rests on
        # (docs/INFERENCE.md "Overlapped scheduling"). One _split() per
        # admit seeds the base: the same chain link the round schedule
        # spends on its admit key, so admission order fixes the streams.
        self._base_keys = np.zeros((n, 2), np.uint32)
        # occupancy epoch per slot: bumped at finish/admit/migrate. The
        # in-flight round snapshots it at issue; sync drops any row whose
        # epoch moved (late stop, re-seat) — the exactly-once guarantee.
        self._epoch = np.zeros(n, np.int64)
        self._inflight = None   # issued-not-yet-synced round record
        self._dev_last = None   # device-resident [slots] last-token row
        self._round_seq = 0     # issued rounds (span labels)
        # scheduling-gap instrumentation (BOTH modes): host time between
        # one round's sync end and the next issue — what overlap exists
        # to hide. 0.0 whenever a round is still in flight at issue.
        self._t_last_sync_end = None
        self._step_sync_wait = 0.0    # per-step blocked-on-device time
        self._ov_device_s = 0.0       # summed issue -> sync-end windows
        self._ov_t0 = None            # first issue (efficiency wall start)
        self._ov_t1 = None            # last sync end (efficiency wall end)
        self._synthetic_sync_s = 0.0  # bench knob: padded device window
        self._gap_hist = reg.histogram(
            "picotron_dispatch_gap_seconds",
            "issue-to-issue scheduling gap net of device time")
        self._host_work_hist = reg.histogram(
            "picotron_host_work_seconds",
            "per-round host scheduling work (step wall minus sync wait)")
        # ---- mixed prefill–decode dispatch (inference.mixed_dispatch) -----
        # one prefill LANE per dp shard rides every decode/verify
        # dispatch (engine._lane_chunk): a long-prompt admission is
        # seated immediately (prefilling=True, budget 0) and its prompt
        # is fed through the lane one fixed-width chunk per round — no
        # solo prefill dispatch ever stalls the decoders behind it. Each
        # lane record tracks one such admission: its slot/epoch/request,
        # the full prompt ids, the radix-cached prefix it resumed past,
        # done_end (rows CONFIRMED landed), fed_end (rows fed — one
        # chunk ahead of done_end while a round is in flight under
        # overlap), the admit-time fold key the final chunk's
        # first-token draw consumes, and the open prefill span.
        self._mixed = bool(getattr(engine, "mixed", False))
        self._lanes: list = [None] * engine.dp_size
        self._lane_scratch = None  # last dispatch's (lane_out, lane_hid)
        # leaf lock for the scratch fields a stats() scrape may read from
        # another thread while the dispatch loop mutates them
        # (_host_sync_s, _last_prefill). Strictly a leaf: no other lock
        # and no blocking call is ever taken inside it (picolint
        # PICO-C002/C003 pin this in tests/test_analysis.py).
        self._scratch_mu = threading.Lock()

    @property
    def accept_rate(self) -> Optional[float]:
        """Fraction of proposed draft tokens that entered an emitted
        stream (None before any speculative dispatch)."""
        if not self.draft_proposed:
            return None
        return self.draft_accepted / self.draft_proposed

    # ---- queue surface ----------------------------------------------------

    def submit(self, req: Request) -> None:
        if not req.prompt:
            # fail at submission, not inside run(): an admit-time prefill
            # error would throw away every already-finished result
            raise ValueError(f"request {req.uid!r}: empty prompt")
        if req.max_new_tokens < 1:
            # a zero-budget request would occupy a slot forever: _remaining()
            # is 0 from admission on, so _token_done() never fires to retire it
            raise ValueError(
                f"request {req.uid!r}: max_new_tokens must be >= 1 "
                f"(got {req.max_new_tokens})")
        if (req.uid in self._submit_t or req.uid in self._results
                or any(s is not None and s.req.uid == req.uid
                       for s in self._slots)):
            # a duplicate would silently overwrite the first request's
            # result (and its queue-wait clock) — fail at submission like
            # the other contract violations above
            raise ValueError(
                f"request {req.uid!r}: duplicate uid (queued, in flight, "
                f"or finished with an untaken result)")
        budget = self.engine.max_seq_len - len(req.prompt)
        if budget < 1:
            raise ValueError(
                f"request {req.uid!r}: prompt of {len(req.prompt)} tokens "
                f"leaves no room to generate under max_seq_len "
                f"{self.engine.max_seq_len}")
        self._submit_t[req.uid] = self._clock()
        # the request's root span: every later stage (queue wait, prefill,
        # per-dispatch decode/verify, the front end's delivery) parents to
        # it, so one request reads as one tree in a trace dump
        self._req_spans[req.uid] = self.obs.tracer.begin(
            "request", uid=req.uid, prompt_tokens=len(req.prompt),
            max_new_tokens=req.max_new_tokens)
        self._pending.append(req)

    @property
    def busy(self) -> bool:
        return (bool(self._pending)
                or any(s is not None for s in self._slots)
                or self._inflight is not None)

    @property
    def queue_depth(self) -> int:
        """Requests waiting for a slot (the bounded-queue admission gate)."""
        return len(self._pending)

    def commitment(self, req) -> int:
        """Worst-case tokens ``req`` can actually occupy: prompt plus its
        generation budget capped by the sequence window (``_remaining()``
        enforces the same cap at decode time), so a huge ``max_new_tokens``
        counts what it can consume, not what it asked for. The admission
        gate (serve.py) prices requests with this BEFORE submit-time
        validation, hence the clamp for over-window prompts."""
        return len(req.prompt) + max(0, min(
            req.max_new_tokens, self.engine.max_seq_len - len(req.prompt)))

    def token_load(self) -> int:
        """Worst-case token commitment of every queued and in-flight
        request — the token-budget admission-control metric: what the
        cache/compute would owe if every live request ran to its cap."""
        load = sum(self.commitment(r) for r in self._pending)
        for s in self._slots:
            if s is not None:
                load += self.commitment(s.req)
        return load

    @property
    def paged(self):
        """The engine's host page allocator (None on the contiguous
        layout) — the admission gate prices in pages against it."""
        return self.engine.paged

    def page_commitment(self, req) -> int:
        """Worst-case POOL PAGES ``req`` can occupy — the paged layout's
        admission price: ``ceil(commitment / page_len)``, not a
        contiguous ``max_seq_len`` strip. Prefix hits only make the
        actual footprint smaller (shared pages are counted once, in the
        holder that wrote them). The price covers the dispatch overshoot
        rows too — a stopped slot's ghost rewrite (+1) or the verify's
        optimistic ``spec_len`` draft rows past the cap — clamped at the
        per-slot window, so a priced admission can never starve
        decode-time allocation."""
        overshoot = (self.engine.spec_len if self.engine.spec_len > 0
                     else 1)
        return min(self.paged.pages_for(self.commitment(req) + overshoot),
                   self.paged.max_pages)

    def page_load(self) -> int:
        """Worst-case page commitment of every queued and in-flight
        request (the serve front end's 429 gate on the paged layout)."""
        load = sum(self.page_commitment(r) for r in self._pending)
        for s in self._slots:
            if s is not None:
                load += self.page_commitment(s.req)
        return load

    # ---- multi-tenant accounting ------------------------------------------

    @staticmethod
    def _tname(req: Request) -> str:
        """The request's tenant label ("" renders as "base" — anonymous
        traffic is itself a tenant in the metric families)."""
        return req.tenant or "base"

    def _tstat(self, req: Request) -> dict:
        name = self._tname(req)
        st = self._tenant_stats.get(name)
        if st is None:
            st = {"admitted": 0, "completed": 0, "expired": 0,
                  "errored": 0, "shed": 0, "tokens": 0,
                  "slo_ttft_met": 0, "slo_ttft_missed": 0,
                  "slo_tpot_met": 0, "slo_tpot_missed": 0,
                  "prefill_deferred": 0, "prefill_preempts": 0}
            self._tenant_stats[name] = st
        return st

    def _tenant_count(self, req: Request, state: str) -> None:
        self._tstat(req)[state] += 1
        self.obs.registry.counter(
            "picotron_tenant_requests_total",
            "request accounting by tenant and terminal state (+ admitted)",
            tenant=self._tname(req), state=state).inc()

    def _tenant_slo(self, req: Request, slo: str, met: bool) -> None:
        outcome = "met" if met else "missed"
        self._tstat(req)[f"slo_{slo}_{outcome}"] += 1
        self.obs.registry.counter(
            "picotron_tenant_slo_total",
            "per-tenant SLO attainment by target and outcome",
            tenant=self._tname(req), slo=slo, outcome=outcome).inc()

    def tenant_token_load(self, tenant: str) -> int:
        """Worst-case token commitment of ONE tenant's queued and
        in-flight requests — the per-tenant quota gate's price (the same
        ladder ``token_load`` prices globally)."""
        load = sum(self.commitment(r) for r in self._pending
                   if (r.tenant or "") == tenant)
        for s in self._slots:
            if s is not None and (s.req.tenant or "") == tenant:
                load += self.commitment(s.req)
        return load

    def tenant_page_load(self, tenant: str) -> int:
        """Worst-case page commitment of one tenant's queued and
        in-flight requests (paged layout; 0 on contiguous)."""
        if self.paged is None:
            return 0
        load = sum(self.page_commitment(r) for r in self._pending
                   if (r.tenant or "") == tenant)
        for s in self._slots:
            if s is not None and (s.req.tenant or "") == tenant:
                load += self.page_commitment(s.req)
        return load

    def shed_lower_priority(self, priority: int, tokens: int = 0,
                            pages: int = 0) -> tuple:
        """Shed QUEUED requests of a class strictly below ``priority`` —
        lowest class first, newest first within a class — until the freed
        worst-case commitment covers ``tokens`` AND ``pages`` (0 = no
        demand on that budget) or no lower-class request remains. The
        serve front end's admission gate calls this before 429ing a
        higher-class arrival: the lowest class sheds first while higher
        classes hold their admission. Returns (tokens_freed,
        pages_freed)."""
        freed_t = freed_p = 0
        while freed_t < tokens or freed_p < pages:
            best = None
            for j, r in enumerate(self._pending):
                if r.priority >= priority:
                    continue
                # <= keeps the LATEST of the lowest class: the request
                # that waited least loses first
                if (best is None
                        or r.priority <= self._pending[best].priority):
                    best = j
            if best is None:
                break
            req = self._pending[best]
            freed_t += self.commitment(req)
            if self.paged is not None:
                freed_p += self.page_commitment(req)
            del self._pending[best]
            self._submit_t.pop(req.uid, None)
            self.counters["shed"] += 1
            self._results[req.uid] = self._shed_result(req)
        return freed_t, freed_p

    def take_results(self) -> dict:
        """Drain finished results accumulated since the last call:
        {uid: GenerationResult}. The serve loop calls this after each
        step(); run() uses it for its final return."""
        out, self._results = self._results, {}
        return out

    def shed_pending(self) -> None:
        """Finish every QUEUED (never admitted) request with reason
        ``"shed"`` — the graceful-drain path: in-flight slots run to
        completion, but work that never started is handed back so the
        client can retry against another replica instead of waiting on a
        server that is exiting."""
        while self._pending:
            req = self._pending.popleft()
            self._submit_t.pop(req.uid, None)
            self.counters["shed"] += 1
            self._results[req.uid] = self._shed_result(req)

    def _shed_result(self, req: Request) -> GenerationResult:
        """Terminal "shed" result + its ended root span."""
        self._tenant_count(req, "shed")
        span = self._req_spans.pop(req.uid, None)
        if span is not None:
            self.obs.tracer.end(span, finish_reason="shed")
        return GenerationResult(
            req.uid, list(req.prompt), [], "shed", span_id=_sid(span))

    def run(self, requests=None) -> dict:
        """Submit ``requests`` (optional) and step until every submitted
        request has finished. Returns {uid: GenerationResult}."""
        for r in requests or ():
            self.submit(r)
        while self.busy:
            self.step()
        return self.take_results()

    def refresh_gauges(self) -> tuple:
        """Re-read live occupancy into the registry gauges; returns
        ``(queued, active)``. Called by ``stats()`` AND by the serve
        front end's ``/metrics`` render, so a Prometheus scraper that
        never touches ``/statz`` still sees current depth/occupancy.
        Safe from any thread: a deque ``len`` and one pass over the
        fixed-size slot list, no batcher state mutated."""
        queued = len(self._pending)
        active = sum(s is not None for s in self._slots)
        reg = self.obs.registry
        reg.gauge("picotron_queue_depth",
                  "requests waiting for a slot").set(queued)
        reg.gauge("picotron_active_slots",
                  "slots holding a live request").set(active)
        # dp-sharded batching: the mesh width and each shard's occupancy
        # (host-side slot-list walk — see shard_occupancy) so the router
        # and fleet controller see ONE bigger replica, not N small ones.
        # Present at dp=1 too (shard "0"), so scrapers never branch.
        reg.gauge("picotron_dp_size",
                  "dp shards of this logical engine").set(
                      self.engine.dp_size)
        for sidx, occ in enumerate(self.shard_occupancy()):
            reg.gauge("picotron_shard_occupancy",
                      "occupied slots by dp shard",
                      shard=str(sidx)).set(occ)
        if self.paged is not None:
            # pool occupancy on /metrics, not just /statz: the router's
            # least-loaded scoring reads it straight off the scrape
            total = self.paged.pool.usable_pages
            live = self.paged.pool.live_count
            reg.gauge("picotron_kv_pages_live",
                      "KV pool pages holding live tokens").set(live)
            reg.gauge("picotron_kv_pool_utilization",
                      "live / usable KV pool pages").set(
                          live / max(total, 1))
        if self.engine.spec_len > 0:
            # speculation health on the scrape (refreshed on render like
            # the depth gauges above): the fabric's router — and any
            # Prometheus scraper — sees each replica's live accept rate
            # and effective per-slot draft length
            reg.gauge("picotron_spec_accept_rate",
                      "fraction of proposed draft tokens accepted").set(
                          self.accept_rate or 0.0)
            reg.gauge("picotron_spec_len",
                      "mean effective draft length over occupied slots"
                      ).set(self.spec_len_effective())
        # per-tenant occupancy + page commitment on the scrape — the
        # router's tenant-aware placement reads these off /metrics
        queued_by: dict = {}
        for r in self._pending:
            name = self._tname(r)
            queued_by[name] = queued_by.get(name, 0) + 1
        active_by: dict = {}
        pages_by: dict = {}
        for s in self._slots:
            if s is None:
                continue
            name = self._tname(s.req)
            active_by[name] = active_by.get(name, 0) + 1
            if self.paged is not None:
                pages_by[name] = (pages_by.get(name, 0)
                                  + self.page_commitment(s.req))
        for name in (set(self._tenant_stats) | set(queued_by)
                     | set(active_by)):
            reg.gauge("picotron_tenant_queue_depth",
                      "queued requests by tenant",
                      tenant=name).set(queued_by.get(name, 0))
            reg.gauge("picotron_tenant_active_slots",
                      "occupied slots by tenant",
                      tenant=name).set(active_by.get(name, 0))
            if self.paged is not None:
                reg.gauge("picotron_tenant_pages_committed",
                          "worst-case page commitment of live slots, "
                          "by tenant",
                          tenant=name).set(pages_by.get(name, 0))
        return queued, active

    def spec_len_effective(self) -> float:
        """Mean draft length across occupied slots: the controller's live
        per-slot choices, or the static ``engine.spec_len`` without one
        (0.0 when nothing is parked or speculation is off)."""
        occ = [i for i, s in enumerate(self._slots) if s is not None]
        if self.engine.spec_len <= 0 or not occ:
            return 0.0
        if self.controller is not None:
            return self.controller.spec_len_mean(occ)
        return float(self.engine.spec_len)

    def stats(self) -> dict:
        """Serving counters + latency percentiles (the ``/statz`` payload):
        request accounting (admitted/completed/expired/errored/shed),
        dispatch/throughput counters, live occupancy, and queue-wait /
        time-to-first-token percentiles over the retained samples."""
        queued, active = self.refresh_gauges()
        d = dict(self.counters)
        d.update(
            decode_dispatches=self.decode_dispatches,
            prefill_dispatches=self.prefill_dispatches,
            generated_tokens=self.generated_tokens,
            queued=queued,
            active_slots=active,
            slots=len(self._slots),
            queue_wait_s=self._queue_wait_hist.percentiles(),
            ttft_s=self._ttft_hist.percentiles(),
        )
        if self.draft_proposed:
            d["accept_rate"] = self.accept_rate
        if self.engine.spec_len > 0:
            d["spec_len_effective"] = self.spec_len_effective()
            if self.controller is not None:
                d["spec_controller"] = self.controller.decisions
        if self.paged is not None:
            # pool occupancy + prefix-cache effectiveness (kv_pages_*,
            # prefix_hit_rate, cow_copies, ...) ride into /statz
            d.update(self.paged.stats())
            # disaggregation: admissions seated straight from an imported
            # handoff (zero prefill dispatches) + remote prefix imports
            d["handoff_seated"] = self.handoff_seated
            d["prefix_remote_hits"] = int(self._remote_hits_total.value)
        if self._tenant_stats:
            # the /statz rendering of the picotron_tenant_* families
            d["tenants"] = {name: dict(st)
                            for name, st in self._tenant_stats.items()}
        # dp-sharded batching: one logical engine's width and balance.
        # Set AFTER paged.stats() so the batcher's slot-list occupancy
        # (the scheduler's view) wins over the allocator's host_len view.
        d["dp_size"] = self.engine.dp_size
        d["slots_total"] = len(self._slots)
        d["shard_occupancy"] = self.shard_occupancy()
        d["rebalance_count"] = self.rebalance_count
        d["rebalance_bytes"] = self.rebalance_bytes
        # scratch the dispatch/admission loop overwrites mid-round: a
        # stats() scrape from another thread (the serve /statz handler)
        # snapshots them under the same leaf lock every writer holds
        with self._scratch_mu:
            d["last_host_sync_s"] = self._host_sync_s
            d["last_prefill"] = dict(self._last_prefill)
        # the overlap A/B payload (bench_decode --overlap, obs-smoke):
        # issue-to-issue gap and per-round host work percentiles from the
        # histograms' retained samples, plus the device-busy fraction
        ov = dict(enabled=self._overlap,
                  dispatch_gap_s=self._gap_hist.percentiles(),
                  host_work_s=self._host_work_hist.percentiles())
        if self._ov_t0 is not None and self._ov_t1 is not None:
            wall = max(self._ov_t1 - self._ov_t0, 1e-9)
            ov["device_busy_s"] = self._ov_device_s
            ov["wall_s"] = wall
            ov["overlap_efficiency"] = min(1.0, self._ov_device_s / wall)
        d["overlap"] = ov
        # mixed prefill–decode dispatch: whether the fused lane family is
        # compiled in, and how many shard lanes are mid-prompt right now
        d["mixed"] = dict(
            enabled=self._mixed,
            lanes_active=sum(ln is not None for ln in self._lanes))
        return d

    # ---- one scheduler round ----------------------------------------------

    def _split(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _dev_tok(self):
        """The device-resident [slots] last-token row (overlap only): the
        next round's input tokens come from here, so issuing round N+1
        never waits on a host materialization of round N. The slot
        programs' ``next_tok`` output replaces it wholesale at issue;
        admissions and rebalance patch individual rows in lockstep with
        ``_last_tok``."""
        if self._dev_last is None:
            self._dev_last = jnp.asarray(self._last_tok)
        return self._dev_last

    _REASON_COUNTER = {"eos": "completed", "length": "completed",
                       "timeout": "expired", "error": "errored",
                       "shed": "shed"}

    def _finish(self, i: int, reason: str) -> None:
        s = self._slots[i]
        self.counters[self._REASON_COUNTER[reason]] += 1
        if reason != "shed":  # shed requests count via _shed_result
            self._tenant_count(s.req, self._REASON_COUNTER[reason])
        if (len(s.generated) > 1 and s.ttft_s is not None
                and s.submit_t is not None):
            # finish-time mean time-per-output-token: the decode half of
            # the request's latency, the per-tenant TPOT instrument
            tpot = ((self._clock() - s.submit_t - s.ttft_s)
                    / (len(s.generated) - 1))
            self.obs.registry.histogram(
                "picotron_tenant_tpot_seconds",
                "mean per-token decode latency by tenant",
                tenant=self._tname(s.req)).observe(tpot)
            if s.req.tpot_slo_ms is not None:
                self._tenant_slo(s.req, "tpot",
                                 tpot * 1000.0 <= s.req.tpot_slo_ms)
        span = self._req_spans.pop(s.req.uid, None)
        if span is not None:
            self.obs.tracer.end(span, finish_reason=reason,
                                tokens=len(s.generated))
        spec_len = drafter_kind = None
        if self.engine.spec_len > 0:
            if self.controller is not None:
                spec_len = int(self.controller.lens()[i])
                drafter_kind = self.controller.drafter_kinds()[i]
            else:
                spec_len = self.engine.spec_len
                drafter_kind = (self.drafter.kind if self.drafter is not None
                                else None)
        self._results[s.req.uid] = GenerationResult(
            s.req.uid, list(s.req.prompt), list(s.generated), reason,
            queue_wait_s=s.queue_wait_s, ttft_s=s.ttft_s,
            span_id=_sid(span), dispatches=s.dispatches,
            spec_len_final=spec_len, drafter=drafter_kind)
        for d in self._drafters.values():
            d.forget(s.req.uid)
        self._slots[i] = None
        # retire bumps the seat's epoch: any in-flight round that was
        # issued against this occupant drops the row at sync
        self._epoch[i] += 1
        if self._mixed:
            # a lane occupant retiring mid-prompt (timeout, dispatch
            # error) abandons its lane; a chunk still in flight is
            # isolated by the epoch bump above
            sh = i // self.engine.slots_per_shard
            if (self._lanes[sh] is not None
                    and self._lanes[sh]["slot"] == i):
                self._lane_drop(sh, reason)
        self._cache = self.engine.release(self._cache, i)
        self._last_tok[i] = 0
        self._temp[i] = 0.0
        self._top_k[i] = 0
        self._top_p[i] = 1.0
        self._eos[i] = -1
        self._budget[i] = 0
        self._adapter[i] = 0

    def _remaining(self, i: int) -> int:
        """Tokens slot i may still produce: its max_new_tokens budget capped
        by the sequence window — the host truth the device's on-block
        budget state mirrors."""
        s = self._slots[i]
        r = s.req
        cap = min(r.max_new_tokens,
                  self.engine.max_seq_len - len(r.prompt))
        return max(cap - len(s.generated), 0)

    def _token_done(self, i: int, tok: int) -> None:
        """Record one generated token for slot i; retire on EOS/budget."""
        s = self._slots[i]
        s.generated.append(tok)
        self.generated_tokens += 1
        self._tokens_total.inc()
        self._tstat(s.req)["tokens"] += 1
        self.obs.registry.counter(
            "picotron_tenant_tokens_total",
            "tokens emitted to streams, by tenant",
            tenant=self._tname(s.req)).inc()
        if s.ttft_s is None and s.submit_t is not None:
            s.ttft_s = self._clock() - s.submit_t
            self._ttft_hist.observe(s.ttft_s)
            self.obs.registry.histogram(
                "picotron_tenant_ttft_seconds",
                "submit -> first token, by tenant",
                tenant=self._tname(s.req)).observe(s.ttft_s)
            if s.req.ttft_slo_ms is not None:
                self._tenant_slo(s.req, "ttft",
                                 s.ttft_s * 1000.0 <= s.req.ttft_slo_ms)
        if self.on_token is not None:
            self.on_token(s.req.uid, tok)
        r = s.req
        if r.eos_id is not None and tok == r.eos_id:
            self._finish(i, "eos")
        elif (len(s.generated) >= r.max_new_tokens
              or len(r.prompt) + len(s.generated) >= self.engine.max_seq_len):
            self._finish(i, "length")
        else:
            self._last_tok[i] = tok

    def _prefill_into(self, req: Request, i: int, key=None):
        """Prefill ``req`` into slot ``i`` (one-shot or chunked) and return
        its last-token logits — or, on a ``sample_on_device`` engine
        (``key`` is then the admit-time PRNG key), the first sampled token
        [1] int32: the fused epilogue draws it inside the prefill dispatch
        from the slot's own sampling params, so the [1, V] logits never
        cross to the host. Mutates the cache/dispatch counters. On the
        paged layout the engine's prefix-sharing admission runs instead:
        the longest radix-cached prefix is shared (no dispatches) and only
        the suffix prefills."""
        sample = None
        rh = self.engine.return_hidden
        hidden = None
        if self.engine.sample_on_device:
            sample = (key, req.temperature, req.top_k, req.top_p)
        # the tenant's adapter rides the prefill dispatch as a single-row
        # id; adapter-less engines pass nothing and trace the base program
        adapter = (int(req.adapter_slot)
                   if self.engine.adapters is not None else None)
        if self.paged is not None and req.kv_import is not None:
            seated = self._try_import(req, i)
            if seated is not None:
                return seated  # ("handoff", first_token)
            # payload landed in the radix as a prefix hint; the normal
            # paged admission below radix-hits it
        if self.paged is not None:
            self.paged.priced[i] = self.page_commitment(req)
            out = self.engine.prefill_paged(
                self.params, self._cache, req.prompt, i, sample=sample,
                adapter_id=adapter, cache_salt=req.tenant)
            self._cache, logits, n, cached = out[:4]
            hidden = out[4] if rh else None
            self.prefill_dispatches += n
            with self._scratch_mu:
                self._last_prefill = {"dispatches": n,
                                      "cached_tokens": cached}
        elif len(req.prompt) > self.engine.prefill_chunk:
            # long prompt: fixed-width chunks straight into the slot —
            # O(1) compiled shapes in prompt length
            n_chunks = -(-len(req.prompt) // self.engine.prefill_chunk)
            out = self.engine.prefill_chunked(
                self.params, self._cache, req.prompt, i, sample=sample,
                adapter_id=adapter)
            self._cache, logits = out[:2]
            hidden = out[2] if rh else None
            self.prefill_dispatches += n_chunks
            with self._scratch_mu:
                self._last_prefill = {"dispatches": n_chunks}
        else:
            out = self.engine.prefill(self.params, req.prompt,
                                      sample=sample, adapter_id=adapter)
            kv, logits = out[:2]
            hidden = out[2] if rh else None
            self._cache = self.engine.insert(
                self._cache, kv, i, len(req.prompt))
            self.prefill_dispatches += 1
            with self._scratch_mu:
                self._last_prefill = {"dispatches": 1}
        if hidden is not None:
            # the prompt's last hidden state seeds the slot's drafting row
            self._hidden = self._hidden.at[i].set(jnp.asarray(hidden)[0])
        return logits

    def _try_import(self, req: Request, i: int):
        """Land ``req.kv_import``'s pages and, when the payload covers the
        FULL prompt with its first token, seat slot ``i`` ready to decode
        — the disaggregated handoff's zero-dispatch admission. Returns
        ``("handoff", first_token)`` on a seat, None when the payload is
        only a prefix hint (or pool pressure evicted part of the import
        before the slot could share it) — the caller then runs the normal
        paged admission, which radix-hits whatever survived. Idempotent
        under the dispatch retry: import skips chunks already cached and
        ``match_prefix`` releases any prior holdings first."""
        from picotron_tpu.inference.page_transport import TransportError
        from picotron_tpu.inference.paged_kv import PagePoolExhausted

        payload = req.kv_import
        self.paged.priced[i] = self.page_commitment(req)
        try:
            self._cache, info = self.engine.import_prefix(self._cache,
                                                          payload)
        except (TransportError, PagePoolExhausted) as e:
            # a payload this replica cannot land (corrupt/truncated bytes,
            # no pool room for the extra pages) must not cost the request:
            # it is perfectly servable by self-prefilling — the documented
            # degrade-to-colocated contract. The import released every
            # page it allocated, so the fallback starts clean.
            self.obs.registry.counter(
                "picotron_handoff_dropped_total",
                "kv payloads dropped as locally unusable").inc()
            log0(f"serving: kv import for {req.uid!r} dropped "
                 f"({type(e).__name__}: {e}); self-prefilling", flush=True)
            return None
        if info["pages_imported"] > 0:
            # counted on pages actually landing — a retried admission's
            # second import (everything already cached) must not inflate
            # the acceptance counter
            self._remote_hits_total.inc()
        ids = [int(t) for t in payload.get("token_ids") or []]
        first = payload.get("first_token")
        if first is None or ids != [int(t) for t in req.prompt]:
            return None
        cached = self.paged.match_prefix(i, ids, cap_last=False,
                                         salt=req.tenant)
        if cached != len(ids):
            return None
        self._cache = self.engine.seat_slot(self._cache, i, cached)
        if self._hidden is not None:
            # no prefill dispatch ran, so there is no hidden state for
            # the learned drafter's first round: zero the row rather
            # than draft from the PREVIOUS occupant's state (the first
            # verify re-seeds it; a garbage first draft is rejected by
            # verify either way — correctness never depends on this)
            self._hidden = self._hidden.at[i].set(0)
        with self._scratch_mu:
            self._last_prefill = {"dispatches": 0, "cached_tokens": cached,
                                  "imported_pages": info["pages_imported"]}
        self.handoff_seated += 1
        return ("handoff", int(first))

    def export_prefix(self, ids, first_token=None,
                      tenant: str = "") -> dict:
        """Serialize the longest radix-cached prefix of ``ids`` from this
        batcher's cache (the serve front end's /kv/export + /kv/pages
        surface — the caller serializes batcher access). ``tenant``
        scopes the lookup to that tenant's radix domain and rides in the
        payload."""
        return self.engine.export_prefix(self._cache, ids,
                                         first_token=first_token,
                                         cache_salt=tenant)

    def import_prefix(self, payload) -> dict:
        """Land a transport payload in this batcher's cache/radix (the
        /kv/import surface). Returns the import info dict."""
        self._cache, info = self.engine.import_prefix(self._cache, payload)
        if info["pages_imported"] > 0:
            # counted on pages actually landing — a retried admission's
            # second import (everything already cached) must not inflate
            # the acceptance counter
            self._remote_hits_total.inc()
        return info

    def _pick(self) -> int:
        """Index of the next admission candidate in the queue: the
        highest priority class first, FIFO within a class — except that
        a TTFT-SLO request jumps ahead of best-effort peers of its OWN
        class (its clock is already running; theirs is not)."""
        best = 0
        for j in range(1, len(self._pending)):
            r, b = self._pending[j], self._pending[best]
            if r.priority > b.priority:
                best = j
            elif (r.priority == b.priority and b.ttft_slo_ms is None
                  and r.ttft_slo_ms is not None):
                best = j
        return best

    def _prefill_gate(self, req: Request, tokens: Optional[int] = None,
                      submit_t: Optional[float] = None) -> bool:
        """SLO-aware chunked-prefill interleaving: when an ACTIVE slot
        carries a TPOT SLO, admission stops after one ``prefill_chunk``'s
        worth of prompt tokens per scheduler round — prefill work
        head-of-line blocks the decode dispatch behind it, and the round
        cap spreads that stall out so the decoders' token gaps stay near
        their target. The first admission of a round always passes
        (progress guarantee). A waiting request whose TTFT budget is
        half spent PREEMPTS the cap — its own SLO outranks the decoders'
        smoothness — with both decisions visible in the
        ``picotron_tenant_prefill_*`` counters.

        ``tokens`` prices the decision (default: the whole prompt — a
        serial admission prefills it all this round); the mixed lane
        feed prices ONE chunk, so the same gate budget becomes the lane
        feed rate. ``submit_t`` overrides the pending-queue clock lookup
        for the TTFT preempt (the lane's request left ``_submit_t`` at
        lane admission; its slot record carries the time instead)."""
        if tokens is None:
            tokens = len(req.prompt)
        if self._round_prefill_tokens == 0:
            return True
        if not any(s is not None and s.req.tpot_slo_ms is not None
                   for s in self._slots):
            return True
        if req.ttft_slo_ms is not None:
            t0 = (submit_t if submit_t is not None
                  else self._submit_t.get(req.uid))
            if (t0 is not None and (self._clock() - t0) * 1000.0
                    >= req.ttft_slo_ms / 2.0):
                self._tstat(req)["prefill_preempts"] += 1
                self.obs.registry.counter(
                    "picotron_tenant_prefill_preempts_total",
                    "TTFT-pressed admissions that preempted the "
                    "interleave cap, by tenant",
                    tenant=self._tname(req)).inc()
                return True
        if (self._round_prefill_tokens + tokens
                <= self.engine.prefill_chunk):
            return True
        self._tstat(req)["prefill_deferred"] += 1
        self.obs.registry.counter(
            "picotron_tenant_prefill_deferred_total",
            "admissions deferred a round by the TPOT interleave cap, "
            "by tenant",
            tenant=self._tname(req)).inc()
        return False

    def _lane_wants(self, req: Request, i: int) -> bool:
        """Whether ``req`` should prefill through slot ``i``'s shard lane
        instead of a blocking serial dispatch. Lane-worthy: a prompt
        longer than one chunk (the serial path would run the exact same
        chunk programs, just as solo stalls), or a paged prompt with a
        radix-cached prefix (the serial path resumes CHUNKED past it —
        again the lane's exact computation). A cold prompt at or under
        one chunk stays serial: its one-shot bucketed prefill is a
        different program family, and admitting it serially keeps the
        mixed-off bit-identity contract chunk-free paths rest on. A
        handoff payload (``kv_import``) stays serial too — its import
        path may seat the slot with zero prefill work."""
        if not self._mixed or req.kv_import is not None:
            return False
        if len(req.prompt) > self.engine.prefill_chunk:
            return True
        if self.paged is None:
            return False
        ids = [int(t) for t in req.prompt]
        if self.engine.dp_size > 1:
            return self.paged.peek_prefix(
                ids, salt=req.tenant,
                shard=i // self.engine.slots_per_shard) > 0
        return self.paged.peek_prefix(ids, salt=req.tenant) > 0

    def _admit(self) -> None:
        self._round_prefill_tokens = 0
        spb = self.engine.slots_per_shard
        order = range(len(self._slots))
        if self._mixed and self.engine.dp_size > 1:
            # feed lanes by the rebalance planner's occupancy view: free
            # slots on the least-occupied shard seat (and lane) first, so
            # the global queue drains toward the shard with headroom.
            # Request ADMISSION order is untouched (_pick per free slot),
            # so the per-admission key chain — and with it every stream —
            # is placement-independent.
            occ = self.shard_occupancy()
            order = sorted(range(len(self._slots)),
                           key=lambda x: (occ[x // spb], x))
        for i in order:
            if self._slots[i] is not None:
                continue
            skip_slot = False
            while True:
                if not self._pending:
                    return
                j = self._pick()
                req = self._pending[j]
                if self.paged is not None:
                    need = self.page_commitment(req)
                    if need > self.paged.usable_pages:
                        # can NEVER fit the pool: shed at the door
                        del self._pending[j]
                        self._submit_t.pop(req.uid, None)
                        self.counters["shed"] += 1
                        self._results[req.uid] = self._shed_result(req)
                        continue
                lane = self._lane_wants(req, i)
                if lane and self._lanes[i // spb] is not None:
                    # this shard's lane is mid-prompt: the candidate
                    # stays queued (FIFO head-of-line, like a gate
                    # deferral) — but a free slot on ANOTHER shard may
                    # still take it, so only this seat is skipped
                    skip_slot = True
                    break
                if self.paged is not None:
                    if not self.paged.can_admit(need, slot=i):
                        # transient pressure: wait — slots finishing
                        # return pages; admitting now could strand a
                        # live slot mid-decode
                        return
                if not lane and not self._prefill_gate(req):
                    return  # deferred to the next round's admission
                del self._pending[j]
                break
            if skip_slot:
                continue
            if lane:
                self._lane_start(req, i)
                continue
            submit_t = self._submit_t.pop(req.uid, None)
            root = self._req_spans.get(req.uid)
            t_admit = self._clock()
            if submit_t is not None:
                # the wait is over the moment the slot is assigned: the
                # span chain's first link, parented to the request root
                self.obs.tracer.record("queue_wait", submit_t, t_admit,
                                       parent=root)
            # the admit-time key: with the on-device epilogue it is drawn
            # BEFORE the dispatch (the program needs it as an operand);
            # host-side it is drawn after, exactly where it always was.
            # Either way it is the SAME link of the split chain — one
            # split per admit — so the two modes emit seeded-identical
            # streams (tests/test_sampling_epilogue.py pins this through
            # a full batcher run).
            fold = None
            if self._sched == "slot":
                # slot schedule: the one per-admit split seeds the slot's
                # BASE key; the first generated token sits at sequence
                # index len(prompt) and is keyed fold_in(base, index - 1)
                # like every later position (see _base_keys in __init__)
                self._base_keys[i] = np.asarray(self._split())
                fold = jax.random.fold_in(
                    jnp.asarray(self._base_keys[i]), len(req.prompt) - 1)
                key = fold if self.engine.sample_on_device else None
            else:
                key = (self._split() if self.engine.sample_on_device
                       else None)
            # every second this SOLO prefill dispatch runs is a second no
            # active decode slot advances — the interference the mixed
            # lane exists to remove. Timed whenever a decoder is parked
            # behind it (in both modes: the mixed-off baseline's stall
            # and the mixed-on residual are the A/B story).
            stall0 = (self._clock()
                      if any(s is not None and not s.prefilling
                             for s in self._slots) else None)
            try:
                pf_span = self.obs.tracer.begin(
                    "prefill", parent=root, uid=req.uid,
                    prompt_tokens=len(req.prompt))
                logits = retry(lambda: self._prefill_into(req, i, key),
                               **self._retry)
                self.obs.tracer.end(pf_span, **self._last_prefill)
            except Exception as e:  # noqa: BLE001 - isolated to this request
                # the failure costs only THIS request: it never held a slot,
                # so release frees whatever partial prefill state landed and
                # everyone already admitted keeps decoding
                self.obs.tracer.end(pf_span, error=type(e).__name__)
                self.counters["admitted"] += 1
                self.counters["errored"] += 1
                self._tenant_count(req, "admitted")
                self._tenant_count(req, "errored")
                span = self._req_spans.pop(req.uid, None)
                if span is not None:
                    self.obs.tracer.end(span, finish_reason="error")
                self._results[req.uid] = GenerationResult(
                    req.uid, list(req.prompt), [], "error",
                    span_id=_sid(span))
                _log_dispatch_failure("prefill", req.uid, e)
                if self._cache_ok():
                    # free whatever partial prefill state landed in the slot
                    self._cache = self.engine.release(self._cache, i)
                else:
                    self._cache_lost()
                continue
            finally:
                if stall0 is not None:
                    self.obs.registry.histogram(
                        "picotron_decode_stall_seconds",
                        "decode time lost to a blocking solo prefill "
                        "dispatch, by tenant",
                        tenant=self._tname(req)).observe(
                            self._clock() - stall0)
            self.counters["admitted"] += 1
            self._tenant_count(req, "admitted")
            if self._last_prefill.get("dispatches", 1) > 0:
                # prompt tokens that actually prefilled this round (a
                # handoff seat or full radix hit costs the gate nothing)
                self._round_prefill_tokens += len(req.prompt)
            now = self._clock()
            deadline = (now + req.timeout_s
                        if req.timeout_s is not None else None)
            slot = _Slot(req, deadline=deadline, submit_t=submit_t)
            if submit_t is not None:
                # measured at the original point (post-prefill), so the
                # /statz percentile semantics are unchanged; the span
                # above ends at slot assignment (the actual queue time)
                slot.queue_wait_s = now - submit_t
                self._queue_wait_hist.observe(slot.queue_wait_s)
            self._slots[i] = slot
            # new occupant: bump the seat's epoch so an in-flight round
            # issued against the PREVIOUS occupant drops this row at sync
            self._epoch[i] += 1
            self._adapter[i] = (req.adapter_slot
                                if self.engine.adapters is not None else 0)
            # fresh request: the controller restarts the slot's policy
            # and stateful drafters drop any previous occupant's index
            if self.controller is not None:
                self.controller.reset(i, tpot_slo_s=(
                    req.tpot_slo_ms / 1000.0
                    if req.tpot_slo_ms is not None else None))
            for d in self._drafters.values():
                d.begin(req.uid)
            self._temp[i] = req.temperature
            self._top_k[i] = req.top_k
            self._top_p[i] = req.top_p
            self._eos[i] = req.eos_id if req.eos_id is not None else -1
            if isinstance(logits, tuple) and logits[:1] == ("handoff",):
                # seated from an imported handoff: the prefill worker
                # already sampled the first token — nothing to draw here
                first = int(logits[1])
            elif self.engine.sample_on_device:
                # the dispatch already drew the first token (epilogue);
                # the one int crossing here is the whole logits payload
                first = int(np.asarray(logits).reshape(-1)[0])
            else:
                # slot schedule host-side: the folded per-position key
                # (categorical over the [1, V] row draws the same token
                # the device epilogue's [V] draw would — element count,
                # not shape, fixes the Gumbel draw)
                skey = fold if self._sched == "slot" else self._split()
                first = int(sampling.sample_jit(
                    logits, skey,
                    np.float32([req.temperature]),
                    np.int32([req.top_k]),
                    np.float32([req.top_p]))[0])
            if self._overlap:
                # seed the device-carried last-token row for the seat
                # (round N+1's input): an in-flight round only reads it
                # through its snapshotted operand, so this patch is safe
                self._dev_last = self._dev_tok().at[i].set(first)
            self._token_done(i, first)

    # ---- mixed prefill–decode dispatch (the fused lane) -------------------

    def _lane_start(self, req: Request, i: int) -> None:
        """Seat ``req`` in free slot ``i`` as a PREFILLING occupant and
        open its shard's lane: the prompt will flow through the fused
        dispatches one ``prefill_chunk`` at a time (``_lane_feed``), no
        solo prefill dispatch ever issued. Admission accounting (counters,
        queue-wait, epoch bump, sampling rows, controller/drafter resets)
        mirrors the serial seat; the first token — and with it TTFT and
        ``_token_done`` — arrives when the final chunk lands."""
        sh = i // self.engine.slots_per_shard
        submit_t = self._submit_t.pop(req.uid, None)
        root = self._req_spans.get(req.uid)
        t_admit = self._clock()
        if submit_t is not None:
            self.obs.tracer.record("queue_wait", submit_t, t_admit,
                                   parent=root)
        # the one per-admit split seeds the slot's base key exactly like
        # a serial admission (admission ORDER fixes the streams); the
        # final chunk's first-token draw folds at len(prompt) - 1 — the
        # same key every serial chunk's unconsumed epilogue uses
        self._base_keys[i] = np.asarray(self._split())
        fold = jax.random.fold_in(
            jnp.asarray(self._base_keys[i]), len(req.prompt) - 1)
        ids = [int(t) for t in req.prompt]
        cached = 0
        if self.paged is not None:
            self.paged.priced[i] = self.page_commitment(req)
            cached = self.paged.match_prefix(i, ids, salt=req.tenant)
            if cached > 0:
                # park the shared prefix ready to resume — the serial
                # path's radix-hit admission, minus its chunk dispatches
                self._cache = self.engine.seat_slot(self._cache, i,
                                                    cached)
        self.counters["admitted"] += 1
        self._tenant_count(req, "admitted")
        now = self._clock()
        deadline = (now + req.timeout_s
                    if req.timeout_s is not None else None)
        slot = _Slot(req, deadline=deadline, submit_t=submit_t,
                     prefilling=True)
        if submit_t is not None:
            slot.queue_wait_s = now - submit_t
            self._queue_wait_hist.observe(slot.queue_wait_s)
        self._slots[i] = slot
        self._epoch[i] += 1
        self._adapter[i] = (req.adapter_slot
                            if self.engine.adapters is not None else 0)
        if self.controller is not None:
            self.controller.reset(i, tpot_slo_s=(
                req.tpot_slo_ms / 1000.0
                if req.tpot_slo_ms is not None else None))
        for d in self._drafters.values():
            d.begin(req.uid)
        self._temp[i] = req.temperature
        self._top_k[i] = req.top_k
        self._top_p[i] = req.top_p
        self._eos[i] = req.eos_id if req.eos_id is not None else -1
        pf_span = self.obs.tracer.begin(
            "prefill", parent=root, uid=req.uid,
            prompt_tokens=len(req.prompt), lane=True)
        self._lanes[sh] = dict(
            slot=i, epoch=int(self._epoch[i]), req=req, ids=ids,
            cached=cached, done_end=cached, fed_end=cached, key=fold,
            chunks=0, span=pf_span, root=root)

    def _lane_drop(self, sh: int, reason: str) -> None:
        """Abandon shard ``sh``'s lane mid-prompt (occupant retired —
        timeout/error/cache loss): close its prefill span; the seat's
        epoch bump already isolates any chunk still in flight."""
        ln = self._lanes[sh]
        if ln is None:
            return
        self._lanes[sh] = None
        self.obs.tracer.end(ln["span"], error=reason,
                            dispatches=ln["chunks"],
                            cached_tokens=ln["cached"])

    def _lane_feed(self) -> tuple:
        """Build this round's engine lane operands from the per-shard
        lane records: one next chunk per live lane, gated by the SAME
        per-round token budget serial admissions pay (``_prefill_gate``
        with the chunk's size — the gate budget IS the lane feed rate,
        deferred chunks count ``prefill_deferred`` exactly like deferred
        admissions). Returns (lanes-or-None for ``engine.decode_block``
        / ``verify``, feed records for ``_lane_land``). Under overlap a
        lane feeds one chunk ahead of its last CONFIRMED row
        (``fed_end`` > ``done_end``): the in-flight round's chunk is
        sequenced on device by the cache donation chain, so the next
        chunk's rows are already parked when this one executes."""
        if not self._mixed:
            return None, ()
        C = self.engine.prefill_chunk
        lanes: list = [None] * self.engine.dp_size
        feeds: list = []
        for sh in range(self.engine.dp_size):
            ln = self._lanes[sh]
            if ln is None:
                continue
            i = ln["slot"]
            s = self._slots[i]
            if (s is None or s.req is not ln["req"]
                    or self._epoch[i] != ln["epoch"]):
                self._lane_drop(sh, "occupant_retired")
                continue
            ids = ln["ids"]
            s0 = ln["fed_end"]
            if s0 >= len(ids):
                continue  # final chunk in flight, waiting to land
            end = min(s0 + C, len(ids))
            if not self._prefill_gate(s.req, tokens=end - s0,
                                      submit_t=s.submit_t):
                continue  # deferred a round; gate counters already bumped
            if self.paged is not None:
                # absolute chunk start (the paged scatter has no clamp
                # hazard; a slid window would pointlessly COW a shared
                # prefix) — prefill_chunked's exact convention
                w0 = s0
            else:
                # contiguous window slide: past max_seq_len - C the
                # window backs up and re-feeds overlap tokens whose rows
                # recompute to the values already parked there
                w0 = min(s0, self.engine.max_seq_len - C)
            entry = dict(slot=i, tokens=ids[w0:end], start=w0)
            if self.engine.sample_on_device:
                entry.update(key=np.asarray(ln["key"]),
                             temperature=s.req.temperature,
                             top_k=s.req.top_k, top_p=s.req.top_p)
            if self.engine.adapters is not None:
                entry["adapter"] = int(s.req.adapter_slot)
            lanes[sh] = entry
            self._round_prefill_tokens += end - s0
            self.obs.registry.counter(
                "picotron_prefill_lane_tokens_total",
                "prompt tokens prefilled through the fused lane, "
                "by tenant",
                tenant=self._tname(s.req)).inc(end - s0)
            ln["fed_end"] = end
            feeds.append(dict(shard=sh, lane=ln, s0=s0, end=end,
                              t0=self._clock()))
        if not any(e is not None for e in lanes):
            return None, feeds
        return lanes, feeds

    def _lane_land(self, feeds) -> None:
        """Deliver one round's lane results: confirm each fed chunk
        (paged host length, ``lane`` span, dispatch accounting) and, on
        a prompt's FINAL chunk, draw/record the first token — the
        ``_token_done`` seat flip that turns the prefilling occupant
        into a decoder next round. ``_lane_scratch`` holds the round's
        (lane_out, lane_hid); a round that never delivered (all-failed
        isolation) rewinds ``fed_end`` so the chunk re-feeds — its
        rewrite is byte-identical, so a retried chunk costs nothing but
        the dispatch."""
        scratch, self._lane_scratch = self._lane_scratch, None
        if not feeds:
            return
        if scratch is None:
            for f in feeds:
                ln = f["lane"]
                if self._lanes[f["shard"]] is ln:
                    ln["fed_end"] = ln["done_end"]
            return
        lane_out, lane_hid = scratch
        for f in feeds:
            sh, ln = f["shard"], f["lane"]
            if self._lanes[sh] is not ln:
                continue  # dropped while the chunk flew
            i = ln["slot"]
            s = self._slots[i]
            if s is None or self._epoch[i] != ln["epoch"]:
                self._lane_drop(sh, "occupant_retired")
                continue
            self.prefill_dispatches += 1
            ln["chunks"] += 1
            ln["done_end"] = f["end"]
            if self.paged is not None:
                self.paged.set_len(i, f["end"])
            t1 = self._clock()
            self.obs.tracer.record(
                "lane", f["t0"], t1, parent=ln["root"],
                chunk=ln["chunks"], start=f["s0"], end=f["end"],
                slot=i)
            if f["end"] < len(ln["ids"]):
                continue  # mid-prompt: more chunks to feed
            # final chunk: the fused epilogue's draw (or logits row) is
            # this prompt's first token — the serial _prefill_into tail
            req = s.req
            if self.engine.sample_on_device:
                first = int(np.asarray(lane_out)[sh])
            else:
                row = np.asarray(lane_out)[sh]
                first = int(sampling.sample_jit(
                    row[None, :], ln["key"],
                    np.float32([req.temperature]),
                    np.int32([req.top_k]),
                    np.float32([req.top_p]))[0])
            if self._hidden is not None and lane_hid is not None:
                self._hidden = self._hidden.at[i].set(
                    jnp.asarray(lane_hid)[sh])
            if self.paged is not None:
                self.paged.register_prompt(i, ln["ids"], salt=req.tenant)
            with self._scratch_mu:
                self._last_prefill = {"dispatches": ln["chunks"],
                                      "cached_tokens": ln["cached"],
                                      "lane": True}
            self.obs.tracer.end(ln["span"], dispatches=ln["chunks"],
                                cached_tokens=ln["cached"], lane=True)
            self._lanes[sh] = None
            s.prefilling = False
            if self._overlap:
                # seed the device-carried last-token row (round N+1's
                # input) exactly like a serial admission's seat patch
                self._dev_last = self._dev_tok().at[i].set(first)
            self._token_done(i, first)

    # dp rebalance discipline (the fleet controller's hysteresis/cooloff
    # shape, applied to slot placement): act only past a real skew, then
    # sit out a few rounds so admission/retirement churn settles before
    # the next move — a planner that can never thrash
    REBALANCE_WATERMARK = 2  # min (max - min) shard occupancy skew
    REBALANCE_COOLOFF = 4    # scheduler rounds to sit out after a move

    def shard_occupancy(self) -> list:
        """Occupied-slot count per dp shard, computed HOST-SIDE from the
        slot list — never from a traced value inside the jitted dispatch
        (reading a device occupancy count there would host-sync the hot
        path: exactly picolint PICO-J001's hazard). dp=1 returns one
        entry covering every slot."""
        occ = [0] * self.engine.dp_size
        for i, s in enumerate(self._slots):
            if s is not None:
                occ[i // self.engine.slots_per_shard] += 1
        return occ

    def _rebalance(self) -> None:
        """Migrate ONE parked slot's KV pages from the most- to the
        least-occupied dp shard when the occupancy skew crosses the
        watermark — through ``engine.migrate_slot`` (the page-transport
        device path: byte-exact, refcount-correct, radix re-grafted on
        the destination shard), then move the slot's host rows and sit
        out the cooloff. An aborted migration (destination pool
        exhausted, dispatch fault) leaves the source slot serving
        untouched and still starts the cooloff — pressure that failed a
        move now will fail it next round too."""
        if (self.engine.dp_size <= 1 or self.paged is None):
            return
        if self._rebalance_cooloff > 0:
            self._rebalance_cooloff -= 1
            return
        occ = self.shard_occupancy()
        hi = max(range(len(occ)), key=lambda x: occ[x])
        lo = min(range(len(occ)), key=lambda x: occ[x])
        if occ[hi] - occ[lo] < self.REBALANCE_WATERMARK:
            return
        spb = self.engine.slots_per_shard
        # a prefilling occupant never migrates: its lane record pins the
        # slot to its shard and its host length trails the fed chunks
        src = next((i for i in range(hi * spb, (hi + 1) * spb)
                    if self._slots[i] is not None
                    and not self._slots[i].prefilling), None)
        dst = next((i for i in range(lo * spb, (lo + 1) * spb)
                    if self._slots[i] is None), None)
        if src is None or dst is None:
            return
        s = self._slots[src]
        try:
            self._cache, moved = self.engine.migrate_slot(
                self._cache, src, dst, prompt_ids=s.req.prompt,
                cache_salt=s.req.tenant)
        except Exception:  # noqa: BLE001 - planned abort, slot unharmed
            # all-or-nothing inside migrate_slot (PagePoolExhausted on a
            # full destination shard, or a dispatch fault caught before
            # the donating write): the source slot is still serving from
            # where it was; just record and back off
            self.obs.registry.counter(
                "picotron_slot_migrations_total",
                "cross-shard slot migrations by outcome",
                outcome="aborted").inc()
            self._rebalance_cooloff = self.REBALANCE_COOLOFF
            return
        # the request follows its pages: every per-slot host row moves to
        # dst and src returns to the _finish free-slot defaults
        self._slots[dst], self._slots[src] = s, None
        for arr in (self._last_tok, self._temp, self._top_k, self._top_p,
                    self._eos, self._budget, self._adapter):
            arr[dst] = arr[src]
        self._last_tok[src] = 0
        self._temp[src] = 0.0
        self._top_k[src] = 0
        self._top_p[src] = 1.0
        self._eos[src] = -1
        self._budget[src] = 0
        self._adapter[src] = 0
        # the slot-schedule base follows the request (its key stream is
        # placement-independent), and both seats change occupant — any
        # in-flight rows for either drop at sync (the overlap path drains
        # before planning a move, so this is belt and braces)
        self._base_keys[dst] = self._base_keys[src]
        self._base_keys[src] = 0
        self._epoch[src] += 1
        self._epoch[dst] += 1
        if self._dev_last is not None:
            self._dev_last = (self._dev_last.at[dst]
                              .set(self._dev_last[src]).at[src].set(0))
        if self._hidden is not None:
            self._hidden = (self._hidden.at[dst].set(self._hidden[src])
                            .at[src].set(0))
        if self.controller is not None:
            # the policy restarts on the destination (its latency stats
            # were per-placement anyway); the vacated slot goes clean
            self.controller.reset(dst, tpot_slo_s=(
                s.req.tpot_slo_ms / 1000.0
                if s.req.tpot_slo_ms is not None else None))
            self.controller.reset(src)
        self.rebalance_count += 1
        self.rebalance_bytes += moved
        self.obs.registry.counter(
            "picotron_slot_migrations_total",
            "cross-shard slot migrations by outcome",
            outcome="ok").inc()
        self._rebalance_cooloff = self.REBALANCE_COOLOFF

    def _expire_deadlines(self) -> None:
        """Retire every slot past its deadline with reason "timeout" — the
        slot frees immediately, so a stuck or over-budget request cannot
        starve the queue behind it. Runs FIRST in each scheduler round
        (before admission), so a slot freed by a timeout is refilled in the
        same round instead of idling one full block."""
        now = self._clock()
        for i, s in enumerate(self._slots):
            if s is not None and s.deadline is not None and now >= s.deadline:
                self._finish(i, "timeout")

    def _plan_spec(self):
        """Per-slot draft lengths + drafter kinds for the next round, or
        (None, None) when the controller has turned EVERY occupied slot
        off — the batcher then falls back to a blocked decode round
        (speculation out of the way entirely, not a 0-draft verify)."""
        n = len(self._slots)
        lens = np.zeros(n, np.int32)
        kinds: list = [None] * n
        occupied = [i for i, s in enumerate(self._slots) if s is not None]
        if self.controller is not None:
            clens = self.controller.lens()
            ckinds = self.controller.drafter_kinds()
            for i in occupied:
                lens[i] = clens[i]
                kinds[i] = ckinds[i]
            if occupied and not lens.any():
                return None, None
        else:
            for i in occupied:
                lens[i] = self.engine.spec_len
                kinds[i] = self.drafter.kind
        return lens, kinds

    def _merge_hidden(self, hid, counts) -> None:
        """Fold one dispatch's hidden states into the per-slot device
        rows: only slots that produced tokens this dispatch advance (a
        solo isolation re-dispatch merges exactly its own row)."""
        if self._hidden is not None and hid is not None:
            self._hidden = jnp.where(
                jnp.asarray(np.asarray(counts) > 0)[:, None],
                hid, self._hidden)

    def step(self) -> None:
        """Expire overdue slots, admit waiting requests into free slots,
        then advance every occupied slot by one decode block (up to
        ``engine.decode_block_len`` tokens per slot, one dispatch) — or,
        on a speculative engine, by one draft-verify dispatch (1 to
        ``engine.spec_len + 1`` tokens per slot; with the controller, a
        RAGGED dispatch at each slot's own draft length, or the blocked-
        decode fallback once every slot's speculation is off). A dispatch
        failure that survives the retry budget is isolated to the slots
        that fail alone (see module docstring) — step() itself never
        raises for an engine-side fault.

        With ``inference.overlap`` the round runs PIPELINED instead: see
        ``_step_overlap`` (issue round N+1, then drain round N)."""
        if self._overlap:
            self._step_overlap()
            return
        t_step0 = self._clock()
        self._step_sync_wait = 0.0
        self._expire_deadlines()
        self._rebalance()
        self._admit()
        if not any(s is not None for s in self._slots):
            return
        for i, s in enumerate(self._slots):
            # a lane occupant rides the dispatch INACTIVE until its
            # final chunk lands (budget 0 — its ghost row is overwritten
            # by the lane chunk inside the same trace)
            self._budget[i] = (self._remaining(i)
                               if s is not None and not s.prefilling
                               else 0)
        budget = self._budget.copy()
        lanes, feeds = self._lane_feed()
        self._lane_scratch = None
        t_round = self._clock()
        spec_lens = spec_kinds = None
        if self.engine.spec_len > 0:
            spec_lens, spec_kinds = self._plan_spec()
        if spec_lens is not None:
            toks, counts, failed = self._spec_round(budget, spec_lens,
                                                    spec_kinds,
                                                    lanes=lanes)
        else:
            block = self.engine.decode_block_len
            if self._sched == "slot":
                # per-slot bases: the program folds each row's position
                # in-trace, so the operand is round-count-independent
                keys = self._base_keys
            else:
                keys = np.stack([np.asarray(self._split())
                                 for _ in range(block)])

            def dispatch(b):
                t0 = self._clock()
                self._note_issue(t0)
                out = self.engine.decode_block(
                    self.params, self._cache, self._last_tok, keys,
                    self._eos, b, self._temp, self._top_k, self._top_p,
                    adapter_ids=(self._adapter if self.engine.adapters
                                 is not None else None), lanes=lanes)
                if self._mixed:
                    # strip the fused lane tail (token/logits row
                    # [+ lane hidden]) — _lane_land consumes it after
                    # the round delivers. An isolation re-dispatch
                    # re-runs the lane chunk too: same rows, same bytes,
                    # so restashing is idempotent.
                    lane_hid = None
                    if self.engine.return_hidden:
                        *out, lane_out, lane_hid = out
                    else:
                        *out, lane_out = out
                    self._lane_scratch = (lane_out, lane_hid)
                if self._sched == "slot":
                    # the slot program's extra next-token output feeds the
                    # overlap pipeline; the synchronous path ignores it
                    # (_last_tok, updated by the walk, stays authoritative)
                    if self.engine.return_hidden:
                        self._cache, toks, counts, _ntok, hid = out
                    else:
                        self._cache, toks, counts, _ntok = out
                        hid = None
                elif self.engine.return_hidden:
                    self._cache, toks, counts, hid = out
                else:
                    self._cache, toks, counts = out
                    hid = None
                self.decode_dispatches += 1
                t_sync = self._clock()
                self._synthetic_wait(t0)
                out = np.asarray(toks), np.asarray(counts), None
                self._merge_hidden(hid, out[1])
                t1 = self._clock()
                dt_sync = t1 - t_sync
                with self._scratch_mu:
                    self._host_sync_s = dt_sync
                self._step_sync_wait += dt_sync
                self._note_sync_end(t0, t1)
                self.engine.observe_dispatch("decode", t1 - t0,
                                             host_sync_s=dt_sync)
                self.obs.tracer.record(
                    "dispatch/decode", t0, t1,
                    slots=int(np.count_nonzero(np.asarray(b) > 0)),
                    host_sync_s=round(dt_sync, 6))
                return out

            toks, counts, _, failed = self._guarded_round(dispatch, budget)
            self._slot_spans("decode", t_round, budget, counts, failed)
        for i, s in enumerate(self._slots):
            if s is not None and budget[i] > 0 and i not in failed:
                s.dispatches += 1
                if self.controller is not None:
                    # policy tick AFTER this round's counters landed in
                    # the registry; idle slots advance their cooloff
                    self.controller.after_round(i)
        for i in failed:
            if self._slots[i] is not None:
                self._finish(i, "error")
        for i in range(len(self._slots)):
            if self._slots[i] is None:
                continue
            # the device already stopped this row at EOS/budget; walking the
            # produced prefix through _token_done applies the same rules
            # host-side (appending the tokens and retiring the slot)
            for t in toks[i, : counts[i]]:
                if self._slots[i] is None:  # device/host rule mismatch guard
                    break
                self._token_done(i, int(t))
        self._lane_land(feeds)
        self._host_work_hist.observe(
            max(0.0, self._clock() - t_step0 - self._step_sync_wait))

    # ---- overlapped (zero-bubble) scheduling ------------------------------

    def _note_issue(self, t0: float) -> None:
        """Record the issue-to-issue scheduling gap: host time between
        the previous round's sync end and this issue — the bubble overlap
        exists to close. While a round is still in flight at issue the
        pipeline is gapless by construction (0.0). Feeds the
        picotron_dispatch_gap_seconds histogram and /statz ``overlap``."""
        if self._ov_t0 is None:
            self._ov_t0 = t0
        if self._inflight is not None:
            gap = 0.0
        elif self._t_last_sync_end is None:
            return  # first round: nothing to gap against
        else:
            gap = max(0.0, t0 - self._t_last_sync_end)
        self._gap_hist.observe(gap)

    def _synthetic_wait(self, t_issue: float) -> None:
        """Bench knob: pad the round's device window to at least
        ``_synthetic_sync_s`` by sleeping the RESIDUAL at the sync point.
        Models hideable device time on hosts whose model is too small to
        produce any (chaos latency fires host-side at issue, so it can
        never be overlapped; this can — bench_decode's --overlap A/B and
        make overlap-smoke drive it). 0.0 (the default) is a no-op."""
        if self._synthetic_sync_s > 0.0:
            wait = t_issue + self._synthetic_sync_s - self._clock()
            if wait > 0:
                time.sleep(wait)

    def _note_sync_end(self, t_issue: float, t_end: float) -> None:
        self._t_last_sync_end = t_end
        self._ov_device_s += max(0.0, t_end - t_issue)
        self._ov_t1 = t_end

    def _step_overlap(self) -> None:
        """One PIPELINED scheduler round (``inference.overlap``): issue
        round N's dispatch before draining round N-1, so token delivery,
        finish detection, drafting, and admission all run while the
        device executes.

            expire -> rebalance -> admit -> issue N -> drain N-1

        Everything host-side sees state that is one round stale — budgets
        may overshoot (the device stops at EOS on its own and the walk
        truncates at the host rules), drafts guess from the previous
        round's tokens (sample-and-match acceptance makes the emitted
        stream independent of the guesses), and controller/admission
        decisions land one round late. A slot that finishes while a round
        is in flight bumps its seat epoch, so the drain drops its rows —
        exactly-once delivery; its KV overshoot dies with the released
        pages under the same length-pointer discipline verify overshoot
        always used. With no occupied slots the in-flight round drains
        and the pipeline empties (serve.py's shutdown loop relies on
        ``busy`` covering the in-flight record)."""
        t_step0 = self._clock()
        self._step_sync_wait = 0.0
        self._expire_deadlines()
        self._rebalance_overlap()
        self._admit()
        if not any(s is not None for s in self._slots):
            self._sync_inflight()
            return
        for i, s in enumerate(self._slots):
            # a lane occupant rides the dispatch INACTIVE until its
            # final chunk lands (budget 0 — its ghost row is overwritten
            # by the lane chunk inside the same trace)
            self._budget[i] = (self._remaining(i)
                               if s is not None and not s.prefilling
                               else 0)
        budget = self._budget.copy()
        rec = self._issue_round(budget)
        self._sync_inflight(next_t0=None if rec is None else rec["t0"])
        self._inflight = rec
        self._host_work_hist.observe(
            max(0.0, self._clock() - t_step0 - self._step_sync_wait))

    def _issue_round(self, budget):
        """Build and ISSUE one decode/verify dispatch without touching its
        results: every output stays an async future in the returned
        in-flight record (drained by ``_sync_inflight``). The input tokens
        come from the device-carried last-token row and the keys from the
        per-slot bases, so nothing here waits on the round before it. An
        issue-time failure (trace error, chaos hook) drains the pipeline
        and re-runs the SAME built inputs through the legacy guarded path
        (retry, then per-slot isolation) — returns None after delivering
        synchronously."""
        t_round = self._clock()
        lead = (None if self._inflight is None
                else self._inflight.get("lead"))
        # the lane rides the in-flight round and lands one round later
        # at sync, exactly like admissions already do: a chunk fed here
        # executes after the previous round's chunk (the cache donation
        # chain sequences them), so fed_end may lead done_end by one
        lanes, feeds = self._lane_feed()
        spec_lens = spec_kinds = None
        if self.engine.spec_len > 0:
            spec_lens, spec_kinds = self._plan_spec()
        adapter = (self._adapter if self.engine.adapters is not None
                   else None)
        if spec_lens is None:
            kind = "decode"
            nwrite = self.engine.decode_block_len

            def issue(b, toks_in):
                return self.engine.decode_block(
                    self.params, self._cache, toks_in, self._base_keys,
                    self._eos, b, self._temp, self._top_k, self._top_p,
                    adapter_ids=adapter, lead=lead, lanes=lanes)
        else:
            kind = "verify"
            nwrite = self.engine.spec_len + 1
            # drafting INSIDE the device-busy window, from one-round-stale
            # host state; column 0 is overridden by the device token row
            tokens = self._draft(spec_lens, spec_kinds)
            drafts = jnp.asarray(tokens[:, 1:])

            def issue(b, toks_in):
                dev_tokens = jnp.concatenate(
                    [toks_in[:, None].astype(jnp.int32), drafts], axis=1)
                return self.engine.verify(
                    self.params, self._cache, dev_tokens, self._base_keys,
                    self._eos, b, self._temp, self._top_k, self._top_p,
                    draft_len=spec_lens, adapter_ids=adapter, lead=lead,
                    lanes=lanes)
        t0 = self._clock()
        self._note_issue(t0)
        epochs = self._epoch.copy()
        try:
            out = issue(budget, self._dev_tok())
        except Exception as e:  # noqa: BLE001 - recovered synchronously
            _log_dispatch_failure("issue", "active slots", e)
            self._sync_inflight()
            self._round_fallback(kind, t_round, budget, spec_lens,
                                 spec_kinds, issue, feeds=feeds)
            return None
        lane_out = lane_hid = None
        if self._mixed:
            if self.engine.return_hidden:
                *out, lane_out, lane_hid = out
            else:
                *out, lane_out = out
        if spec_lens is None:
            accepted = None
            if self.engine.return_hidden:
                self._cache, toks, counts, ntok, hid = out
            else:
                self._cache, toks, counts, ntok = out
                hid = None
        elif self.engine.return_hidden:
            self._cache, toks, counts, accepted, ntok, hid = out
        else:
            self._cache, toks, counts, accepted, ntok = out
            hid = None
        self._dev_last = ntok
        self.decode_dispatches += 1
        self._round_seq += 1
        return dict(kind=kind, t_round=t_round, t0=t0,
                    budget=budget, epochs=epochs, toks=toks,
                    counts=counts, accepted=accepted, hid=hid,
                    spec_lens=spec_lens, spec_kinds=spec_kinds,
                    # lane futures + feed records: the sync stage lands
                    # them after the round's outputs materialize
                    lane=(lane_out, lane_hid), feeds=feeds,
                    # the NEXT issue's _pre_write reach: this round may
                    # advance each slot by up to lead rows before the
                    # stale host_len catches up at sync
                    lead=np.minimum(np.maximum(budget, 0), nwrite),
                    seq=self._round_seq)

    def _round_fallback(self, kind, t_round, budget, spec_lens,
                        spec_kinds, issue, feeds=()) -> None:
        """Issue-time failure recovery: the pipeline is already drained
        (host state is current again), so re-run the round's built inputs
        through ``_guarded_round`` — the legacy retry/isolation semantics,
        transient chaos faults absorbed identically — and deliver
        synchronously like a non-overlapped step. Budget rows of seats
        freed by the drain are masked (their occupants are gone; a stale
        row would generate into a released seat). ``feeds`` are the
        failed issue's lane feed records: the ``issue`` closure carries
        their chunk operands, so the re-dispatch advances the lane too
        (byte-identical rewrite under isolation) and the shared land
        stage confirms or rewinds it."""
        occ = np.array([s is not None for s in self._slots])
        budget = np.where(occ, budget, 0).astype(budget.dtype)
        g = self.engine.spec_len
        self._lane_scratch = None

        def dispatch(b):
            t0 = self._clock()
            self._note_issue(t0)
            out = issue(b, self._dev_tok())
            if self._mixed:
                lane_hid = None
                if self.engine.return_hidden:
                    *out, lane_out, lane_hid = out
                else:
                    *out, lane_out = out
                self._lane_scratch = (lane_out, lane_hid)
            if kind == "decode":
                accepted = None
                if self.engine.return_hidden:
                    self._cache, toks, counts, ntok, hid = out
                else:
                    self._cache, toks, counts, ntok = out
                    hid = None
            elif self.engine.return_hidden:
                self._cache, toks, counts, accepted, ntok, hid = out
            else:
                self._cache, toks, counts, accepted, ntok = out
                hid = None
            self._dev_last = ntok
            self.decode_dispatches += 1
            t_sync = self._clock()
            self._synthetic_wait(t0)
            outs = (np.asarray(toks), np.asarray(counts),
                    None if accepted is None else np.asarray(accepted))
            # deferred page-table advance (engine.defer_advance): lands
            # here per successful dispatch, so isolation re-dispatches
            # compose exactly like the legacy per-dispatch advance
            self.engine.apply_advance(outs[1])
            self._merge_hidden(hid, outs[1])
            t1 = self._clock()
            dt_sync = t1 - t_sync
            with self._scratch_mu:
                self._host_sync_s = dt_sync
            self._step_sync_wait += dt_sync
            self._note_sync_end(t0, t1)
            self.engine.observe_dispatch(kind, t1 - t0,
                                         host_sync_s=dt_sync)
            args = dict(slots=int(np.count_nonzero(np.asarray(b) > 0)),
                        host_sync_s=round(dt_sync, 6))
            if kind == "verify":
                args["draft_len"] = g
            self.obs.tracer.record("dispatch/" + kind, t0, t1, **args)
            return outs

        toks, counts, accepted, failed = self._guarded_round(dispatch,
                                                             budget)
        extra = None
        if kind == "verify":
            self._spec_account(spec_lens, spec_kinds, accepted, budget,
                               failed)
            extra = (lambda i: {
                "draft_len": int(spec_lens[i]),
                "accepted": (int(accepted[i])
                             if accepted is not None else 0)})
        self._slot_spans(kind, t_round, budget, counts, failed,
                         extra=extra)
        for i, s in enumerate(self._slots):
            if s is not None and budget[i] > 0 and i not in failed:
                s.dispatches += 1
                if self.controller is not None:
                    self.controller.after_round(i)
        for i in failed:
            if self._slots[i] is not None:
                self._finish(i, "error")
        for i in range(len(self._slots)):
            if self._slots[i] is None:
                continue
            for t in toks[i, : counts[i]]:
                if self._slots[i] is None:
                    break
                self._token_done(i, int(t))
        self._lane_land(feeds)

    def _sync_inflight(self, next_t0=None) -> None:
        """Drain the in-flight round: materialize its device outputs (the
        ONLY blocking sync on the overlap hot path), drop every row whose
        seat epoch moved since issue (late stop, re-seat — the
        exactly-once guarantee), apply the deferred page-table advance
        for the surviving rows, then deliver exactly like the legacy
        tail. ``next_t0`` is the just-issued round's issue time: when the
        drain ends after it, the window in between is recorded as an
        ``overlap`` span parented to this round's dispatch span (the
        chain tools/trace_dump.py validates)."""
        rec, self._inflight = self._inflight, None
        if rec is None:
            return
        kind = rec["kind"]
        t_sync = self._clock()
        try:
            toks = np.asarray(rec["toks"])
            counts = np.asarray(rec["counts"])
            accepted = (None if rec["accepted"] is None
                        else np.asarray(rec["accepted"]))
        except Exception as e:  # noqa: BLE001 - device-side round failure
            _log_dispatch_failure("sync", "in-flight round", e)
            if not self._cache_ok():
                self._cache_lost()
                return
            # outputs unrecoverable but the cache survived: the round's
            # slots retire like a failed dispatch's would. The lane's
            # chunk outputs are equally unrecoverable — rewind its feed
            # so the chunk re-runs (a byte-identical rewrite; the ghost
            # row an interim round writes past the stale length lands
            # masked, NULL-paged, or overwritten by the refeed).
            for f in rec.get("feeds") or ():
                ln = f["lane"]
                if self._lanes[f["shard"]] is ln:
                    ln["fed_end"] = ln["done_end"]
            for i in range(len(self._slots)):
                if (self._slots[i] is not None and rec["budget"][i] > 0
                        and rec["epochs"][i] == self._epoch[i]):
                    self._finish(i, "error")
            return
        self._synthetic_wait(rec["t0"])
        t1 = self._clock()
        dt_sync = t1 - t_sync
        with self._scratch_mu:
            self._host_sync_s = dt_sync
        self._step_sync_wait += dt_sync
        self._note_sync_end(rec["t0"], t1)
        live = ((rec["epochs"] == self._epoch)
                & np.array([s is not None for s in self._slots]))
        counts = np.where(live, counts, 0)
        mbud = np.where(live, rec["budget"], 0)
        self.engine.apply_advance(counts)
        self._merge_hidden(rec["hid"], counts)
        self.engine.observe_dispatch(kind, t1 - rec["t0"],
                                     host_sync_s=dt_sync)
        args = dict(round=rec["seq"],
                    slots=int(np.count_nonzero(
                        np.asarray(rec["budget"]) > 0)),
                    host_sync_s=round(dt_sync, 6))
        if kind == "verify":
            args["draft_len"] = self.engine.spec_len
        span = self.obs.tracer.record("dispatch/" + kind,
                                      rec["t0"], t1, **args)
        if next_t0 is not None and next_t0 < t1:
            # the zero-bubble witness: round seq's sync/deliver stage ran
            # while round seq+1 executed on device
            self.obs.tracer.record("overlap", next_t0, t1, parent=span,
                                   round=rec["seq"], over=rec["seq"] + 1)
        extra = None
        if kind == "verify":
            self._spec_account(rec["spec_lens"], rec["spec_kinds"],
                               accepted, mbud, ())
            extra = (lambda i: {
                "draft_len": int(rec["spec_lens"][i]),
                "accepted": (int(accepted[i])
                             if accepted is not None else 0)})
        self._slot_spans(kind, rec["t_round"], mbud, counts, (),
                         extra=extra)
        for i, s in enumerate(self._slots):
            if s is not None and mbud[i] > 0:
                s.dispatches += 1
                if self.controller is not None:
                    self.controller.after_round(i)
        for i in range(len(self._slots)):
            if self._slots[i] is None or counts[i] <= 0:
                continue
            for t in toks[i, : counts[i]]:
                if self._slots[i] is None:
                    break
                self._token_done(i, int(t))
        if rec.get("feeds"):
            # the round's lane chunk lands with its outputs: confirmed
            # host lengths, lane span, and — on the final chunk — the
            # first token, one round after it was fed (like admissions)
            self._lane_scratch = rec["lane"]
            self._lane_land(rec["feeds"])

    def _rebalance_overlap(self) -> None:
        """dp rebalance under overlap: the migration planner reads the
        allocator's HOST view (host_len, page tables), which lags the
        in-flight round — so the pipeline drains before a move is
        planned, and only when the cheap host-side skew checks say one
        would actually happen."""
        if self.engine.dp_size <= 1 or self.paged is None:
            return
        if self._rebalance_cooloff > 0:
            self._rebalance()  # just the cooloff decrement — no drain
            return
        occ = self.shard_occupancy()
        if max(occ) - min(occ) < self.REBALANCE_WATERMARK:
            return
        self._sync_inflight()
        self._rebalance()

    def _slot_spans(self, kind: str, t0: float, budget, counts,
                    failed, extra=None) -> None:
        """Mirror one dispatch round into a child span PER REQUEST (the
        shared engine dispatch serves many slots; Chrome traces have no
        multi-parent events, so each request's chain gets its own copy of
        the round window). ``extra(i) -> dict`` adds per-slot args (the
        verify round's draft/accept counts)."""
        t1 = self._clock()
        for i, s in enumerate(self._slots):
            if s is None or budget[i] <= 0:
                continue
            args = {"tokens": int(counts[i])}
            if i in failed:
                args["error"] = "dispatch_failed"
            if extra is not None:
                args.update(extra(i))
            self.obs.tracer.record(kind, t0, t1,
                                   parent=self._req_spans.get(s.req.uid),
                                   **args)

    # ---- dispatch fault recovery ------------------------------------------

    def _cache_ok(self) -> bool:
        """Whether the cache's buffers are still live (a dispatch that
        failed DURING execution consumed the donated cache; one that failed
        before — hook faults, trace/compile errors — did not)."""
        lengths = self._cache["lengths"]
        return not (hasattr(lengths, "is_deleted") and lengths.is_deleted())

    def _cache_lost(self) -> None:
        """The donated cache was consumed by a failed dispatch: every
        parked sequence's K/V is gone, so every occupied slot finishes
        ``"error"`` and a fresh cache is built — the batcher (and its
        queue) outlives the fault even when isolation is impossible."""
        self._cache = self.engine.init_cache()
        # any in-flight round consumed the same dead buffers; its record
        # and the device-carried token row die with them (the _finish
        # epoch bumps below already mask its rows, this just drops the
        # references so the drain is a no-op)
        self._inflight = None
        self._dev_last = None
        for i, s in enumerate(self._slots):
            if s is not None:
                self._finish(i, "error")
        for sh in range(len(self._lanes)):
            # any lane record the _finish sweep above did not close
            # (stale occupant) dies with the cache it was writing into
            self._lane_drop(sh, "cache_lost")

    def _guarded_round(self, dispatch, budget) -> tuple:
        """Run one decode/verify round with fault recovery.

        ``dispatch(budget) -> (toks [n, S], counts [n], aux [n] | None)``
        performs the jitted round restricted to the slots whose budget row
        is nonzero (free slots always carry 0). The happy path is ONE
        retried call. On persistent failure, the round is ISOLATED: each
        occupied slot is re-dispatched alone (everyone else's budget masked
        to 0) with the SAME keys/tokens, which reproduces the group round's
        per-row results exactly — row b's logits see only slot b's cache,
        and the samplers draw per-row from the shared key — so surviving
        slots emit bit-identical tokens to a fault-free round. Slots that
        still fail alone are returned in ``failed`` (the caller retires
        them as ``"error"``). A failure that consumed the donated cache
        ends the round via ``_cache_lost``.

        Returns (toks, counts, aux, failed_slot_indices); counts rows of
        failed/finished slots are 0, so the step() walk skips them."""
        try:
            toks, counts, aux = retry(lambda: dispatch(budget),
                                      **self._retry)
            return toks, counts, aux, []
        except Exception as e:  # noqa: BLE001 - recovery, rethrown never
            _log_dispatch_failure("round", "active slots", e)
        n = len(self._slots)
        counts_out = np.zeros(n, np.int64)
        toks_out = aux_out = None
        failed: list = []
        if not self._cache_ok():
            self._cache_lost()
            return np.zeros((n, 1), np.int32), counts_out, None, []
        for i in range(n):
            if self._slots[i] is None or budget[i] <= 0:
                continue
            solo = np.zeros_like(budget)
            solo[i] = budget[i]
            try:
                t, c, a = retry(lambda: dispatch(solo), **self._retry)
            except Exception as e:  # noqa: BLE001 - isolated to slot i
                _log_dispatch_failure("solo", f"slot {i}", e)
                if not self._cache_ok():
                    # mid-isolation cache loss: everyone still parked fails
                    self._cache_lost()
                    return (np.zeros((n, 1), np.int32),
                            np.zeros(n, np.int64), None, [])
                failed.append(i)
                continue
            if toks_out is None:
                toks_out = np.zeros_like(t)
                aux_out = None if a is None else np.zeros_like(a)
            toks_out[i] = t[i]
            counts_out[i] = c[i]
            if a is not None:
                aux_out[i] = a[i]
        if toks_out is None:  # every occupied slot failed alone
            toks_out = np.zeros((n, 1), np.int32)
        return toks_out, counts_out, aux_out, failed

    def _draft(self, lens, kinds):
        """Propose draft tokens for every occupied slot — ``_spec_round``'s
        drafting stage, shared with the overlap issue path (where it runs
        INSIDE the device-busy window, from host state that is one round
        stale; a stale guess only costs acceptance, never correctness —
        the slot verify program's sample-and-match emission is independent
        of the draft values). Returns the [slots, spec_len + 1] token
        block; column 0 is the host's last-token view (the overlap path
        overrides it with the device-carried row at dispatch)."""
        g = self.engine.spec_len
        n = len(self._slots)
        reg = self.obs.registry
        tokens = np.zeros((n, g + 1), np.int32)
        with self.obs.tracer.span("draft", spec_len=g):
            learned = [i for i, s in enumerate(self._slots)
                       if s is not None and kinds[i] == "learned"
                       and lens[i] > 0]
            batch = None
            if learned:
                ld = self._drafters["learned"]
                t0 = self._clock()
                batch = ld.propose_batch(self._last_tok, self._hidden, g)
                reg.counter("picotron_dispatch_total",
                            "engine dispatches by kind",
                            kind="draft").inc()
                self.engine.observe_dispatch("draft",
                                             self._clock() - t0)
            for i, s in enumerate(self._slots):
                if s is None:
                    continue
                tokens[i, 0] = self._last_tok[i]
                gi = int(lens[i])
                if gi == 0:
                    continue  # this slot rides the dispatch draft-free
                if kinds[i] == "learned":
                    tokens[i, 1: 1 + gi] = batch[i, :gi]
                    continue
                d = self._drafters.get(kinds[i], self.drafter)
                hist = np.asarray(list(s.req.prompt) + s.generated,
                                  np.int32)
                if getattr(d, "stateful", False):
                    tokens[i, 1: 1 + gi] = d.propose(hist, gi,
                                                     ctx=s.req.uid)
                else:
                    tokens[i, 1: 1 + gi] = d.propose(hist, gi)
        return tokens

    def _spec_account(self, lens, kinds, accepted, budget, failed) -> None:
        """Accumulate one verify round's acceptance stats: the lifetime
        totals, the per-slot and per-drafter registry counter families the
        controller and the bench read, and the controller's own record —
        shared by the synchronous round and the overlap sync stage."""
        reg = self.obs.registry
        for i, s in enumerate(self._slots):
            if s is None or i in failed or budget[i] <= 0:
                continue
            gi = int(lens[i])
            if gi == 0:
                continue
            acc = int(accepted[i]) if accepted is not None else 0
            self.draft_proposed += gi
            self._draft_proposed_total.inc(gi)
            self.draft_accepted += acc
            self._draft_accepted_total.inc(acc)
            # the labeled families the CONTROLLER reads back (telemetry
            # as a control surface) and the bench's per-drafter split
            reg.counter("picotron_slot_draft_proposed_total",
                        "draft tokens proposed, by slot",
                        slot=str(i)).inc(gi)
            reg.counter("picotron_slot_draft_accepted_total",
                        "draft tokens accepted, by slot",
                        slot=str(i)).inc(acc)
            kind = kinds[i] or "unknown"
            reg.counter("picotron_drafter_proposed_total",
                        "draft tokens proposed, by drafter",
                        drafter=kind).inc(gi)
            reg.counter("picotron_drafter_accepted_total",
                        "draft tokens accepted, by drafter",
                        drafter=kind).inc(acc)
            if self.controller is not None:
                self.controller.record(i, gi, acc)

    def _spec_round(self, budget, lens, kinds, lanes=None) -> tuple:
        """One draft-verify round: propose ``lens[i]`` tokens per occupied
        slot (per-slot RAGGED under the controller; the full
        ``engine.spec_len`` otherwise), dispatch ONE ``engine.verify``
        pass (fault-isolated like the decode round), and return its
        (emitted tokens, per-slot counts, failed slots).

        Drafting is per kind: "learned" slots draft TOGETHER in one small
        jitted dispatch from the device-resident hidden states
        (LearnedDrafter.propose_batch — timed into the "draft" latency
        histogram the controller's cost model reads); host drafters
        (n-gram, scripted) propose per slot from the slot's own history
        while the device is free. Acceptance stats accumulate here — the
        lifetime totals, the per-slot and per-drafter registry counter
        families the controller and the bench read, and the controller's
        obs-off shadow; the shared step() tail walks the emitted prefixes
        through ``_token_done`` exactly like a decode block's."""
        g = self.engine.spec_len
        t_round = self._clock()
        tokens = self._draft(lens, kinds)
        key = (self._base_keys if self._sched == "slot"
               else self._split())

        def dispatch(b):
            t0 = self._clock()
            self._note_issue(t0)
            out = self.engine.verify(
                self.params, self._cache, tokens, key, self._eos,
                b, self._temp, self._top_k, self._top_p, draft_len=lens,
                adapter_ids=(self._adapter if self.engine.adapters
                             is not None else None), lanes=lanes)
            if self._mixed:
                # strip the fused lane tail for _lane_land (idempotent
                # under isolation re-dispatch — see step()'s closure)
                lane_hid = None
                if self.engine.return_hidden:
                    *out, lane_out, lane_hid = out
                else:
                    *out, lane_out = out
                self._lane_scratch = (lane_out, lane_hid)
            if self._sched == "slot":
                # extra next-token output (overlap feed) — ignored here
                if self.engine.return_hidden:
                    (self._cache, emitted, counts, accepted, _ntok,
                     hid) = out
                else:
                    self._cache, emitted, counts, accepted, _ntok = out
                    hid = None
            elif self.engine.return_hidden:
                self._cache, emitted, counts, accepted, hid = out
            else:
                self._cache, emitted, counts, accepted = out
                hid = None
            self.decode_dispatches += 1
            t_sync = self._clock()
            self._synthetic_wait(t0)
            out = (np.asarray(emitted), np.asarray(counts),
                   np.asarray(accepted))
            self._merge_hidden(hid, out[1])
            t1 = self._clock()
            dt_sync = t1 - t_sync
            with self._scratch_mu:
                self._host_sync_s = dt_sync
            self._step_sync_wait += dt_sync
            self._note_sync_end(t0, t1)
            self.engine.observe_dispatch("verify", t1 - t0,
                                         host_sync_s=dt_sync)
            self.obs.tracer.record(
                "dispatch/verify", t0, t1,
                slots=int(np.count_nonzero(np.asarray(b) > 0)),
                draft_len=g, host_sync_s=round(dt_sync, 6))
            return out

        emitted, counts, accepted, failed = self._guarded_round(
            dispatch, budget)
        self._spec_account(lens, kinds, accepted, budget, failed)
        self._slot_spans(
            "verify", t_round, budget, counts, failed,
            extra=lambda i: {"draft_len": int(lens[i]),
                             "accepted": (int(accepted[i])
                                          if accepted is not None else 0)})
        return emitted, counts, failed
